"""The `Runtime`: TPU-native replacement for Lightning Fabric.

The reference instantiates `lightning.fabric.Fabric` from config and calls
`fabric.launch(entrypoint, cfg)` — the single process-spawn point
(/root/reference/sheeprl/cli.py:101-199).  On TPU there is nothing to spawn:
JAX is single-controller per host, every local chip is already visible, and
multi-host synchronization comes from `jax.distributed`.  `Runtime` therefore
carries:

- the device mesh (1-D ``data`` axis) and precision policy;
- PRNG seeding;
- host-side "collectives" that mirror Fabric's API surface
  (`all_gather`/`broadcast`/object broadcast) — trivial in-process when
  world_size==1 per host, `multihost_utils` when distributed;
- the callback hook mechanism (`runtime.call("on_checkpoint_coupled", ...)`)
  used by the checkpoint callback (reference utils/callback.py:14-148).

A second, strategy-free runtime for "player" models
(`get_single_device_runtime`, reference utils/fabric.py:8-35) is a
device-pinning helper here: players run on ``mesh.devices[0]`` and never touch
collectives.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.parallel.mesh import make_mesh
from sheeprl_tpu.parallel.precision import PRECISION_DTYPES as _PRECISION_TO_DTYPES
from sheeprl_tpu.parallel.precision import cast_floating


class Runtime:
    # Run-health facade (sheeprl_tpu/diagnostics): attached by the CLI before
    # launch, or lazily by utils.get_diagnostics for direct entrypoint callers.
    diagnostics = None

    def __init__(
        self,
        devices: int | str = 1,
        num_nodes: int = 1,
        strategy: str = "auto",
        accelerator: str = "auto",
        precision: str = "32-true",
        callbacks: Optional[Sequence[Any]] = None,
        fsdp: int = 1,
        fsdp_min_shard_bytes: Optional[int] = None,
    ):
        self.num_nodes = num_nodes
        self.strategy = strategy
        self.accelerator = accelerator
        self.precision = precision
        if precision not in _PRECISION_TO_DTYPES:
            raise ValueError(f"Unknown precision '{precision}'; valid: {list(_PRECISION_TO_DTYPES)}")
        self.param_dtype, self.compute_dtype = _PRECISION_TO_DTYPES[precision]
        self.callbacks = list(callbacks or [])

        # Multi-host: initialize jax.distributed only when a coordinator is set
        # (TPU pods set these in the environment). Single host: no-op.
        if num_nodes > 1 and not jax.process_count() > 1 and os.environ.get("JAX_COORDINATOR_ADDRESS"):
            jax.distributed.initialize()  # pragma: no cover - needs a pod

        if accelerator in ("auto", None):
            available = jax.devices()
        else:
            # explicit backend: "cpu" | "gpu" | "tpu" (the axon TPU tunnel
            # registers under its own platform name, so fall back to it)
            try:
                available = jax.devices(accelerator)
            except RuntimeError:
                if accelerator == "tpu":
                    available = jax.devices("axon")
                else:
                    raise
        if devices in ("auto", -1, "-1"):
            n = len(available)
        else:
            n = int(devices)
        if n > len(available):
            raise ValueError(f"Requested {n} devices but only {len(available)} are available")
        self.fsdp = int(fsdp or 1)
        self.fsdp_min_shard_bytes = None if fsdp_min_shard_bytes is None else int(fsdp_min_shard_bytes)
        if self.fsdp > 1:
            if n % self.fsdp != 0:
                raise ValueError(
                    f"fsdp axis size ({self.fsdp}) must divide the device count ({n})"
                )
            # 2-D ("data", "model") mesh: batch shards over both axes, params
            # and optimizer state shard over "model" (parallel/fsdp.py rule).
            self.mesh = make_mesh(
                n_devices=n,
                axis_names=("data", "model"),
                axis_sizes=(n // self.fsdp, self.fsdp),
            )
        else:
            self.mesh = make_mesh(n_devices=n, axis_names=("data",))
        self._launched = False

    # -- topology ---------------------------------------------------------
    @property
    def devices(self) -> List[Any]:
        return list(self.mesh.devices.reshape(-1))

    @property
    def device(self) -> Any:
        """The 'player' device (first in the mesh)."""
        return self.devices[0]

    @property
    def world_size(self) -> int:
        return len(self.devices)

    @property
    def global_rank(self) -> int:
        # single-controller: the process rank; per-device rank only matters
        # inside jitted collectives which use mesh axes instead.
        return jax.process_index()

    @property
    def is_global_zero(self) -> bool:
        return jax.process_index() == 0

    # -- launch -----------------------------------------------------------
    def launch(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        """Run the entrypoint. No process spawn: the mesh already spans all
        local devices (ICI) and, when `jax.distributed` is initialized, all
        hosts (DCN)."""
        self._launched = True
        return fn(self, *args, **kwargs)

    # -- precision --------------------------------------------------------
    def cast(self, tree: Any) -> Any:
        """Cast floating leaves to the compute dtype."""
        return cast_floating(tree, self.compute_dtype)

    # -- host collectives (Fabric API surface; executed by
    # tests/test_parallel/test_multihost.py on a 2-process CPU mesh) --------
    def all_gather(self, tree: Any) -> Any:
        """Gather across *processes* (multi-host). In-process device-sharded
        values are already globally addressable, so this is the identity on a
        single host."""
        if jax.process_count() == 1:
            return tree
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(tree)

    def broadcast(self, obj: Any, src: int = 0) -> Any:
        """Object broadcast (the reference's Gloo ``broadcast_object_list``,
        e.g. the log-dir broadcast of utils/logger.py:78-114): arbitrary
        picklable objects ride the array collective as length-prefixed bytes —
        ``broadcast_one_to_all`` itself only ships numeric array pytrees."""
        if jax.process_count() == 1:
            return obj
        import pickle

        from jax.experimental import multihost_utils

        is_src = jax.process_index() == src
        payload = pickle.dumps(obj) if is_src else b""
        n = int(
            multihost_utils.broadcast_one_to_all(np.int32(len(payload)), is_source=is_src)
        )
        buf = np.frombuffer(payload, np.uint8) if is_src else np.zeros(n, np.uint8)
        buf = np.asarray(multihost_utils.broadcast_one_to_all(buf, is_source=is_src), np.uint8)
        return pickle.loads(buf.tobytes())

    def barrier(self) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("sheeprl_tpu_barrier")

    # -- callbacks ---------------------------------------------------------
    def call(self, hook_name: str, **kwargs: Any) -> None:
        for cb in self.callbacks:
            hook = getattr(cb, hook_name, None)
            if hook is not None:
                hook(runtime=self, **kwargs)

    # -- checkpoint io ------------------------------------------------------
    def save(self, path: str, state: Dict[str, Any]) -> None:
        """Checkpoint write, routed through the resilience layer when the
        diagnostics facade carries one (async off-critical-path writer +
        manifest sidecar + ckpt_begin/ckpt_end journaling); otherwise a plain
        synchronous save that still writes the manifest, so resume-time
        verification works for every producer (eval helpers, tests, bench).

        Multi-process (``jax.distributed``) saves are *coordinated* group
        snapshots (resilience/coordination.py): barrier → broadcast-agreed
        step → one ``ckpt_<step>_<rank>.ckpt`` shard per rank with a group
        manifest, so resume selection can reject torn snapshots.  The
        single-process path below is bit-identical to the pre-coordination
        behavior.

        FSDP (``fsdp > 1``, single process): the save is *truly sharded* —
        one ``ckpt_<step>_<k>.ckpt`` partial per model-axis shard, each
        holding only the leaf slices that shard owns, with the layout
        recorded in the manifest group (resilience/sharded.py).  Bytes per
        shard scale down with the axis; the write is synchronous (partials
        must land as one verified group)."""
        if jax.process_count() > 1:
            from sheeprl_tpu.resilience.coordination import coordinated_save

            coordinated_save(self, path, state)
            return
        if self.fsdp > 1:
            from sheeprl_tpu.resilience.sharded import save_sharded_checkpoint

            save_sharded_checkpoint(
                path, state, axis_size=self.fsdp, min_shard_bytes=self.fsdp_min_shard_bytes
            )
            self.barrier()
            return
        if self.is_global_zero:
            diagnostics = self.diagnostics
            routed = diagnostics is not None and diagnostics.save_checkpoint(path, state)
            if not routed:
                from sheeprl_tpu.resilience.manifest import save_verified_checkpoint

                save_verified_checkpoint(path, state)
        self.barrier()

    def load(self, path: str) -> Dict[str, Any]:
        """Checkpoint read; a non-zero rank of a multi-process run loads its
        own shard of a coordinated group when one exists next to the
        (canonical, rank-0) resolved path, falling back to the rank-0 file —
        today's state is replicated, so the fallback is always valid.

        FSDP partial-shard groups are detected from the shard-0 manifest and
        reassembled into the full host tree (resilience/sharded.py) — the
        loaded tree is axis-size-agnostic, so resuming under a *different*
        ``fsdp_axis_size`` (or pure DP) just re-places it under the new
        rule."""
        from sheeprl_tpu.utils.checkpoint import load_state

        if jax.process_count() == 1:
            from sheeprl_tpu.resilience.sharded import is_partial_checkpoint, load_sharded_checkpoint

            if is_partial_checkpoint(path):
                return load_sharded_checkpoint(path)
        if jax.process_count() > 1 and jax.process_index() > 0:
            from sheeprl_tpu.resilience.coordination import rank_shard_path

            mine = rank_shard_path(path, jax.process_index())
            if os.path.isfile(mine):
                path = mine
        return load_state(path)

    def seed_everything(self, seed: int) -> jax.Array:
        np.random.seed(seed)
        import random

        random.seed(seed)
        return jax.random.PRNGKey(seed)


def get_single_device_runtime(runtime: Runtime) -> Runtime:
    """Strategy-free runtime sharing device/precision with `runtime`
    (reference utils/fabric.py:8-35): used to wrap player models so env
    interaction never crosses collectives."""
    single = Runtime.__new__(Runtime)
    single.num_nodes = 1
    single.strategy = "single"
    single.accelerator = runtime.accelerator
    single.precision = runtime.precision
    single.param_dtype = runtime.param_dtype
    single.compute_dtype = runtime.compute_dtype
    single.callbacks = runtime.callbacks
    single.diagnostics = runtime.diagnostics
    single.fsdp = 1
    single.fsdp_min_shard_bytes = None
    single.mesh = make_mesh(n_devices=1, devices=[runtime.device])
    single._launched = True
    return single
