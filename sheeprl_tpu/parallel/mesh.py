"""Device-mesh helpers.

The reference scales with Lightning Fabric DDP (one process per device, NCCL
all-reduce — see SURVEY §2.4).  The TPU-native design is single-controller:
one process drives all local chips through a `jax.sharding.Mesh`; gradient
reduction is whatever XLA inserts for a batch-sharded / param-replicated jit —
a `psum` riding ICI.  Multi-host extends the same mesh over DCN via
`jax.distributed.initialize` without changing any algorithm code.

Axis conventions used across the framework:
- ``data``: data-parallel axis (batch sharded, params replicated)
- ``trainer``/player sub-meshes: decoupled topology (algos/ppo/ppo_decoupled.py)
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = ("data",),
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    arr = np.asarray(devices)
    if len(axis_names) > 1:
        raise NotImplementedError("only 1-D meshes are used in this build")
    return Mesh(arr.reshape(-1), axis_names)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_along(tree: Any, mesh: Mesh, axis_name: str = "data", axis: int = 0) -> Any:
    """Shard every leaf's ``axis`` dimension over ``axis_name``."""

    def put(x):
        spec = [None] * np.ndim(x)
        spec[axis] = axis_name
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(put, tree)


def batch_sharding(mesh: Mesh, axis_name: str = "data") -> NamedSharding:
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
