"""Device-mesh helpers.

The reference scales with Lightning Fabric DDP (one process per device, NCCL
all-reduce — see SURVEY §2.4).  The TPU-native design is single-controller:
one process drives all local chips through a `jax.sharding.Mesh`; gradient
reduction is whatever XLA inserts for a batch-sharded / param-replicated jit —
a `psum` riding ICI.  Multi-host extends the same mesh over DCN via
`jax.distributed.initialize` without changing any algorithm code.

Axis conventions used across the framework:
- ``data``: data-parallel axis (batch sharded, params replicated)
- ``model``: FSDP axis (params/opt-state sharded — parallel/fsdp.py owns the
  partition rule; batch sharded over *both* axes so FSDP is still DP + ZeRO-3)
- ``trainer``/player sub-meshes: decoupled topology (algos/ppo/ppo_decoupled.py)
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = ("data",),
    devices: Optional[Sequence[Any]] = None,
    axis_sizes: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build the device mesh.

    1-D (the default): all devices on one axis.  2-D (``("data", "model")``):
    ``axis_sizes`` gives the extent of every axis — the trailing (``model``)
    axis rides ICI-adjacent devices so FSDP's all-gather/reduce-scatter stays
    on the fastest links, exactly the GSPMD mesh-major convention.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    arr = np.asarray(devices)
    if len(axis_names) == 1:
        return Mesh(arr.reshape(-1), axis_names)
    if axis_sizes is None or len(axis_sizes) != len(axis_names):
        raise ValueError(
            f"a {len(axis_names)}-D mesh needs axis_sizes of the same length, got {axis_sizes!r}"
        )
    want = int(np.prod(axis_sizes))
    if want != arr.size:
        raise ValueError(
            f"axis_sizes {tuple(axis_sizes)} needs {want} devices but the mesh has {arr.size}"
        )
    return Mesh(arr.reshape(tuple(axis_sizes)), tuple(axis_names))


def model_axis_size(mesh: Optional[Mesh]) -> int:
    """Extent of the ``model`` (FSDP) axis; 1 when the mesh is 1-D/absent."""
    if mesh is None or MODEL_AXIS not in mesh.axis_names:
        return 1
    return int(mesh.shape[MODEL_AXIS])


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_along(tree: Any, mesh: Mesh, axis_name: str = "data", axis: int = 0) -> Any:
    """Shard every leaf's ``axis`` dimension over ``axis_name``."""

    def put(x):
        spec = [None] * np.ndim(x)
        spec[axis] = axis_name
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(put, tree)


def batch_sharding(mesh: Mesh, axis_name: str = "data") -> NamedSharding:
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
