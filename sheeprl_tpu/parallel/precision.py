"""Mixed-precision policy for TPU training.

The reference delegates precision to Lightning Fabric's plugin
(``fabric.precision`` = "32-true" | "bf16-mixed" | "bf16-true" | ...,
reference sheeprl/cli.py:160-199 passes it straight to ``Fabric``).  On TPU
bf16 is the native matmul dtype (~2x MXU throughput vs fp32), so the policy
here is JMP-style and needs no module threading:

- ``bf16-mixed``: params live in fp32 (master weights); inside each loss the
  params **and** batch are cast to bf16, flax modules (``dtype=None``) promote
  to bf16 compute, and the gradient of the cast flows back to fp32 params.
  Optimizer state stays fp32.
- ``bf16-true``: params themselves are cast to bf16 once after init; the
  loss-side cast is then a no-op and optimizer state is bf16 too.
- numerics-sensitive math (distribution log-probs, two-hot, lambda targets,
  quantile moments) always runs in fp32: every distribution in
  ``sheeprl_tpu.ops.distributions`` upcasts its parameters at construction,
  so network outputs re-enter fp32 exactly at the loss boundary.

Validated: DV3-S bf16-mixed tracks fp32 losses within 0.5% over held steps
(tests/test_parallel/test_precision.py) and bf16-mixed PPO trains CartPole-v1
to the max test reward of 500 end-to-end.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

# precision name -> (param_dtype, compute_dtype)
PRECISION_DTYPES = {
    "32-true": (jnp.float32, jnp.float32),
    "16-mixed": (jnp.float32, jnp.bfloat16),  # fp16 has no TPU advantage; bf16 is native
    "bf16-mixed": (jnp.float32, jnp.bfloat16),
    "bf16-true": (jnp.bfloat16, jnp.bfloat16),
    "64-true": (jnp.float64, jnp.float64),
}


def resolve_precision(precision: str) -> Tuple[Any, Any]:
    """``precision`` name -> ``(param_dtype, compute_dtype)``."""
    if precision not in PRECISION_DTYPES:
        raise ValueError(f"Unknown precision '{precision}'; valid: {list(PRECISION_DTYPES)}")
    return PRECISION_DTYPES[precision]


def compute_dtype_of(cfg) -> Any:
    """The compute dtype implied by ``cfg.fabric.precision`` (fp32 default)."""
    fabric = cfg.get("fabric") if hasattr(cfg, "get") else None
    precision = (fabric or {}).get("precision", "32-true") if fabric else "32-true"
    return resolve_precision(precision)[1]


def cast_floating(tree: Any, dtype: Any) -> Any:
    """Cast every floating leaf of ``tree`` to ``dtype``; other leaves pass
    through.  Differentiable: the VJP of ``astype`` casts the cotangent back,
    so fp32 master params receive fp32 gradients through a bf16 cast."""
    if dtype == jnp.float32:
        return tree

    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)
