"""Coupled data-parallelism helpers (reference: Lightning DDP, SURVEY §2.4).

The reference's coupled mode is: every rank computes its own batch, gradients
are all-reduced (torch DDP on ``fabric.backward``), and the DreamerV3
``Moments`` quantile is computed over the all-gathered return values
(reference ``algos/dreamer_v3/utils.py:56-64``).

The TPU-native equivalent used across this package is ``jax.shard_map`` over a
1-D ``"data"`` mesh axis: the batch enters sharded (``P(..., "data", ...)``),
params/opt-states enter replicated (``P()``), the body computes local
gradients and explicitly ``lax.pmean``-reduces them before the optimizer
update — the collective is *in the compiled HLO*, riding ICI, not implied.
``tests/test_parallel/test_dp_sharding.py`` asserts both the input shardings
and the presence of the all-reduce in the compiled module.

Off-policy loops use these helpers so a single code path serves 1..N devices:

- :func:`dp_axis` — the axis name iff genuinely distributed, else ``None``
- :func:`fold_key` — per-device independent RNG (reference: per-rank seeds)
- :func:`pmean_tree` — gradient/metric all-reduce
- :func:`dp_jit` — shard_map + jit wrapper
- :func:`stage` — host batch → sharded device arrays (``device_put`` with a
  ``NamedSharding``; raw dtype travels over PCIe, normalization runs sharded)
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "data"


def dp_axis(mesh: Optional[Mesh]) -> Optional[str]:
    """The data-parallel axis name if ``mesh`` spans >1 device, else None."""
    if mesh is not None and mesh.devices.size > 1:
        return AXIS
    return None


def fold_key(key: jax.Array, axis: Optional[str]) -> jax.Array:
    """Per-device independent RNG stream (like per-rank seeding in DDP)."""
    if axis is None:
        return key
    return jax.random.fold_in(key, jax.lax.axis_index(axis))


def pmean_tree(tree: Any, axis: Optional[str]) -> Any:
    """Mean-reduce a pytree across the data axis (no-op when single device)."""
    if axis is None:
        return tree
    return jax.lax.pmean(tree, axis)


def all_gather_cat(x: jax.Array, axis: Optional[str]) -> jax.Array:
    """Gather shards from every device and stack on a new leading axis, so a
    subsequent global reduction (quantile, mean) sees the full batch — the
    reference's ``fabric.all_gather`` semantics."""
    if axis is None:
        return x
    return jax.lax.all_gather(x, axis)


def dp_jit(
    fn,
    mesh: Optional[Mesh],
    in_specs: Sequence[Any],
    out_specs: Any,
    donate_argnums: Tuple[int, ...] = (),
):
    """shard_map ``fn`` over the 1-D data mesh and jit it.

    ``fn`` must already be written for the local view (fold its RNG keys with
    :func:`fold_key`, pmean its grads with :func:`pmean_tree`).  When ``mesh``
    is None/size-1, this is a plain ``jax.jit`` — one code path for both.
    """
    if dp_axis(mesh) is None:
        return jax.jit(fn, donate_argnums=donate_argnums)
    from sheeprl_tpu.parallel.compat import shard_map

    mapped = shard_map(fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_specs, check_vma=False)
    return jax.jit(mapped, donate_argnums=donate_argnums)


def local_sample_size(global_batch: int, device_resident: bool = False) -> int:
    """Rows THIS PROCESS must draw from its replay buffer so the trained
    global batch is ``global_batch``.

    Host replay: single-process (any number of local devices) draws the full
    amount — ``stage`` shards it over the mesh; multi-process (DCN) draws
    ``global_batch / process_count`` because each host contributes its block
    to ``make_array_from_process_local_data`` (drawing the full global batch
    per process would silently train at ``process_count``x the configured
    batch — code-review finding, round 4).

    Device-resident replay (``device_resident=True``): the HBM ring's
    ``sample`` always takes the GLOBAL batch — its sharded gather divides
    over the whole mesh internally — so the full amount is returned
    regardless of process count."""
    if device_resident:
        return global_batch
    n = jax.process_count()
    if global_batch % n != 0:
        raise ValueError(
            f"global batch ({global_batch}) must be divisible by the process count ({n})"
        )
    return global_batch // n


def batch_spec(batch_axis: int = 0) -> P:
    """PartitionSpec sharding ``batch_axis`` over the data axis (prefix-spec
    for a whole batch pytree)."""
    return P(*([None] * batch_axis), AXIS)


def stage(tree: Any, mesh: Optional[Mesh], batch_axis: int = 0) -> Any:
    """Move a host batch pytree onto the mesh, sharded along ``batch_axis``.

    Single-device: plain ``jnp.asarray``.  Multi-device: ``jax.device_put``
    with a ``NamedSharding`` — each device receives only its shard (this is
    what makes DP *real*: the compiled step's batch argument sharding is
    ``P(..., "data")``, not replicated).
    """
    if dp_axis(mesh) is None:
        return jax.tree_util.tree_map(jnp.asarray, tree)
    sharding_cache = {}
    multiprocess = len(getattr(mesh, "devices", np.empty(0)).ravel()) > len(jax.local_devices())

    def put(x):
        x = np.asarray(x)
        spec = [None] * x.ndim
        spec[batch_axis] = AXIS
        key = x.ndim
        if key not in sharding_cache:
            sharding_cache[key] = NamedSharding(mesh, P(*spec))
        if multiprocess:
            # DCN path: the mesh spans processes, so each host holds only ITS
            # batch rows (the reference's per-rank DDP batches); assemble the
            # global array from the process-local block — only local shards
            # are transferred, the global view is logical.
            return jax.make_array_from_process_local_data(sharding_cache[key], x)
        return jax.device_put(x, sharding_cache[key])

    return jax.tree_util.tree_map(put, tree)


def normalize_staged(staged: Any, cnn_keys) -> Any:
    """Shared device-side batch preprocessing for the Dreamer loops: float32
    upcast + pixel scaling to [-0.5, 0.5] for CNN keys (data crosses the wire
    in its raw dtype; this runs on device arrays)."""
    batch = {}
    for k, arr in staged.items():
        arr = arr.astype(jnp.float32)
        if k in cnn_keys:
            arr = arr / 255.0 - 0.5
        batch[k] = arr
    return batch


def train_batches(local_data: Any, n: int, mesh: Optional[Mesh], cnn_keys, device_resident: bool):
    """The Dreamer loops' per-gradient-step batch iterator.

    Device-resident replay: ``local_data`` is already a list of HBM batches —
    just normalize.  Host replay: double-buffer the host->HBM staging via
    ``prefetch_staged``.
    """
    from functools import partial

    _normalize = partial(normalize_staged, cnn_keys=cnn_keys)
    if device_resident:
        return (_normalize(b) for b in local_data)
    return prefetch_staged(local_data, n, mesh, batch_axis=1, transform=_normalize)


def prefetch_staged(samples: Any, n: int, mesh: Optional[Mesh], batch_axis: int = 0, transform=None):
    """Double-buffered host→HBM staging over the ``n`` gradient-step slices of
    a sampled super-batch (SURVEY §2.2 TPU note; VERDICT r1 item 10).

    ``samples`` leaves are ``[n, ...]`` host arrays; slice ``i+1`` is staged
    (``device_put`` is asynchronous) immediately after slice ``i`` is yielded,
    so its host-gather + PCIe/ICI transfer overlaps the device executing step
    ``i`` instead of sitting on the critical path.  ``transform`` runs on the
    *device* arrays (normalization etc. — keep the wire format raw uint8).
    """

    def _stage(i: int):
        staged = stage(jax.tree_util.tree_map(lambda v: np.asarray(v[i]), samples), mesh, batch_axis)
        return transform(staged) if transform is not None else staged

    if n <= 0:
        return
    current = _stage(0)
    for i in range(1, n):
        upcoming = _stage(i)  # async H2D while the consumer's step i-1 runs
        yield current
        current = upcoming
    yield current
