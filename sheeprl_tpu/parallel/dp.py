"""Coupled data-parallelism helpers (reference: Lightning DDP, SURVEY §2.4).

The reference's coupled mode is: every rank computes its own batch, gradients
are all-reduced (torch DDP on ``fabric.backward``), and the DreamerV3
``Moments`` quantile is computed over the all-gathered return values
(reference ``algos/dreamer_v3/utils.py:56-64``).

The TPU-native equivalent used across this package is ``jax.shard_map`` over a
1-D ``"data"`` mesh axis: the batch enters sharded (``P(..., "data", ...)``),
params/opt-states enter replicated (``P()``), the body computes local
gradients and explicitly ``lax.pmean``-reduces them before the optimizer
update — the collective is *in the compiled HLO*, riding ICI, not implied.
``tests/test_parallel/test_dp_sharding.py`` asserts both the input shardings
and the presence of the all-reduce in the compiled module.

Off-policy loops use these helpers so a single code path serves 1..N devices:

- :func:`dp_axis` — the axis name iff genuinely distributed, else ``None``
- :func:`fold_key` — per-device independent RNG (reference: per-rank seeds)
- :func:`pmean_tree` — gradient/metric all-reduce
- :func:`dp_jit` — shard_map + jit wrapper
- :func:`stage` — host batch → sharded device arrays (``device_put`` with a
  ``NamedSharding``; raw dtype travels over PCIe, normalization runs sharded)

FSDP (2-D ``("data", "model")`` mesh — parallel/fsdp.py owns the partition
rule): the step compiles through a *global-view* jit instead of shard_map.
``dp_axis`` returns ``None`` on a model-axis mesh, so ``fold_key`` /
``pmean_tree`` / ``all_gather_cat`` become identities and ``jax.grad`` yields
global gradients; layout flows from the committed input shardings (params
sharded by :func:`fsdp.shard_tree`, batch sharded over both axes by
:func:`stage`) plus the output constraints :func:`dp_jit` applies — the
all-gather/reduce-scatter pattern is inserted by XLA, not hand-written.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.parallel.mesh import MODEL_AXIS, model_axis_size

AXIS = "data"


def fsdp_axis(mesh: Optional[Mesh]) -> Optional[str]:
    """The ``model`` (FSDP) axis name when the mesh has one of extent > 1."""
    if model_axis_size(mesh) > 1:
        return MODEL_AXIS
    return None


def dp_axis(mesh: Optional[Mesh]) -> Optional[str]:
    """The data-parallel axis name if ``mesh`` spans >1 device, else None.

    Deliberately ``None`` on an FSDP (model-axis) mesh: that path runs
    global-view jit, so the explicit per-device collectives keyed off this
    axis must become no-ops.
    """
    if fsdp_axis(mesh) is not None:
        return None
    if mesh is not None and mesh.devices.size > 1:
        return AXIS
    return None


def fold_key(key: jax.Array, axis: Optional[str]) -> jax.Array:
    """Per-device independent RNG stream (like per-rank seeding in DDP)."""
    if axis is None:
        return key
    return jax.random.fold_in(key, jax.lax.axis_index(axis))


def pmean_tree(tree: Any, axis: Optional[str]) -> Any:
    """Mean-reduce a pytree across the data axis (no-op when single device)."""
    if axis is None:
        return tree
    return jax.lax.pmean(tree, axis)


def all_gather_cat(x: jax.Array, axis: Optional[str]) -> jax.Array:
    """Gather shards from every device and stack on a new leading axis, so a
    subsequent global reduction (quantile, mean) sees the full batch — the
    reference's ``fabric.all_gather`` semantics."""
    if axis is None:
        return x
    return jax.lax.all_gather(x, axis)


def dp_jit(
    fn,
    mesh: Optional[Mesh],
    in_specs: Sequence[Any],
    out_specs: Any,
    donate_argnums: Tuple[int, ...] = (),
    min_shard_bytes: Optional[int] = None,
):
    """shard_map ``fn`` over the 1-D data mesh and jit it.

    ``fn`` must already be written for the local view (fold its RNG keys with
    :func:`fold_key`, pmean its grads with :func:`pmean_tree`).  When ``mesh``
    is None/size-1, this is a plain ``jax.jit`` — one code path for both.

    FSDP mesh: global-view jit.  The per-device collectives inside ``fn`` are
    already no-ops (``dp_axis`` returned None to the caller), inputs carry
    committed shardings, and every *output* leaf is constrained to its
    partition-rule spec (``min_shard_bytes`` tunes the rule) — params-out gets
    the identical spec as params-in, keeping donation an in-place shard-to-
    shard alias and the steady-state layout stable across iterations.
    """
    if fsdp_axis(mesh) is not None:
        from sheeprl_tpu.parallel.fsdp import constrain_tree

        def constrained(*args):
            out = fn(*args)
            return constrain_tree(out, mesh, min_shard_bytes)

        return jax.jit(constrained, donate_argnums=donate_argnums)
    if dp_axis(mesh) is None:
        return jax.jit(fn, donate_argnums=donate_argnums)
    from sheeprl_tpu.parallel.compat import shard_map

    mapped = shard_map(fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_specs, check_vma=False)
    return jax.jit(mapped, donate_argnums=donate_argnums)


def fsdp_min_shard_bytes(cfg) -> Optional[int]:
    """The configured FSDP replication floor, or None for the rule default.

    ``fabric.fsdp_min_shard_bytes`` interpolates ``distribution.
    fsdp_min_shard_bytes`` in the shipped configs; checking fabric first keeps
    a direct fabric override and the train step consistent."""
    for section in ("fabric", "distribution"):
        try:
            block = cfg.get(section) or {}
            value = block.get("fsdp_min_shard_bytes")
        except AttributeError:
            continue
        if value is not None:
            return int(value)
    return None


def local_sample_size(global_batch: int, device_resident: bool = False) -> int:
    """Rows THIS PROCESS must draw from its replay buffer so the trained
    global batch is ``global_batch``.

    Host replay: single-process (any number of local devices) draws the full
    amount — ``stage`` shards it over the mesh; multi-process (DCN) draws
    ``global_batch / process_count`` because each host contributes its block
    to ``make_array_from_process_local_data`` (drawing the full global batch
    per process would silently train at ``process_count``x the configured
    batch — code-review finding, round 4).

    Device-resident replay (``device_resident=True``): the HBM ring's
    ``sample`` always takes the GLOBAL batch — its sharded gather divides
    over the whole mesh internally — so the full amount is returned
    regardless of process count."""
    if device_resident:
        return global_batch
    n = jax.process_count()
    if global_batch % n != 0:
        raise ValueError(
            f"global batch ({global_batch}) must be divisible by the process count ({n})"
        )
    return global_batch // n


def batch_spec(batch_axis: int = 0, mesh: Optional[Mesh] = None) -> P:
    """PartitionSpec sharding ``batch_axis`` over the data axis (prefix-spec
    for a whole batch pytree).  On an FSDP mesh the batch shards over *both*
    axes — FSDP is still data parallelism (ZeRO-3: every device trains its
    own rows, only the params/opt-state are sharded)."""
    entry = (AXIS, MODEL_AXIS) if fsdp_axis(mesh) is not None else AXIS
    return P(*([None] * batch_axis), entry)


def stage(tree: Any, mesh: Optional[Mesh], batch_axis: int = 0) -> Any:
    """Move a host batch pytree onto the mesh, sharded along ``batch_axis``.

    Single-device: plain ``jnp.asarray``.  Multi-device: ``jax.device_put``
    with a ``NamedSharding`` — each device receives only its shard (this is
    what makes DP *real*: the compiled step's batch argument sharding is
    ``P(..., "data")``, not replicated).  FSDP meshes shard the batch over
    both axes (see :func:`batch_spec`).
    """
    if mesh is None or mesh.devices.size <= 1:
        return jax.tree_util.tree_map(jnp.asarray, tree)
    batch_entry = (AXIS, MODEL_AXIS) if fsdp_axis(mesh) is not None else AXIS
    sharding_cache = {}
    multiprocess = len(getattr(mesh, "devices", np.empty(0)).ravel()) > len(jax.local_devices())

    def put(x):
        x = np.asarray(x)
        spec = [None] * x.ndim
        spec[batch_axis] = batch_entry
        key = x.ndim
        if key not in sharding_cache:
            sharding_cache[key] = NamedSharding(mesh, P(*spec))
        if multiprocess:
            # DCN path: the mesh spans processes, so each host holds only ITS
            # batch rows (the reference's per-rank DDP batches); assemble the
            # global array from the process-local block — only local shards
            # are transferred, the global view is logical.
            return jax.make_array_from_process_local_data(sharding_cache[key], x)
        return jax.device_put(x, sharding_cache[key])

    return jax.tree_util.tree_map(put, tree)


def normalize_staged(staged: Any, cnn_keys) -> Any:
    """Shared device-side batch preprocessing for the Dreamer loops: float32
    upcast + pixel scaling to [-0.5, 0.5] for CNN keys (data crosses the wire
    in its raw dtype; this runs on device arrays)."""
    batch = {}
    for k, arr in staged.items():
        arr = arr.astype(jnp.float32)
        if k in cnn_keys:
            arr = arr / 255.0 - 0.5
        batch[k] = arr
    return batch


def train_batches(local_data: Any, n: int, mesh: Optional[Mesh], cnn_keys, device_resident: bool):
    """The Dreamer loops' per-gradient-step batch iterator.

    Device-resident replay: ``local_data`` is already a list of HBM batches —
    just normalize.  Host replay: double-buffer the host->HBM staging via
    ``prefetch_staged``.
    """
    from functools import partial

    _normalize = partial(normalize_staged, cnn_keys=cnn_keys)
    if device_resident:
        return (_normalize(b) for b in local_data)
    return prefetch_staged(local_data, n, mesh, batch_axis=1, transform=_normalize)


def prefetch_staged(samples: Any, n: int, mesh: Optional[Mesh], batch_axis: int = 0, transform=None):
    """Double-buffered host→HBM staging over the ``n`` gradient-step slices of
    a sampled super-batch (SURVEY §2.2 TPU note; VERDICT r1 item 10).

    ``samples`` leaves are ``[n, ...]`` host arrays; slice ``i+1`` is staged
    (``device_put`` is asynchronous) immediately after slice ``i`` is yielded,
    so its host-gather + PCIe/ICI transfer overlaps the device executing step
    ``i`` instead of sitting on the critical path.  ``transform`` runs on the
    *device* arrays (normalization etc. — keep the wire format raw uint8).
    """

    def _stage(i: int):
        staged = stage(jax.tree_util.tree_map(lambda v: np.asarray(v[i]), samples), mesh, batch_axis)
        return transform(staged) if transform is not None else staged

    if n <= 0:
        return
    current = _stage(0)
    for i in range(1, n):
        upcoming = _stage(i)  # async H2D while the consumer's step i-1 runs
        yield current
        current = upcoming
    yield current
