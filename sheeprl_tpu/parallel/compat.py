"""Version-compat imports for jax APIs that moved between releases.

``shard_map`` was promoted out of ``jax.experimental`` (and its replication
check renamed ``check_rep`` -> ``check_vma``) in newer jax; this image ships a
jax where only the experimental spelling exists.  Import the one canonical
wrapper from here instead of ``from jax import shard_map`` so every call site
works on both sides of the move — the bare top-level import was the single
cause of all 2-device test failures on this image.
"""

from __future__ import annotations

try:  # newer jax: top-level export, `check_vma` kwarg
    from jax import shard_map as _shard_map

    _CHECK_KWARG = "check_vma"
except ImportError:  # this image's jax: experimental module, `check_rep` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KWARG = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication-check kwarg translated to
    whatever the installed jax calls it (``check_vma`` is the modern name,
    ``check_rep`` the experimental-era one — same semantics)."""
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KWARG: check_vma},
    )
