"""CLI entrypoints: train / evaluate / register / list-agents.

TPU-native equivalent of /root/reference/sheeprl/cli.py:23-450.  The reference
wraps Hydra (`@hydra.main`) and Lightning Fabric (`fabric.launch` spawns one
process per device); here config composition is :func:`sheeprl_tpu.config.compose`
and there is nothing to spawn — JAX is single-controller, the `Runtime` mesh
already spans every local chip (ICI) and, under `jax.distributed`, every host.
"""

from __future__ import annotations

import importlib
import os
import pathlib
import sys
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence

import yaml

from sheeprl_tpu.config import compose, instantiate
from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry, find_algorithm, find_evaluation
from sheeprl_tpu.utils.utils import dotdict, nest_dotted, print_config


def resume_from_checkpoint(cfg: dotdict, overrides: Sequence[str] = ()) -> dotdict:
    """Merge the saved run config when resuming (reference cli.py:23-57).

    The checkpoint's archived ``config.yaml`` is the base; the user may only
    change a restricted set of keys (the reference warns and keeps the ckpt
    value for the rest).  ``overrides`` is the raw CLI override list: for the
    ``env`` / ``diagnostics`` groups only the keys the user *explicitly*
    passed are applied — replacing those whole blocks with the freshly
    composed ones would silently revert every archived setting the user did
    not re-type to its group default (and could change observation shapes
    under the checkpoint).

    ``checkpoint.resume_from`` may be a checkpoint file or any directory
    above one (run dir, ``version_N``, checkpoint dir): selection is "newest
    checkpoint whose manifest verifies" — corrupt/truncated/partial files are
    skipped with a journaled ``ckpt_skipped`` reason, never crashed on
    (howto/resilience.md).  The resolved file is protected from ``keep_last``
    pruning for the lifetime of the resumed run.
    """
    from sheeprl_tpu.resilience.manifest import resolve_resume_from
    from sheeprl_tpu.utils.checkpoint import protect_checkpoint

    resolved = resolve_resume_from(str(cfg.checkpoint.resume_from))
    protect_checkpoint(resolved)
    ckpt_path = pathlib.Path(resolved)
    old_cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not old_cfg_path.is_file():
        raise FileNotFoundError(
            f"Cannot resume from '{ckpt_path}': archived config '{old_cfg_path}' not found"
        )
    with open(old_cfg_path) as fp:
        old_cfg = dotdict(yaml.safe_load(fp))
    if old_cfg.env.id != cfg.env.id:
        raise ValueError(
            f"This experiment is run with a different environment from the one of the experiment "
            f"you want to restart: got '{cfg.env.id}', expected '{old_cfg.env.id}'"
        )
    if old_cfg.algo.name != cfg.algo.name:
        raise ValueError(
            f"This experiment is run with a different algorithm from the one of the experiment "
            f"you want to restart: got '{cfg.algo.name}', expected '{old_cfg.algo.name}'"
        )
    # keys the user is allowed to override on resume
    allowed = {"checkpoint", "fabric", "metric", "run_name", "exp_name", "seed", "dry_run", "total_steps"}
    merged = dotdict(old_cfg)
    for key in allowed:
        if key in cfg:
            merged[key] = cfg[key]
    # `diagnostics` and `env` are also overridable — a resumed run must be
    # able to e.g. raise a stall threshold, point at a new compilation-cache
    # dir, or retune env host knobs (num_envs, capture_video, executor) —
    # and so is `algo.offline`, so a collected run can be resumed straight
    # into offline fine-tuning on its own exported dataset
    # (howto/offline_rl.md) — but ONLY the dotted keys the user explicitly
    # passed: the env/algo identity stays pinned by the env.id / algo.name
    # equality checks above, and everything the user did not mention keeps
    # its archived value
    from sheeprl_tpu.config import deep_merge, yaml_load

    explicit: Dict[str, Any] = {}
    for ov in overrides:
        key, _, value = ov.partition("=")
        key = key.lstrip("+~")
        offline_key = key == "algo.offline" or key.startswith("algo.offline.")
        if key.split(".", 1)[0] not in ("env", "diagnostics") and not offline_key:
            continue
        if "." in key and key != "algo.offline":
            explicit[key] = yaml_load(value) if value != "" else None
        else:
            # group swap (env=atari / algo.offline={...}): take the whole
            # freshly composed block
            explicit[key] = cfg.get(key) if "." not in key else yaml_load(value)
    if explicit:
        deep_merge(merged, dotdict(nest_dotted(explicit)))
    merged.checkpoint.resume_from = str(ckpt_path)
    merged.root_dir = old_cfg.root_dir
    return merged


def check_configs(cfg: dotdict) -> None:
    """Config validation (reference cli.py:271-345)."""
    import warnings

    algo_name = cfg.algo.name
    entry = find_algorithm(algo_name)
    if entry is None:
        registered = sorted({m["name"] for v in algorithm_registry.values() for m in v})
        raise ValueError(
            f"Algorithm '{algo_name}' is not registered. Available algorithms: {registered}"
        )
    if cfg.get("matmul_precision", "default") not in ("default", "high", "highest", "tensorfloat32", "bfloat16", "float32"):
        raise ValueError(
            f"Invalid 'matmul_precision' value {cfg.get('matmul_precision')!r}; "
            "must be one of: default, high, highest, tensorfloat32, bfloat16, float32"
        )
    devices = cfg.fabric.devices
    strategy = str(cfg.fabric.get("strategy", "auto")).lower()
    known_strategies = ("auto", "dp", "ddp", "single")
    if entry["decoupled"]:
        if strategy not in ("auto", "dp", "ddp"):
            raise ValueError(
                f"Decoupled algorithm '{algo_name}' needs a data-parallel mesh "
                f"(fabric.strategy=auto|dp), got {strategy!r}"
            )
        n = devices if isinstance(devices, int) else 0
        if isinstance(devices, str) and devices not in ("auto", "-1"):
            n = int(devices)
        if isinstance(n, int) and 0 < n < 2:
            raise RuntimeError(
                f"Decoupled algorithm '{algo_name}' needs at least 2 devices "
                f"(1 player + >=1 trainer), got fabric.devices={devices}"
            )
    elif strategy not in known_strategies:
        warnings.warn(
            f"Unknown fabric.strategy {strategy!r}; the mesh runtime treats it as 'auto' "
            f"(known: {known_strategies})",
            UserWarning,
        )
    if cfg.metric.log_level not in (0, 1):
        raise ValueError(f"metric.log_level must be 0 or 1, got {cfg.metric.log_level}")
    # telemetry knobs fail here, not hours into a run (the endpoint binds and
    # the watchdog arms only after the log dir exists)
    telemetry_cfg = (cfg.get("diagnostics") or {}).get("telemetry") or {}
    http_cfg = telemetry_cfg.get("http") or {}
    port = http_cfg.get("port", 0) or 0
    if not isinstance(port, int) or port < 0 or port > 65535:
        raise ValueError(
            f"diagnostics.telemetry.http.port must be an integer in [0, 65535] (0 = ephemeral), got {port!r}"
        )
    watchdog_cfg = telemetry_cfg.get("watchdog") or {}
    storm_threshold = watchdog_cfg.get("storm_threshold")
    if storm_threshold is not None and int(storm_threshold) < 1:
        raise ValueError(
            f"diagnostics.telemetry.watchdog.storm_threshold must be >= 1, got {storm_threshold!r}"
        )
    # goodput watchdog knobs: >0-or-null, here AND in the GoodputMonitor
    # constructor (direct entrypoint callers skip check_configs) —
    # Event.wait(<=0) degenerates into a busy-spin, so it must never arm
    goodput_cfg = (cfg.get("diagnostics") or {}).get("goodput") or {}
    goodput_wd_cfg = goodput_cfg.get("watchdog") or {}
    for knob in ("heartbeat_s", "stall_threshold_s"):
        value = goodput_wd_cfg.get(knob)
        if value is not None and float(value) <= 0:
            raise ValueError(
                f"diagnostics.goodput.watchdog.{knob} must be > 0 or null "
                f"(null disables the watchdog), got {value!r}"
            )
    profile_cfg = goodput_cfg.get("profile") or {}
    # validated only while the pillar can actually run: the remedy the error
    # suggests (profile.enabled=False) must itself pass validation, and the
    # enabled default must match the GoodputMonitor ctor's (opt-in: False)
    if goodput_cfg.get("enabled", True) and profile_cfg.get("enabled", False):
        max_ms = profile_cfg.get("max_ms")
        if max_ms is not None and float(max_ms) < 10:
            raise ValueError(
                f"diagnostics.goodput.profile.max_ms must be >= 10 (the capture floor), "
                f"got {max_ms!r}; set diagnostics.goodput.profile.enabled=False instead"
            )
    # resilience knobs: validated here AND in the ResilienceMonitor ctor
    # (direct entrypoint callers skip check_configs) — a zero snapshot-buffer
    # depth would deadlock the first async submit
    res_cfg = (cfg.get("diagnostics") or {}).get("resilience") or {}
    max_pending = res_cfg.get("max_pending_snapshots")
    if max_pending is not None and int(max_pending) < 1:
        raise ValueError(
            f"diagnostics.resilience.max_pending_snapshots must be >= 1, got {max_pending!r}"
        )
    inject_preempt = res_cfg.get("inject_preempt_iter")
    if inject_preempt is not None and int(inject_preempt) < 1:
        raise ValueError(
            f"diagnostics.resilience.inject_preempt_iter must be >= 1 (1 = first "
            f"iteration) or null, got {inject_preempt!r}"
        )
    # fault-isolation / chaos knobs: validated here AND in their monitor
    # ctors (direct entrypoint callers skip check_configs) so a bad budget or
    # schedule fails before the run dir exists
    iso_cfg = res_cfg.get("isolation") or {}
    max_staleness = iso_cfg.get("max_staleness")
    if max_staleness is not None and int(max_staleness) < 1:
        raise ValueError(
            f"diagnostics.resilience.isolation.max_staleness must be >= 1, got {max_staleness!r}"
        )
    retry_budget = iso_cfg.get("retry_budget")
    if retry_budget is not None and int(retry_budget) < 0:
        raise ValueError(
            f"diagnostics.resilience.isolation.retry_budget must be >= 0, got {retry_budget!r}"
        )
    refresh_every = iso_cfg.get("refresh_every")
    if refresh_every is not None and int(refresh_every) < 1:
        raise ValueError(
            f"diagnostics.resilience.isolation.refresh_every must be >= 1, got {refresh_every!r}"
        )
    chaos_cfg = res_cfg.get("chaos") or {}
    from sheeprl_tpu.resilience.chaos import parse_schedule

    parse_schedule(chaos_cfg.get("schedule"))  # raises ValueError on a bad entry
    slow_write_s = chaos_cfg.get("slow_write_s")
    if slow_write_s is not None and float(slow_write_s) <= 0:
        raise ValueError(
            f"diagnostics.resilience.chaos.slow_write_s must be > 0, got {slow_write_s!r}"
        )
    # learning-health knobs: validated here AND in the HealthMonitor ctor
    # (direct entrypoint callers skip check_configs) so a bad band/window
    # fails before the run dir exists
    health_cfg = (cfg.get("diagnostics") or {}).get("health") or {}
    confirm = health_cfg.get("confirm")
    if confirm is not None and int(confirm) < 1:
        raise ValueError(f"diagnostics.health.confirm must be >= 1, got {confirm!r}")
    health_det_cfg = health_cfg.get("detectors") or {}
    ratio_low = health_det_cfg.get("update_ratio_low")
    ratio_high = health_det_cfg.get("update_ratio_high")
    if ratio_low is not None and ratio_high is not None and float(ratio_low) >= float(ratio_high):
        raise ValueError(
            "diagnostics.health.detectors.update_ratio_low must be < update_ratio_high, "
            f"got {ratio_low!r} >= {ratio_high!r}"
        )
    plateau_window = health_det_cfg.get("plateau_window")
    if plateau_window is not None and int(plateau_window) < 2:
        raise ValueError(
            f"diagnostics.health.detectors.plateau_window must be >= 2, got {plateau_window!r}"
        )
    if (
        health_cfg.get("inject_entropy_collapse_iter") is not None
        and health_det_cfg.get("entropy_floor") is None
    ):
        raise ValueError(
            "diagnostics.health.inject_entropy_collapse_iter requires "
            "diagnostics.health.detectors.entropy_floor — a drill against a disarmed "
            "detector could never fire"
        )
    # chunked RSSM scan knobs (DV3-family): fail at compose time, not at the
    # first train-step trace hours into a run
    rssm_chunks = cfg.algo.get("rssm_chunks")
    if rssm_chunks is not None:
        rssm_chunks = int(rssm_chunks)
        if rssm_chunks < 1:
            raise ValueError(f"algo.rssm_chunks must be >= 1, got {rssm_chunks}")
        burn_in = int(cfg.algo.get("rssm_chunk_burn_in", 0) or 0)
        if burn_in < 0:
            raise ValueError(f"algo.rssm_chunk_burn_in must be >= 0, got {burn_in}")
        seq_len = cfg.algo.get("per_rank_sequence_length")
        if rssm_chunks > 1 and isinstance(seq_len, int):
            if seq_len % rssm_chunks != 0:
                raise ValueError(
                    f"algo.rssm_chunks ({rssm_chunks}) must divide "
                    f"algo.per_rank_sequence_length ({seq_len})"
                )
            if burn_in >= seq_len // rssm_chunks:
                raise ValueError(
                    f"algo.rssm_chunk_burn_in ({burn_in}) must be < the chunk length "
                    f"({seq_len // rssm_chunks} = per_rank_sequence_length / rssm_chunks)"
                )
    # FSDP knobs (howto/sharding.md): fail at compose time — a bad axis size
    # would otherwise surface as an opaque mesh-reshape error inside Runtime
    fsdp_raw = cfg.fabric.get("fsdp", 1)
    fsdp = 1 if fsdp_raw is None else int(fsdp_raw)
    if fsdp < 1:
        raise ValueError(f"distribution.fsdp_axis_size must be >= 1, got {fsdp}")
    min_shard = cfg.fabric.get("fsdp_min_shard_bytes")
    if min_shard is not None and int(min_shard) < 0:
        raise ValueError(
            f"distribution.fsdp_min_shard_bytes must be >= 0, got {min_shard!r}"
        )
    if fsdp > 1:
        # literal set (mirrors the offline gate below): the global-view FSDP
        # step is wired through _dreamer_main only
        fsdp_supported = ("dreamer_v3", "dreamer_v3_jepa", "p2e_dv1", "p2e_dv2", "p2e_dv3")
        if algo_name not in fsdp_supported:
            raise ValueError(
                f"distribution.fsdp_axis_size > 1 supports the DV3 family "
                f"{list(fsdp_supported)}, got algo.name={algo_name!r}"
            )
        if (cfg.algo.get("offline") or {}).get("enabled"):
            raise ValueError(
                "distribution.fsdp_axis_size > 1 is not supported with "
                "algo.offline.enabled=true (the offline loop is single-device)"
            )
        n_dev = devices
        if isinstance(n_dev, str) and n_dev not in ("auto", "-1"):
            n_dev = int(n_dev)
        if isinstance(n_dev, int) and n_dev > 0 and n_dev % fsdp != 0:
            raise ValueError(
                f"distribution.fsdp_axis_size ({fsdp}) must divide "
                f"fabric.devices ({n_dev})"
            )
    # offline training mode (howto/offline_rl.md): fail at compose time, not
    # after the log dir exists — the mode swaps the whole entrypoint
    offline_cfg = cfg.algo.get("offline") or {}
    if offline_cfg.get("enabled"):
        # literal set (not an import) so config validation never pays the
        # offline subsystem's jax imports
        supported = ("sac", "droq", "dreamer_v3")
        if algo_name not in supported:
            raise ValueError(
                f"algo.offline.enabled=true supports {list(supported)}, got algo.name={algo_name!r}"
            )
        if not offline_cfg.get("dataset_dir"):
            raise ValueError(
                "algo.offline.enabled=true requires algo.offline.dataset_dir "
                "(an exported dataset — see sheeprl-export / howto/offline_rl.md)"
            )
        if float(offline_cfg.get("cql_alpha", 0.0) or 0.0) < 0:
            raise ValueError(
                f"algo.offline.cql_alpha must be >= 0, got {offline_cfg.get('cql_alpha')!r}"
            )
        cql_samples = offline_cfg.get("cql_samples")
        if cql_samples is not None and int(cql_samples) < 1:
            raise ValueError(f"algo.offline.cql_samples must be >= 1, got {cql_samples!r}")
        grad_steps = offline_cfg.get("grad_steps_per_iter")
        if grad_steps is not None and int(grad_steps) < 1:
            raise ValueError(
                f"algo.offline.grad_steps_per_iter must be >= 1, got {grad_steps!r}"
            )
        if int(offline_cfg.get("prefetch", 2) or 0) < 0:
            raise ValueError(
                f"algo.offline.prefetch must be >= 0 (0 disables the prefetch thread), "
                f"got {offline_cfg.get('prefetch')!r}"
            )
        seq = offline_cfg.get("sequence_length")
        if seq is not None and int(seq) < 1:
            raise ValueError(f"algo.offline.sequence_length must be >= 1 or null, got {seq!r}")
        if entry["decoupled"]:
            raise ValueError(
                "algo.offline.enabled=true drives the coupled train step; decoupled "
                f"algorithm '{algo_name}' has no offline mode"
            )
    elif float(offline_cfg.get("cql_alpha", 0.0) or 0.0) != 0.0:
        warnings.warn(
            "algo.offline.cql_alpha is set but algo.offline.enabled=false: the conservative "
            "penalty WILL apply to the online run's critic update too (it is a train-step "
            "knob); set it to 0 unless that is intended",
            UserWarning,
        )
    learning_starts = cfg.algo.get("learning_starts")
    if learning_starts is not None and learning_starts < 0:
        raise ValueError("The `algo.learning_starts` parameter must be greater or equal to zero")
    if cfg.env.get("action_repeat", 1) < 1:
        warnings.warn(
            f"env.action_repeat={cfg.env.action_repeat} is below the minimum of 1; clamping to 1",
            UserWarning,
        )
        cfg.env.action_repeat = 1
    if not cfg.model_manager.get("disabled", True):
        from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

        if not _IS_MLFLOW_AVAILABLE:
            warnings.warn(
                "MLFlow is not installed; setting model_manager.disabled=True", UserWarning
            )
            cfg.model_manager.disabled = True


def check_configs_evaluation(cfg: dotdict) -> None:
    if cfg.checkpoint_path is None:
        raise ValueError("You must specify the evaluation checkpoint path: checkpoint_path=...")


def run_algorithm(cfg: dotdict):
    """Registry lookup → runtime instantiation → entrypoint launch
    (reference cli.py:60-199).  Returns whatever the entrypoint returns —
    training mains return the final test reward when ``algo.run_test`` is on,
    which the search harness uses as its objective."""
    entry = find_algorithm(cfg.algo.name)
    if entry is None:
        raise ValueError(f"Algorithm '{cfg.algo.name}' is not registered")
    if (cfg.algo.get("offline") or {}).get("enabled"):
        # env-free offline mode: same runtime/diagnostics scaffold, but the
        # dataset loader replaces the env/player entirely
        # (sheeprl_tpu/offline/train.py; pipelined_vector_env refuses to run)
        from sheeprl_tpu.offline.train import offline_main

        entrypoint = offline_main
    else:
        module = importlib.import_module(entry["module"])
        entrypoint = getattr(module, entry["entrypoint"])

    # Algo utils module exposes AGGREGATOR_KEYS / MODELS_TO_REGISTER
    # (reference cli.py:151-181): prune metric + model-manager config to what
    # the algorithm actually produces.
    utils_module_name = entry["module"].rsplit(".", 1)[0] + ".utils"
    try:
        algo_utils = importlib.import_module(utils_module_name)
    except ModuleNotFoundError:
        algo_utils = None
    if algo_utils is not None:
        keys = getattr(algo_utils, "AGGREGATOR_KEYS", None)
        metrics_cfg = cfg.metric.aggregator.get("metrics", {})
        if keys is not None and isinstance(metrics_cfg, dict):
            cfg.metric.aggregator.metrics = dotdict(
                {k: v for k, v in metrics_cfg.items() if k in keys}
            )
        models = getattr(algo_utils, "MODELS_TO_REGISTER", None)
        mm = cfg.model_manager.get("models", {})
        if models is not None and isinstance(mm, dict):
            cfg.model_manager.models = dotdict({k: v for k, v in mm.items() if k in models})

    runtime = instantiate(cfg.fabric)
    # Run-health facade (journal / sentinel / tracing): built here, attached
    # to the runtime, opened by the training loop once the run dir exists
    # (utils.get_diagnostics / utils.logger plumbing).
    from sheeprl_tpu.diagnostics import SentinelHalt, build_diagnostics

    diagnostics = runtime.diagnostics = build_diagnostics(cfg)
    status = "completed"
    try:
        profiler_cfg = cfg.metric.get("profiler", {})
        if profiler_cfg.get("enabled", False):
            # one trace around the whole run: compile + steps + host gaps all
            # land in the same Perfetto timeline (SURVEY §5 profiling upgrade)
            import jax

            trace_dir = profiler_cfg.get("trace_dir") or os.path.join("logs", "profiler_trace")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            try:
                return runtime.launch(entrypoint, cfg)
            finally:
                jax.profiler.stop_trace()
        return runtime.launch(entrypoint, cfg)
    except SentinelHalt:
        status = "halted"
        raise
    except BaseException as err:
        from sheeprl_tpu.resilience.preemption import PreemptedExit

        # a graceful preemption already journaled `preempted` and closed the
        # facade with status="preempted" before raising; the close() in the
        # finally block is idempotent, so "aborted" never overwrites it
        status = "preempted" if isinstance(err, PreemptedExit) else "aborted"
        raise
    finally:
        # idempotent: a loop that finished cleanly already closed with
        # status="completed"; this covers exceptions (journal gets run_end)
        diagnostics.close(status)


def _force_cpu_platform_if_selected(cfg: dotdict) -> None:
    """Force the CPU platform BEFORE any jax array op when the config selects
    the cpu accelerator: site configuration may pre-register a remote
    accelerator plugin (e.g. a tunneled TPU) as the default backend, and
    merely selecting cpu devices later would still initialize — and block
    on — that backend for the default-placed arrays (PRNG keys, host
    scalars).  Shared by run/evaluation/registration; callers must invoke it
    before anything touches jax."""
    if cfg.fabric.get("accelerator") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def run(args: Optional[Sequence[str]] = None):
    """Train entrypoint (reference cli.py:358-366).  ``args`` defaults to
    ``sys.argv[1:]`` — Hydra-style ``group=option``/``a.b=v`` overrides."""
    overrides = list(args if args is not None else sys.argv[1:])
    cfg = compose(overrides)
    _force_cpu_platform_if_selected(cfg)
    n_threads = cfg.get("num_threads")
    if n_threads and int(n_threads) > 0:
        # host-side thread budget.  BLAS pools already initialized in this
        # process ignore these (sheeprl.py sets them pre-import for the CLI
        # path); they still cap async-env subprocesses, which inherit the env.
        for var in ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS", "MKL_NUM_THREADS"):
            os.environ.setdefault(var, str(int(n_threads)))
    if cfg.checkpoint.resume_from:
        cfg = resume_from_checkpoint(cfg, overrides)
    print_config(cfg)
    check_configs(cfg)
    _apply_global_flags(cfg)
    return run_algorithm(cfg)


def _apply_global_flags(cfg: dotdict) -> None:
    """Determinism/precision flags (reference cli.py:187-197 seeds torch and
    sets deterministic algorithms; here: matmul precision + PRNG seeding is
    done per-runtime in `seed_everything`)."""
    import jax

    precision = cfg.get("matmul_precision", "default")
    if precision and precision != "default":
        jax.config.update("jax_default_matmul_precision", precision)
    # persistent compilation cache (ROADMAP item 2): must be set BEFORE the
    # first compile, which is why it lives here and not in the diagnostics
    # facade (opened only once the run dir exists).  The facade journals a
    # `compilation_cache` event at open so the run records where it cached.
    cache_dir = (cfg.get("diagnostics") or {}).get("compilation_cache_dir")
    if cache_dir:
        os.makedirs(str(cache_dir), exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        # default min compile time is 1s — production restarts should also
        # skip the many sub-second helper jits, not just the train step
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        except AttributeError:  # pragma: no cover - older jax spelling
            pass


def eval_algorithm(cfg: dotdict) -> None:
    """Evaluation launch (reference cli.py:202-268)."""
    entry = find_evaluation(cfg.algo.name)
    if entry is None:
        registered = sorted({m["name"] for v in evaluation_registry.values() for m in v})
        raise ValueError(
            f"Evaluation for algorithm '{cfg.algo.name}' is not registered. Available: {registered}"
        )
    module = importlib.import_module(entry["module"])
    entrypoint = getattr(module, entry["entrypoint"])
    runtime = instantiate(cfg.fabric)
    state = runtime.load(cfg.checkpoint_path)
    runtime.launch(entrypoint, cfg, state)


def evaluation(args: Optional[Sequence[str]] = None) -> None:
    """Eval entrypoint ``sheeprl-eval`` (reference cli.py:369-405): loads the
    checkpoint's archived config, merges user overrides, forces one device."""
    overrides = list(args if args is not None else sys.argv[1:])
    flat: Dict[str, Any] = {}
    for ov in overrides:
        key, _, value = ov.partition("=")
        flat[key.lstrip("+")] = yaml.safe_load(value) if value != "" else None
    if "checkpoint_path" not in flat or flat["checkpoint_path"] is None:
        raise ValueError("You must specify the evaluation checkpoint path: checkpoint_path=...")
    ckpt_path = pathlib.Path(flat.pop("checkpoint_path"))
    cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not cfg_path.is_file():
        raise FileNotFoundError(f"Archived run config not found at '{cfg_path}'")
    with open(cfg_path) as fp:
        cfg = dotdict(yaml.safe_load(fp))
    from sheeprl_tpu.config import deep_merge

    deep_merge(cfg, dotdict(nest_dotted(flat)))
    if not any(k == "run_name" for k in flat):
        cfg.run_name = f"{os.path.basename(str(ckpt_path.parent.parent))}_evaluation"
    user_logger_override = any(
        k == "metric.logger" or k.startswith("metric.logger.") for k in flat
    ) or (isinstance(flat.get("metric"), dict) and "logger" in flat["metric"])
    logger_cfg = cfg.metric.get("logger")
    if logger_cfg is not None and not user_logger_override:
        # the archived logger paths are fully resolved and point INSIDE the
        # training run; re-root them at the FINAL (post-override) evaluation
        # run dir so eval metrics don't append to the trained run's event
        # stream — unless the user pointed the logger somewhere explicitly
        if "root_dir" in logger_cfg:
            logger_cfg.root_dir = os.path.join("logs", "runs", str(cfg.root_dir))
        if "name" in logger_cfg:
            logger_cfg.name = cfg.run_name
        if "save_dir" in logger_cfg:
            logger_cfg.save_dir = os.path.join("logs", "runs", str(cfg.root_dir))
        # wandb/mlflow don't carry a `name` key in their archived configs, so
        # the branch above leaves their eval runs indistinguishable from the
        # training run; inject the backend's run-name kwarg so they show up
        # as `*_evaluation` like the tensorboard layout does
        target = str(logger_cfg.get("_target_", ""))
        if target.endswith("WandbLogger"):
            logger_cfg.name = cfg.run_name  # wandb.init(name=...)
        elif target.endswith("MLFlowLogger"):
            logger_cfg.run_name = cfg.run_name  # mlflow.start_run(run_name=...)
    cfg.checkpoint_path = str(ckpt_path)
    # honors the ARCHIVED config too; nothing has touched jax before this point
    _force_cpu_platform_if_selected(cfg)
    # force single-device, strategy-free evaluation (reference cli.py:388-401)
    cfg.fabric = dotdict(
        {
            "_target_": "sheeprl_tpu.parallel.runtime.Runtime",
            "devices": 1,
            "num_nodes": 1,
            "strategy": "auto",
            "accelerator": cfg.fabric.get("accelerator", "auto"),
            "precision": cfg.fabric.get("precision", "32-true"),
        }
    )
    cfg.env.num_envs = 1
    check_configs_evaluation(cfg)
    eval_algorithm(cfg)


def serve(args: Optional[Sequence[str]] = None) -> None:
    """Inference-tier entrypoint ``sheeprl-serve`` / ``python -m sheeprl_tpu
    serve`` (howto/serving.md): load a checkpoint with its archived run
    config, start the batched policy server and the health-gated hot-reload
    watcher.

    Overrides follow the eval/registration pattern: ``checkpoint_path=...``
    is required, everything else (``serving.port=8080``,
    ``serving.reload.enabled=False``, ``fabric.accelerator=cpu``, ...) is a
    dotted override on top of the archived config.
    """
    overrides = list(args if args is not None else sys.argv[1:])
    flat: Dict[str, Any] = {}
    for ov in overrides:
        key, _, value = ov.partition("=")
        flat[key.lstrip("+")] = yaml.safe_load(value) if value != "" else None
    ckpt = flat.pop("checkpoint_path", None)
    if ckpt is None:
        raise ValueError("You must specify the checkpoint path: checkpoint_path=...")
    ckpt_path = pathlib.Path(ckpt)
    cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not cfg_path.is_file():
        raise FileNotFoundError(f"Archived run config not found at '{cfg_path}'")
    with open(cfg_path) as fp:
        cfg = dotdict(yaml.safe_load(fp))
    from sheeprl_tpu.config import compose_group, deep_merge

    deep_merge(cfg, dotdict(nest_dotted(flat)))
    # checkpoints archived before the serving group existed (or with a
    # partial block): the group defaults underpin whatever the archive /
    # overrides carry, so every knob has a value
    serving = compose_group("serving", "default")
    deep_merge(serving, cfg.get("serving") or {})
    cfg.serving = serving
    # honors the archived config too; nothing has touched jax before this point
    _force_cpu_platform_if_selected(cfg)
    from sheeprl_tpu.serving.server import serve_checkpoint

    serve_checkpoint(cfg, str(ckpt_path))


def registration(args: Optional[Sequence[str]] = None) -> None:
    """Model-registry entrypoint ``sheeprl-registration``
    (reference cli.py:408-450): publish checkpointed models to MLflow."""
    overrides = list(args if args is not None else sys.argv[1:])
    flat: Dict[str, Any] = {}
    for ov in overrides:
        key, _, value = ov.partition("=")
        flat[key.lstrip("+")] = yaml.safe_load(value) if value != "" else None
    ckpt = flat.pop("checkpoint_path", None)
    if ckpt is None:
        raise ValueError("You must specify the checkpoint path: checkpoint_path=...")
    ckpt_path = pathlib.Path(ckpt)
    cfg_path = ckpt_path.parent.parent / "config.yaml"
    with open(cfg_path) as fp:
        cfg = dotdict(yaml.safe_load(fp))
    from sheeprl_tpu.config import deep_merge

    deep_merge(cfg, dotdict(nest_dotted(flat)))
    cfg.checkpoint_path = str(ckpt_path)
    # honors the archived config too; nothing has touched jax before this point
    _force_cpu_platform_if_selected(cfg)
    from sheeprl_tpu.utils.mlflow import register_model_from_checkpoint

    register_model_from_checkpoint(cfg)
