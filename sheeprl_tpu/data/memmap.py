"""Disk-backed numpy arrays with ownership semantics.

Behavioral equivalent of the reference's ``MemmapArray``
(/root/reference/sheeprl/utils/memmap.py:22-270): an ndarray view over an OS
memory-mapped file with explicit file ownership (the owner deletes the file on
``__del__``), safe flush/close, and pickling support that re-attaches to the
file on restore (the receiving process never owns the file).

On a TPU-VM this is how replay buffers exceed host RAM: the OS pages buffer
slices in on demand while sampling, and `sample_tensors` stages only the
sampled minibatch into HBM.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Tuple

import numpy as np

_ALLOWED_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")


class MemmapArray:
    def __init__(
        self,
        shape: Tuple[int, ...],
        dtype: Any = np.float32,
        mode: str = "r+",
        filename: str | os.PathLike | None = None,
    ):
        if mode not in _ALLOWED_MODES:
            raise ValueError(f"Accepted values for mode are {_ALLOWED_MODES}, got '{mode}'")
        if filename is None:
            raise ValueError("A 'filename' must be provided for a MemmapArray")
        self._filename = Path(filename).resolve()
        self._filename.parent.mkdir(parents=True, exist_ok=True)
        self._shape = tuple(shape)
        self._dtype = np.dtype(dtype)
        self._mode = mode
        existed = self._filename.is_file()
        # np.memmap needs 'w+' to create; preserve content when attaching
        create_mode = mode if existed and mode != "w+" else "w+"
        self._array = np.memmap(self._filename, dtype=self._dtype, mode=create_mode, shape=self._shape)
        self._has_ownership = True

    # -- core ndarray-ish API ------------------------------------------------
    @property
    def array(self) -> np.memmap:
        if self._array is None:
            raise RuntimeError("The memmap has been closed")
        return self._array

    @array.setter
    def array(self, value: np.ndarray) -> None:
        if not isinstance(value, np.ndarray):
            raise ValueError("The value to set must be a numpy array")
        if value.shape != self._shape:
            raise ValueError(f"Shape mismatch: expected {self._shape}, got {value.shape}")
        self._array[:] = value

    @property
    def filename(self) -> str:
        return str(self._filename)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def nbytes(self) -> int:
        """Bytes of the backing file (shape x itemsize — what the buffer
        costs on disk; the OS pages it in and out of RAM on demand)."""
        size = self._dtype.itemsize
        for dim in self._shape:
            size *= int(dim)
        return size

    @property
    def has_ownership(self) -> bool:
        return self._has_ownership

    @has_ownership.setter
    def has_ownership(self, value: bool) -> None:
        self._has_ownership = bool(value)

    def __getitem__(self, idx) -> np.ndarray:
        return self.array[idx]

    def __setitem__(self, idx, value) -> None:
        self.array[idx] = value

    def __array__(self, dtype=None) -> np.ndarray:
        arr = np.asarray(self.array)
        return arr.astype(dtype) if dtype is not None else arr

    def __len__(self) -> int:
        return self._shape[0]

    def flush(self) -> None:
        if self._array is not None:
            self._array.flush()

    def __del__(self) -> None:
        try:
            if getattr(self, "_array", None) is not None:
                self._array.flush()
                # release the mmap before (possibly) deleting the backing file
                del self._array
                self._array = None
            if getattr(self, "_has_ownership", False) and self._filename.is_file():
                self._filename.unlink()
        except Exception:
            pass

    # -- pickling: re-attach without taking ownership ------------------------
    def __getstate__(self) -> dict:
        self.flush()
        return {
            "_filename": self._filename,
            "_shape": self._shape,
            "_dtype": self._dtype,
            "_mode": self._mode,
        }

    def __setstate__(self, state: dict) -> None:
        self._filename = state["_filename"]
        self._shape = state["_shape"]
        self._dtype = state["_dtype"]
        self._mode = state["_mode"]
        self._array = np.memmap(self._filename, dtype=self._dtype, mode="r+", shape=self._shape)
        self._has_ownership = False

    @classmethod
    def from_array(
        cls, array: np.ndarray | "MemmapArray", filename: str | os.PathLike, mode: str = "r+"
    ) -> "MemmapArray":
        if isinstance(array, MemmapArray):
            array = array.array
        out = cls(shape=array.shape, dtype=array.dtype, mode=mode, filename=filename)
        out.array = np.asarray(array)
        return out

    def __repr__(self) -> str:
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, mode={self._mode}, filename={self._filename})"
