"""Vectorized step-slab builder for the training loops' replay writes.

Every hot loop appends one vector step to its replay buffer as a
``{key: [1, num_envs, ...]}`` dict.  Before this helper each loop hand-rolled
the slab key by key (``np.asarray(...).reshape(1, num_envs, -1)`` etc.) —
O(keys) redundant Python per step spread over eleven loops, each a chance to
drift in dtype or layout.  :func:`step_slab` builds the whole record with one
vectorized view (or dtype-cast copy) per key and no per-env Python:

* inputs are per-env batched arrays ``[num_envs]`` or ``[num_envs, ...]``
  (exactly what the vector env / policy fetch returns);
* 1-D inputs gain the trailing feature axis (``[N] -> [1, N, 1]``), matching
  the buffer convention every loop used;
* >=2-D inputs keep their trailing dims (``[N, C, H, W] -> [1, N, C, H, W]``);
* an optional per-key dtype map applies the cast in the same pass (e.g.
  ``rewards``/``terminated`` to float32).

``reshape``/``expand_dims`` return views, so the only copies are requested
dtype casts — the buffer's own ``add`` does the one storage write per key.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import numpy as np


def step_slab(
    num_envs: int,
    arrays: Mapping[str, Any],
    dtypes: Optional[Mapping[str, Any]] = None,
) -> Dict[str, np.ndarray]:
    """Build the ``[1, num_envs, ...]`` step record for ``ReplayBuffer.add``.

    Raises on a leading-dim mismatch — a key accidentally passed per-env (or
    already slab-shaped) would otherwise silently write garbage rows.
    """
    out: Dict[str, np.ndarray] = {}
    for key, value in arrays.items():
        dtype = dtypes.get(key) if dtypes else None
        arr = np.asarray(value, dtype=dtype)
        if arr.ndim == 0 or arr.shape[0] != num_envs:
            raise ValueError(
                f"step_slab key '{key}' must be [num_envs={num_envs}, ...], got shape {arr.shape}"
            )
        if arr.ndim == 1:
            arr = arr.reshape(num_envs, 1)
        out[key] = arr[np.newaxis]
    return out
