"""Vectorized step-slab builder for the training loops' replay writes.

Every hot loop appends one vector step to its replay buffer as a
``{key: [1, num_envs, ...]}`` dict.  Before this helper each loop hand-rolled
the slab key by key (``np.asarray(...).reshape(1, num_envs, -1)`` etc.) —
O(keys) redundant Python per step spread over eleven loops, each a chance to
drift in dtype or layout.  :func:`step_slab` builds the whole record with one
vectorized view (or dtype-cast copy) per key and no per-env Python:

* inputs are per-env batched arrays ``[num_envs]`` or ``[num_envs, ...]``
  (exactly what the vector env / policy fetch returns);
* 1-D inputs gain the trailing feature axis (``[N] -> [1, N, 1]``), matching
  the buffer convention every loop used;
* >=2-D inputs keep their trailing dims (``[N, C, H, W] -> [1, N, C, H, W]``);
* an optional per-key dtype map applies the cast in the same pass (e.g.
  ``rewards``/``terminated`` to float32).

``reshape``/``expand_dims`` return views, so the only copies are requested
dtype casts — the buffer's own ``add`` does the one storage write per key.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import numpy as np


def step_slab(
    num_envs: int,
    arrays: Mapping[str, Any],
    dtypes: Optional[Mapping[str, Any]] = None,
) -> Dict[str, np.ndarray]:
    """Build the ``[1, num_envs, ...]`` step record for ``ReplayBuffer.add``.

    Raises on a leading-dim mismatch — a key accidentally passed per-env (or
    already slab-shaped) would otherwise silently write garbage rows.
    """
    out: Dict[str, np.ndarray] = {}
    for key, value in arrays.items():
        dtype = dtypes.get(key) if dtypes else None
        arr = np.asarray(value, dtype=dtype)
        if arr.ndim == 0 or arr.shape[0] != num_envs:
            raise ValueError(
                f"step_slab key '{key}' must be [num_envs={num_envs}, ...], got shape {arr.shape}"
            )
        if arr.ndim == 1:
            arr = arr.reshape(num_envs, 1)
        out[key] = arr[np.newaxis]
    return out


def rssm_state_slab(num_envs: int, recurrent: Any, stochastic: Any, valid: bool) -> Dict[str, Any]:
    """``[1, num_envs, ...]`` replay record of the player's post-step RSSM
    state (``algo.rssm_chunks > 1`` — see
    ``sheeprl_tpu/algos/dreamer_v3/utils.py::RSSM_STATE_KEYS``).

    ``recurrent``/``stochastic`` are the ``[num_envs, H]`` / ``[num_envs, Z]``
    state the player already computed for this step; numpy arrays pass
    through as views, **device arrays stay on device** (the HBM-resident
    replay path writes them without a host round trip).  ``valid=False``
    marks rows written without a real player state (prefill random actions,
    episode-end bookkeeping rows): a chunk whose initial state lands on such
    a row resets to the learned initial state instead of training on
    garbage."""
    if recurrent.shape[0] != num_envs or stochastic.shape[0] != num_envs:
        raise ValueError(
            f"rssm_state_slab states must be [num_envs={num_envs}, ...], got "
            f"{recurrent.shape} / {stochastic.shape}"
        )
    return {
        "rssm_recurrent": recurrent[np.newaxis],
        "rssm_posterior": stochastic[np.newaxis],
        "rssm_valid": np.full((1, num_envs, 1), 1.0 if valid else 0.0, np.float32),
    }
