"""Device-resident sequential replay buffer.

The reference streams every sampled batch host->GPU each gradient step
(reference sheeprl/data/buffers.py:291-326 converts to torch tensors per
sample).  On TPU that transfer is the end-to-end bottleneck: a DV3-S batch
(16 x 64 x 64x64x3 uint8) is ~50 MB per gradient step, while the *collected*
data is only ~12 KB per policy step.  This buffer therefore keeps the whole
replay ring in HBM:

- ``add`` scatters one policy step into the ring in place (jitted, donated)
  — the only host->device traffic is the newest frame;
- per-env write heads: envs advance independently (episode-end rows are
  appended only to done envs), replacing the host path's one-sub-buffer-per-
  env ``EnvIndependentReplayBuffer`` + ``SequentialReplayBuffer`` pair;
- ``sample`` draws sequence windows with the host ``SequentialReplayBuffer``'s
  age-space semantics (windows never span an env's write head; starts uniform
  over each env's valid range) but the gather runs on device and the returned
  ``[T, B, ...]`` batch never touches the host.  Env choice is uniform on a
  single device; in multi-device mode it is *block-stratified* — each device's
  batch block draws only from its own env shard (see ``_draw_env_idx``);
- capacity math: DV3 Atari-100K (1e5 steps x 64x64x3 uint8) is ~1.2 GB — it
  fits v5e HBM next to the S model.  For bigger buffers keep the host path
  (``buffer.device=False``).

Head bookkeeping (per-env ``pos``/``full``) stays on the host: it's a few
ints per policy step, and host-side index math keeps sampling logic in cheap
numpy while every array byte stays in HBM.
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, donate_argnums=(0,))
def _scatter_all(buf: Dict[str, jax.Array], step: Dict[str, jax.Array], rows: jax.Array, envs: jax.Array) -> Dict[str, jax.Array]:
    """Whole-dict ring write in ONE dispatched program: ``step[k]`` is
    ``[n_sel, ...]`` written at ``(rows[i], envs[i])`` of ``buf[k]``.  One
    device call per policy step instead of one per key — through a remote
    device tunnel each dispatch costs ~1 ms, so at 7 buffer keys this is the
    difference between ~1 ms and ~7 ms of per-step overhead.  Works for
    sharded storage too: the updates are tiny and the SPMD partitioner
    applies each to the owning shard."""
    return {k: buf[k].at[rows, envs].set(step[k]) for k in buf}


@partial(jax.jit, static_argnums=(3,))
def _gather_all(buf: Dict[str, jax.Array], starts: jax.Array, env_idx: jax.Array, seq_len: int) -> Dict[str, jax.Array]:
    """Whole-dict sequence gather in ONE dispatched program:
    ``[cap, n_envs, ...] -> [seq_len, B, ...]`` per key; window ``b`` is rows
    ``(starts[b] + t) % cap`` of env ``env_idx[b]``."""
    cap = next(iter(buf.values())).shape[0]
    rows = (starts[None, :] + jnp.arange(seq_len)[:, None]) % cap  # [T, B]
    return {k: v[rows, env_idx[None, :]] for k, v in buf.items()}


def _make_sharded_gather(mesh, seq_len: int):
    """Per-device local gather over an env-sharded ring (multi-device mode).

    Inside ``shard_map`` every device sees only its env block; ``env_idx`` is
    drawn block-stratified on the host so each device's indices are local.
    The output batch leaves sharded ``P(None, "data")`` on the batch axis —
    exactly the in_spec of the shard_map'd Dreamer train steps — with ZERO
    cross-device traffic.
    """
    from jax.sharding import PartitionSpec as P

    from sheeprl_tpu.parallel.dp import dp_jit

    def local_gather(storage, starts, env_local):
        return _gather_all(storage, starts, env_local, seq_len)

    return dp_jit(
        local_gather,
        mesh,
        in_specs=(P(None, "data"), P("data"), P("data")),
        out_specs=P(None, "data"),
    )


class DeviceSequentialReplayBuffer:
    """Sequence replay living in HBM (single-host; per-env write heads).

    API mirrors what the Dreamer loop needs from the host
    ``EnvIndependentReplayBuffer(SequentialReplayBuffer)``: ``add(step_data[,
    indices])``, ``sample(batch, sequence_length, n_samples)`` (a list of
    device batches, one per gradient step), ``state_dict``/``load_state_dict``,
    ``mark_last_truncated``.
    """

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = (),
        mesh: Optional[Any] = None,
        **_: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        self._buffer_size = int(buffer_size)
        self._n_envs = int(n_envs)
        self._obs_keys = tuple(obs_keys)
        self._buf: Dict[str, jax.Array] = {}
        self._pos = np.zeros(self._n_envs, dtype=np.int64)
        self._filled = np.zeros(self._n_envs, dtype=np.int64)  # rows ever written, capped at size
        self._added = np.zeros(self._n_envs, dtype=np.int64)  # monotone (dataset-export cursor)
        self.dataset_disk_bytes = 0
        self._rng = np.random.default_rng()
        # multi-device: the ring is sharded over the mesh's data axis along
        # the env dimension; each device stores and samples only its env block
        self._mesh = mesh if (mesh is not None and mesh.devices.size > 1) else None
        self._world = int(self._mesh.devices.size) if self._mesh else 1
        if self._mesh and self._n_envs % self._world != 0:
            raise ValueError(
                f"n_envs ({self._n_envs}) must be divisible by the mesh size ({self._world}) "
                "for the env-sharded device buffer"
            )
        self._gather_cache: Dict[int, Any] = {}

    # -- properties mirrored from the host buffer ---------------------------
    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def full(self):
        return tuple(bool(f >= self._buffer_size) for f in self._filled)

    @property
    def empty(self) -> bool:
        return not self._buf

    @property
    def is_memmap(self) -> bool:
        return False

    @property
    def added_steps(self) -> np.ndarray:
        """Per-env monotone count of steps ever added (envs advance
        independently here — episode-end rows go only to done envs)."""
        return self._added.copy()

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # -- write path ----------------------------------------------------------
    def add(self, data: Dict[str, np.ndarray], indices: Any = None, validate_args: bool = False) -> None:
        """Insert ONE policy step.  ``data`` leaves are ``[1, n_sel, ...]``
        where ``n_sel = len(indices)`` (all envs when ``indices`` is None)."""
        del validate_args
        # Coerce non-array leaves (lists/scalars) so .shape/.dtype are defined
        # everywhere below; array leaves (numpy or jax) pass through without a
        # host round-trip. Build a local dict rather than writing back into
        # the caller's (callers reuse step_data across iterations).
        data = {
            k: v if isinstance(v, (np.ndarray, jax.Array)) else np.asarray(v)
            for k, v in data.items()
        }
        steps = next(iter(data.values())).shape[0]
        if steps != 1:
            raise ValueError(
                f"DeviceSequentialReplayBuffer.add expects one step at a time, got {steps}"
            )
        envs = np.arange(self._n_envs) if indices is None else np.asarray(list(indices))
        was_empty = self.empty
        # the whole-dict single-dispatch scatter requires every add() to carry
        # the full key set (partial writes would need per-key dispatches back)
        if not was_empty and data.keys() != self._buf.keys():
            raise KeyError(
                f"add() must provide exactly the buffer's key set {sorted(self._buf)}; "
                f"got {sorted(data)}"
            )
        for k, v in data.items():
            if k not in self._buf:
                # only reachable on the very first add (the key-set equality
                # check above rejects any mismatch once initialized)
                # Dtype policy: device storage is at most 32-bit.  JAX's x64
                # mode is off framework-wide, so 64-bit leaves would silently
                # narrow inside jnp.zeros; make the narrowing explicit and loud
                # (checkpoint round trips toggling buffer.device would
                # otherwise change dtypes without a trace — ADVICE r2).
                dtype = np.dtype(v.dtype)
                if dtype.itemsize == 8 and dtype.kind in "fiu":
                    narrowed = np.dtype(f"{dtype.kind}4")
                    warnings.warn(
                        f"DeviceSequentialReplayBuffer: key '{k}' arrives as {dtype} but device "
                        f"storage is 32-bit; storing as {narrowed}",
                        UserWarning,
                        stacklevel=2,
                    )
                    dtype = narrowed
                self._buf[k] = self._to_storage(
                    jnp.zeros((self._buffer_size, self._n_envs, *v.shape[2:]), dtype=dtype)
                )
        rows = jnp.asarray(self._pos[envs] % self._buffer_size, jnp.int32)
        envs_dev = jnp.asarray(envs, jnp.int32)
        # device leaves (e.g. the player's actions) stay on device: the slice
        # is a dispatched op, never a blocking fetch — this is what lets the
        # hot loop add the current step *before* fetching the action values
        # (see dreamer_v3.py's pipelined iteration).  Host leaves ride along
        # as KB-sized transfer operands of the same single dispatch.
        step = {k: v[0] for k, v in data.items()}
        self._buf = _scatter_all(self._buf, step, rows, envs_dev)
        self._pos[envs] = (self._pos[envs] + 1) % self._buffer_size
        self._filled[envs] = np.minimum(self._filled[envs] + 1, self._buffer_size)
        self._added[envs] += 1

    def mark_last_truncated(self, env_idx: int) -> None:
        """Flag the most recent stored step of one env as truncated (the
        RestartOnException surgery, reference dreamer_v3.py:656-664)."""
        last = int((self._pos[env_idx] - 1) % self._buffer_size)
        self._buf["terminated"] = self._buf["terminated"].at[last, env_idx].set(0.0)
        self._buf["truncated"] = self._buf["truncated"].at[last, env_idx].set(1.0)
        if "is_first" in self._buf:
            self._buf["is_first"] = self._buf["is_first"].at[last, env_idx].set(0.0)

    # -- read path -----------------------------------------------------------
    def _draw_env_idx(self, n: int, seq_len: int) -> np.ndarray:
        valid_envs = np.nonzero(self._filled >= seq_len)[0]
        if self._mesh is None:
            if valid_envs.size == 0:
                raise ValueError(
                    f"Cannot sample a sequence of length {seq_len}. Data added so far: {self._filled.tolist()}"
                )
            return valid_envs[self._rng.integers(0, valid_envs.size, size=(n,))]
        # env-sharded: each device's batch block draws only from its own env
        # block (block-stratified rather than iid-uniform over all envs), so
        # the shard_map gather stays fully local
        if n % self._world != 0:
            raise ValueError(f"batch_size ({n}) must be divisible by the mesh size ({self._world})")
        n_local = self._n_envs // self._world
        b_local = n // self._world
        blocks = []
        for d in range(self._world):
            local_valid = valid_envs[(valid_envs >= d * n_local) & (valid_envs < (d + 1) * n_local)]
            if local_valid.size == 0:
                raise ValueError(
                    f"Cannot sample a sequence of length {seq_len} from device {d}'s env block. "
                    f"Data added so far: {self._filled.tolist()}"
                )
            blocks.append(local_valid[self._rng.integers(0, local_valid.size, size=(b_local,))])
        return np.concatenate(blocks)

    def _draw(self, n: int, seq_len: int):
        """(starts, env_idx) numpy arrays for ``n`` valid sequence windows."""
        if self.empty or self._filled.max(initial=0) == 0:
            raise ValueError("No sample has been added to the buffer. Call 'add' first")
        if seq_len > self._buffer_size:
            raise ValueError(
                f"The sequence length ({seq_len}) is greater than the buffer size ({self._buffer_size})"
            )
        env_idx = self._draw_env_idx(n, seq_len)
        filled = self._filled[env_idx]
        pos = self._pos[env_idx]
        # age of the window start, uniform over each env's valid range
        start_ages = seq_len - 1 + (
            self._rng.random(n) * (filled - seq_len + 1)
        ).astype(np.int64)
        starts = np.where(
            filled >= self._buffer_size,
            (pos - 1 - start_ages) % self._buffer_size,
            filled - 1 - start_ages,
        )
        return starts, env_idx

    def sample(self, batch_size: int, sequence_length: int = 1, n_samples: int = 1, **_: Any):
        """A LIST of ``n_samples`` device batches, each a dict of
        ``[T, batch_size, ...]`` arrays already resident in HBM."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        gather = None
        if self._mesh is not None:
            if sequence_length not in self._gather_cache:
                self._gather_cache[sequence_length] = _make_sharded_gather(self._mesh, sequence_length)
            gather = self._gather_cache[sequence_length]
        out = []
        for _ in range(n_samples):
            starts, env_idx = self._draw(batch_size, sequence_length)
            if self._mesh is not None:
                # local env index within each device's block + sharded inputs
                n_local = self._n_envs // self._world
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                idx_sharding = NamedSharding(self._mesh, P("data"))
                starts_dev = jax.device_put(jnp.asarray(starts, jnp.int32), idx_sharding)
                env_local = jax.device_put(jnp.asarray(env_idx % n_local, jnp.int32), idx_sharding)
                out.append(gather(self._buf, starts_dev, env_local))
            else:
                out.append(
                    _gather_all(
                        self._buf,
                        jnp.asarray(starts, jnp.int32),
                        jnp.asarray(env_idx, jnp.int32),
                        sequence_length,
                    )
                )
        return out

    # -- footprint (diagnostics memory telemetry) ------------------------------
    def footprint(self) -> Dict[str, int]:
        """HBM-resident storage bytes (``device_bytes`` is the GLOBAL total;
        env-sharded storage splits it evenly across the mesh's devices)."""
        total = sum(int(v.nbytes) for v in self._buf.values())
        out = {"device_bytes": total}
        if self.dataset_disk_bytes:
            out["dataset_disk"] = int(self.dataset_disk_bytes)
        return out

    # -- checkpointing ---------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        # np.asarray over a jax.Array is a read-only view; copy so checkpoint
        # surgery (truncated-flag patching) can write into the snapshot
        return {
            "buffer": {k: np.array(v) for k, v in self._buf.items()},
            "pos": self._pos.copy(),
            "filled": self._filled.copy(),
            "added": self._added.copy(),
        }

    def _to_storage(self, arr) -> jax.Array:
        storage = jnp.asarray(arr)
        if self._mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            storage = jax.device_put(storage, NamedSharding(self._mesh, P(None, "data")))
        return storage

    def load_state_dict(self, state: Dict[str, Any]) -> "DeviceSequentialReplayBuffer":
        if "buffers" in state:
            # host EnvIndependentReplayBuffer format (one sub-state per env):
            # stack the per-env [cap, 1, ...] storages along the env axis so
            # checkpoints survive toggling buffer.device between runs
            subs = state["buffers"]
            keys = subs[0]["buffer"].keys()
            self._buf = {
                k: self._to_storage(np.concatenate([np.asarray(s["buffer"][k]) for s in subs], axis=1))
                for k in keys
            }
            self._pos = np.asarray([s["pos"] for s in subs], dtype=np.int64)
            self._filled = np.asarray(
                [self._buffer_size if s["full"] else s["pos"] for s in subs], dtype=np.int64
            )
            self._added = np.asarray(
                [s.get("added", self._buffer_size if s["full"] else s["pos"]) for s in subs],
                dtype=np.int64,
            )
            return self
        self._buf = {k: self._to_storage(v) for k, v in state["buffer"].items()}
        self._pos = np.asarray(state["pos"], dtype=np.int64).copy()
        self._filled = np.asarray(state["filled"], dtype=np.int64).copy()
        # checkpoints predating the export subsystem: the stored window is
        # the best lower bound (mirrors ReplayBuffer.load_state_dict)
        self._added = np.asarray(state.get("added", self._filled), dtype=np.int64).copy()
        return self
