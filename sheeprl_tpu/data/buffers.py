"""Replay buffers: host-side numpy storage + device staging.

Behavioral equivalents of the four reference buffer classes
(/root/reference/sheeprl/data/buffers.py:20-1155) with the same shape
convention ``[time, n_envs, ...]`` and sampling semantics.  This layer is
host-side by design (SURVEY §2.2): dynamic shapes (episode boundaries, ragged
episodes, wrap-around) stay in numpy where they are free, and only the sampled
minibatch crosses to HBM — ``sample_tensors`` returns ``jax.Array``s placed on
a device or `NamedSharding` in one ``device_put`` per key.
"""

from __future__ import annotations

import os
import shutil
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from sheeprl_tpu.data.memmap import MemmapArray

_ALLOWED_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")


def to_device(samples: Dict[str, np.ndarray], device: Any = None, dtype: Any = None) -> Dict[str, Any]:
    """Stage a dict of host arrays into device memory (optionally sharded).

    ``device`` may be a `jax.Device`, a `jax.sharding.Sharding`, or None
    (commit to the default device).  Floating arrays are cast to ``dtype``.
    """
    import jax
    import jax.numpy as jnp

    out: Dict[str, Any] = {}
    for k, v in samples.items():
        arr = np.asarray(v)
        if dtype is not None and np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(dtype)
        out[k] = jax.device_put(arr, device) if device is not None else jnp.asarray(arr)
    return out


def _validate_add_data(data: Dict[str, np.ndarray]) -> None:
    if not isinstance(data, dict):
        raise ValueError(f"'data' must be a dictionary containing Numpy arrays, got type '{type(data)}'")
    for k, v in data.items():
        if not isinstance(v, np.ndarray):
            raise ValueError(f"'data' must contain Numpy arrays. Key '{k}' has type '{type(v)}'")
    shapes = {k: v.shape[:2] for k, v in data.items()}
    for k, v in data.items():
        if v.ndim < 2:
            raise RuntimeError(f"'data' must have at least 2 dims [time, n_envs, ...]; '{k}' has shape {v.shape}")
    if len(set(shapes.values())) > 1:
        raise RuntimeError(f"Every array in 'data' must agree in the first 2 dims, got {shapes}")


class ReplayBuffer:
    """Circular uniform-sampling buffer over dict-of-ndarray storage
    (reference buffers.py:20-360)."""

    batch_axis: int = 1

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        if self._memmap:
            if memmap_mode not in _ALLOWED_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_ALLOWED_MODES}")
            if memmap_dir is None:
                raise ValueError("memmap=True requires a 'memmap_dir'")
            self._memmap_dir = Path(memmap_dir)
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._buf: Dict[str, np.ndarray | MemmapArray] = {}
        self._pos = 0
        self._full = False
        # monotone count of steps ever added — the logical-stream clock the
        # incremental dataset export (offline/export.py) cursors against
        self._added = 0
        # bytes of exported dataset shards attributed to this buffer
        # (offline/export.py::note_dataset_bytes); footprint() reports them
        self.dataset_disk_bytes = 0
        self._rng: np.random.Generator = np.random.default_rng()

    # -- properties ---------------------------------------------------------
    @property
    def buffer(self) -> Dict[str, np.ndarray]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> bool:
        return self._full

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> bool:
        return len(self._buf) == 0

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def added_steps(self) -> int:
        """Steps ever added (monotone; ``added_steps - buffer_size`` is the
        oldest logical step still in the ring once full)."""
        return self._added

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    def flush(self) -> None:
        """Force memmap-backed storage to disk — called before any export or
        snapshot read so the reader never sees pages the OS still holds."""
        for v in self._buf.values():
            if isinstance(v, MemmapArray):
                v.flush()

    # -- write path ---------------------------------------------------------
    def _allocate(self, key: str, per_step_shape: tuple, dtype: Any) -> None:
        full_shape = (self._buffer_size, self._n_envs, *per_step_shape)
        if self._memmap:
            self._buf[key] = MemmapArray(
                shape=full_shape,
                dtype=dtype,
                mode=self._memmap_mode,
                filename=Path(self._memmap_dir) / f"{key}.memmap",
            )
        else:
            self._buf[key] = np.empty(shape=full_shape, dtype=dtype)

    def add(self, data: "ReplayBuffer" | Dict[str, np.ndarray], validate_args: bool = False) -> None:
        """Insert ``[T, n_envs, ...]`` rows at the write head (behavioral
        parity with reference buffers.py:138-221).

        The circular write is two contiguous slice assignments: the span from
        the head to the end of storage, then the wrapped remainder from slot 0.
        An add longer than the whole buffer keeps only its most recent
        ``buffer_size`` rows (the older ones would be overwritten within the
        same call anyway).
        """
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            _validate_add_data(data)
        steps = next(iter(data.values())).shape[0]
        if steps > self._buffer_size:
            data = {k: v[steps - self._buffer_size :] for k, v in data.items()}
            steps = self._buffer_size
        head = self._pos
        tail_span = min(steps, self._buffer_size - head)
        was_empty = self.empty
        for k, v in data.items():
            if k not in self._buf:
                if not was_empty:
                    # a key appearing after the first add would leave every
                    # earlier row uninitialized — fail loudly instead
                    raise KeyError(
                        f"Unknown buffer key '{k}'; the buffer was initialized with {sorted(self._buf)}"
                    )
                self._allocate(k, v.shape[2:], v.dtype)
            storage = self._buf[k]
            storage[head : head + tail_span] = v[:tail_span]
            if steps > tail_span:  # wrapped remainder
                storage[: steps - tail_span] = v[tail_span:]
        if head + steps >= self._buffer_size:
            self._full = True
        self._pos = (head + steps) % self._buffer_size
        self._added += steps

    # -- read path ----------------------------------------------------------
    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Uniform sample of ``[n_samples, batch_size, ...]``, avoiding the
        write head when next-obs are requested (reference buffers.py:223-268)."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer. Call 'add' first")
        draw = batch_size * n_samples
        if self._full:
            if sample_next_obs:
                # every slot but the newest is valid (the newest slot's
                # successor is the oldest entry — a data discontinuity);
                # draw an *age* in [1, size) and map it back to a slot
                ages = self._rng.integers(1, self._buffer_size, size=(draw,), dtype=np.intp)
                batch_idxes = (self._pos - 1 - ages) % self._buffer_size
            else:
                batch_idxes = self._rng.integers(0, self._buffer_size, size=(draw,), dtype=np.intp)
        else:
            stored = self._pos - 1 if sample_next_obs else self._pos
            if stored == 0:
                raise RuntimeError(
                    "Cannot sample next observations with a single stored step; add at least two steps"
                )
            batch_idxes = self._rng.integers(0, stored, size=(draw,), dtype=np.intp)
        samples = self._get_samples(batch_idxes, sample_next_obs=sample_next_obs, clone=clone)
        return {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in samples.items()}

    def _get_samples(
        self, batch_idxes: np.ndarray, sample_next_obs: bool = False, clone: bool = False
    ) -> Dict[str, np.ndarray]:
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first")
        env_idxes = self._rng.integers(0, self._n_envs, size=(len(batch_idxes),), dtype=np.intp)
        flat_idxes = batch_idxes * self._n_envs + env_idxes
        if sample_next_obs:
            flat_next = ((batch_idxes + 1) % self._buffer_size) * self._n_envs + env_idxes
        samples: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            flat_v = np.reshape(np.asarray(v), (-1, *v.shape[2:]))
            samples[k] = np.take(flat_v, flat_idxes, axis=0)
            if clone:
                samples[k] = samples[k].copy()
            if sample_next_obs and k in self._obs_keys:
                samples[f"next_{k}"] = np.take(flat_v, flat_next, axis=0)
                if clone:
                    samples[f"next_{k}"] = samples[f"next_{k}"].copy()
        return samples

    def sample_tensors(
        self,
        batch_size: int,
        clone: bool = False,
        sample_next_obs: bool = False,
        dtype: Any = None,
        device: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """Sample and stage to device (the reference's torch conversion,
        buffers.py:291-326, becomes a single host→HBM ``device_put``)."""
        samples = self.sample(batch_size=batch_size, sample_next_obs=sample_next_obs, clone=clone, **kwargs)
        return to_device(samples, device=device, dtype=dtype)

    # -- dict access ---------------------------------------------------------
    def __getitem__(self, key: str) -> np.ndarray | MemmapArray:
        if not isinstance(key, str):
            raise TypeError("'key' must be a string")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first")
        return self._buf[key]

    def __setitem__(self, key: str, value: np.ndarray | MemmapArray) -> None:
        if not isinstance(value, (np.ndarray, MemmapArray)):
            raise ValueError(f"The value must be an np.ndarray or MemmapArray, got {type(value)}")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first")
        if tuple(value.shape[:2]) != (self._buffer_size, self._n_envs):
            raise RuntimeError(
                f"'value' must have shape [buffer_size, n_envs, ...]; got {value.shape} "
                f"vs ({self._buffer_size}, {self._n_envs})"
            )
        if self._memmap:
            filename = value.filename if isinstance(value, MemmapArray) else Path(self._memmap_dir) / f"{key}.memmap"
            self._buf[key] = MemmapArray.from_array(value, filename=filename, mode=self._memmap_mode)
        else:
            self._buf[key] = np.copy(value.array if isinstance(value, MemmapArray) else value)

    # -- footprint (diagnostics memory telemetry) -----------------------------
    def footprint(self) -> Dict[str, int]:
        """Allocated storage bytes by residence: memmap-backed keys count as
        ``disk_bytes`` (the OS pages them; they do not pin RAM), plain numpy
        keys as ``host_bytes``; exported dataset shards (``buffer.export`` /
        ``sheeprl-export``) as ``dataset_disk``.  Journaled per metric
        interval when the loop registered the buffer with
        ``diag.track_buffer``."""
        host = 0
        disk = 0
        for v in self._buf.values():
            if isinstance(v, MemmapArray):
                disk += v.nbytes
            else:
                host += int(v.nbytes)
        out = {"host_bytes": host, "disk_bytes": disk}
        if self.dataset_disk_bytes:
            out["dataset_disk"] = int(self.dataset_disk_bytes)
        return out

    # -- checkpointing --------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {
            "buffer": {k: np.asarray(v).copy() for k, v in self._buf.items()},
            "pos": self._pos,
            "full": self._full,
            "added": self._added,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "ReplayBuffer":
        for k, v in state["buffer"].items():
            if self._memmap:
                self._buf[k] = MemmapArray.from_array(
                    v, filename=Path(self._memmap_dir) / f"{k}.memmap", mode=self._memmap_mode
                )
            else:
                self._buf[k] = v.copy()
        self._pos = state["pos"]
        self._full = state["full"]
        # checkpoints predating the export subsystem carry no add counter:
        # the stored span is the best lower bound
        self._added = int(state.get("added", self._buffer_size if self._full else self._pos))
        return self


class SequentialReplayBuffer(ReplayBuffer):
    """Samples fixed-length contiguous sequences ignoring episode bounds —
    Dreamer's data source (reference buffers.py:363-526).  Returns
    ``[n_samples, sequence_length, batch_size, ...]``."""

    batch_axis: int = 2

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        batch_dim = batch_size * n_samples
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer. Call 'add' first")
        if not self._full and self._pos - sequence_length + 1 < 1:
            raise ValueError(f"Cannot sample a sequence of length {sequence_length}. Data added so far: {self._pos}")
        if self._full and sequence_length > self._buffer_size:
            raise ValueError(
                f"The sequence length ({sequence_length}) is greater than the buffer size ({self._buffer_size})"
            )
        if self._full:
            # a window is valid iff it lies inside the logical stream (it may
            # wrap physically, never across the write head).  In age space
            # (newest stored row = age 0) the window's *start* age ranges over
            # [sequence_length - 1, size) — draw there and map back to slots.
            start_ages = self._rng.integers(
                sequence_length - 1, self._buffer_size, size=(batch_dim,), dtype=np.intp
            )
            start_idxes = (self._pos - 1 - start_ages) % self._buffer_size
        else:
            start_idxes = self._rng.integers(0, self._pos - sequence_length + 1, size=(batch_dim,), dtype=np.intp)
        offsets = np.arange(sequence_length, dtype=np.intp)[None, :]
        idxes = (start_idxes[:, None] + offsets) % self._buffer_size
        return self._get_seq_samples(idxes, batch_size, n_samples, sequence_length, sample_next_obs, clone)

    def _get_seq_samples(
        self,
        batch_idxes: np.ndarray,
        batch_size: int,
        n_samples: int,
        sequence_length: int,
        sample_next_obs: bool,
        clone: bool,
    ) -> Dict[str, np.ndarray]:
        flat_batch_idxes = np.ravel(batch_idxes)
        n_seqs = batch_size * n_samples
        if self._n_envs == 1:
            env_idxes = np.zeros((n_seqs * sequence_length,), dtype=np.intp)
        else:
            env_idxes = self._rng.integers(0, self._n_envs, size=(n_seqs,), dtype=np.intp)
            env_idxes = np.ravel(np.tile(env_idxes.reshape(-1, 1), (1, sequence_length)))
        flat_idxes = flat_batch_idxes * self._n_envs + env_idxes
        samples: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            flat_v = np.reshape(np.asarray(v), (-1, *v.shape[2:]))
            taken = np.take(flat_v, flat_idxes, axis=0)
            batched = np.reshape(taken, (n_samples, batch_size, sequence_length) + taken.shape[1:])
            samples[k] = np.swapaxes(batched, 1, 2)
            if clone:
                samples[k] = samples[k].copy()
            if sample_next_obs:
                next_taken = np.asarray(v)[(flat_batch_idxes + 1) % self._buffer_size, env_idxes]
                next_batched = np.reshape(next_taken, (n_samples, batch_size, sequence_length) + next_taken.shape[1:])
                samples[f"next_{k}"] = np.swapaxes(next_batched, 1, 2)
                if clone:
                    samples[f"next_{k}"] = samples[f"next_{k}"].copy()
        return samples


class EnvIndependentReplayBuffer:
    """One sub-buffer per environment, needed when async envs finish episodes
    at different times (reference buffers.py:529-743)."""

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
        buffer_cls: Type[ReplayBuffer] = ReplayBuffer,
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap:
            if memmap_mode not in _ALLOWED_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_ALLOWED_MODES}")
            if memmap_dir is None:
                raise ValueError("memmap=True requires a 'memmap_dir'")
            memmap_dir = Path(memmap_dir)
            memmap_dir.mkdir(parents=True, exist_ok=True)
        self._buf: List[ReplayBuffer] = [
            buffer_cls(
                buffer_size=buffer_size,
                n_envs=1,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=memmap_dir / f"env_{i}" if memmap else None,
                memmap_mode=memmap_mode,
                **kwargs,
            )
            for i in range(n_envs)
        ]
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._rng: np.random.Generator = np.random.default_rng()
        self._concat_along_axis = buffer_cls.batch_axis

    @property
    def buffer(self) -> Sequence[ReplayBuffer]:
        return tuple(self._buf)

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> Sequence[bool]:
        return tuple(b.full for b in self._buf)

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> Sequence[bool]:
        return tuple(b.empty for b in self._buf)

    @property
    def is_memmap(self) -> Sequence[bool]:
        return tuple(b.is_memmap for b in self._buf)

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)
        for i, b in enumerate(self._buf):
            b.seed(None if seed is None else seed + i)

    def add(
        self,
        data: "ReplayBuffer" | Dict[str, np.ndarray],
        indices: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if indices is None:
            indices = tuple(range(self._n_envs))
        elif len(indices) != next(iter(data.values())).shape[1]:
            raise ValueError(
                f"The length of 'indices' ({len(indices)}) must equal the env dim of 'data' "
                f"({next(iter(data.values())).shape[1]})"
            )
        if validate_args:
            _validate_add_data(data)
        # Lockstep fast path (the hot-loop shape: every env adds one step,
        # every sub-buffer at the same write head, no wrap): write each key's
        # whole [T, N, ...] slab column-by-column straight into the
        # sub-buffer storages.  Skips the per-env dict building + per-env
        # ``add()`` head bookkeeping — the difference between O(N) Python
        # call machinery and N plain slice assignments per key at 64+ envs.
        # The wrap/first-add/misaligned cases keep the general path below.
        steps = next(iter(data.values())).shape[0]
        bufs = [self._buf[env_idx] for env_idx in indices]
        first = bufs[0]
        head = first._pos
        if (
            not first.empty
            and head + steps <= self._buffer_size
            and first._buf.keys() == data.keys()
            and len(set(indices)) == len(bufs)
            and all(not b.empty and b._pos == head for b in bufs)
        ):
            for k, v in data.items():
                for data_idx, b in enumerate(bufs):
                    b._buf[k][head : head + steps] = v[:, data_idx : data_idx + 1]
            full = head + steps >= self._buffer_size
            pos = (head + steps) % self._buffer_size
            for b in bufs:
                b._full = b._full or full
                b._pos = pos
                b._added += steps
            return
        for data_idx, env_idx in enumerate(indices):
            env_data = {k: v[:, data_idx : data_idx + 1] for k, v in data.items()}
            # already validated once on the whole slab above
            self._buf[env_idx].add(env_data, validate_args=False)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        bs_per_buf = np.bincount(self._rng.integers(0, self._n_envs, (batch_size,)), minlength=self._n_envs)
        per_buf = [
            b.sample(batch_size=bs, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs)
            for b, bs in zip(self._buf, bs_per_buf)
            if bs > 0
        ]
        samples: Dict[str, np.ndarray] = {}
        for k in per_buf[0].keys():
            samples[k] = np.concatenate([s[k] for s in per_buf], axis=self._concat_along_axis)
        return samples

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        dtype: Any = None,
        device: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(
            batch_size=batch_size, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs
        )
        return to_device(samples, device=device, dtype=dtype)

    def flush(self) -> None:
        for b in self._buf:
            b.flush()

    def footprint(self) -> Dict[str, int]:
        out = {"host_bytes": 0, "disk_bytes": 0}
        for b in self._buf:
            for kind, size in b.footprint().items():
                out[kind] = out.get(kind, 0) + size
        if getattr(self, "dataset_disk_bytes", 0):
            out["dataset_disk"] = out.get("dataset_disk", 0) + int(self.dataset_disk_bytes)
        return out

    def state_dict(self) -> Dict[str, Any]:
        return {"buffers": [b.state_dict() for b in self._buf]}

    def load_state_dict(self, state: Dict[str, Any]) -> "EnvIndependentReplayBuffer":
        if "filled" in state:
            # DeviceSequentialReplayBuffer format: split the stacked storage
            # back into per-env sub-buffers (checkpoints survive toggling
            # buffer.device between runs)
            for e, b in enumerate(self._buf):
                b.load_state_dict(
                    {
                        "buffer": {k: np.asarray(v[:, e : e + 1]) for k, v in state["buffer"].items()},
                        "pos": int(state["pos"][e]),
                        "full": bool(state["filled"][e] >= self._buffer_size),
                    }
                )
            return self
        for b, s in zip(self._buf, state["buffers"]):
            b.load_state_dict(s)
        return self


class EpisodeBuffer:
    """Stores whole episodes; evicts oldest when over capacity
    (reference buffers.py:746-1155)."""

    batch_axis: int = 2

    def __init__(
        self,
        buffer_size: int,
        minimum_episode_length: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        prioritize_ends: bool = False,
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if minimum_episode_length <= 0:
            raise ValueError(f"The sequence length must be greater than zero, got: {minimum_episode_length}")
        if buffer_size < minimum_episode_length:
            raise ValueError(
                f"The sequence length must be lower than the buffer size, got: bs = {buffer_size} "
                f"and sl = {minimum_episode_length}"
            )
        self._buffer_size = buffer_size
        self._minimum_episode_length = minimum_episode_length
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._prioritize_ends = prioritize_ends
        self._open_episodes: List[List[Dict[str, np.ndarray]]] = [[] for _ in range(n_envs)]
        self._cum_lengths: List[int] = []
        self._buf: List[Dict[str, np.ndarray | MemmapArray]] = []
        # monotone per-episode ids (parallel to _buf): the dataset export
        # keys its one-stream-per-episode layout off these, so an evicted
        # episode's stream is never reused
        self._episode_ids: List[int] = []
        self._episodes_saved = 0
        self.dataset_disk_bytes = 0
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        self._rng: np.random.Generator = np.random.default_rng()
        if self._memmap:
            if memmap_mode not in _ALLOWED_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_ALLOWED_MODES}")
            if memmap_dir is None:
                raise ValueError("memmap=True requires a 'memmap_dir'")
            self._memmap_dir = Path(memmap_dir)
            self._memmap_dir.mkdir(parents=True, exist_ok=True)

    @property
    def prioritize_ends(self) -> bool:
        return self._prioritize_ends

    @prioritize_ends.setter
    def prioritize_ends(self, value: bool) -> None:
        self._prioritize_ends = value

    @property
    def buffer(self) -> Sequence[Dict[str, np.ndarray | MemmapArray]]:
        return self._buf

    @property
    def obs_keys(self) -> Sequence[str]:
        return self._obs_keys

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def minimum_episode_length(self) -> int:
        return self._minimum_episode_length

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def full(self) -> bool:
        return self._cum_lengths[-1] + self._minimum_episode_length > self._buffer_size if self._buf else False

    @property
    def episode_ids(self) -> Sequence[int]:
        """Monotone id per stored episode (parallel to :attr:`buffer`)."""
        return tuple(self._episode_ids)

    def __len__(self) -> int:
        return self._cum_lengths[-1] if self._buf else 0

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    def flush(self) -> None:
        """Force memmap-backed episode storage to disk before export reads."""
        for episode in self._buf:
            for v in episode.values():
                if isinstance(v, MemmapArray):
                    v.flush()

    def add(
        self,
        data: "ReplayBuffer" | Dict[str, np.ndarray],
        env_idxes: Sequence[int] | None = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            _validate_add_data(data)
            if "terminated" not in data and "truncated" not in data:
                raise RuntimeError(
                    f"The episode must contain the `terminated` and the `truncated` keys, got: {data.keys()}"
                )
            if env_idxes is not None and (np.array(env_idxes) >= self._n_envs).any():
                raise ValueError(f"Env indices must be in [0, {self._n_envs}), given {env_idxes}")
        if env_idxes is None:
            env_idxes = range(self._n_envs)
        # Vectorized fast path for the overwhelmingly common slab: no episode
        # ends anywhere — ONE done-reduction over the whole [T, N] slab and a
        # per-env view append, instead of a per-env done scan + nonzero.
        if "terminated" in data and "truncated" in data and not np.logical_or(
            data["terminated"], data["truncated"]
        ).any():
            for i, env in enumerate(env_idxes):
                self._open_episodes[env].append({k: v[:, i] for k, v in data.items()})
            return
        for i, env in enumerate(env_idxes):
            env_data = {k: v[:, i] for k, v in data.items()}
            done = np.logical_or(env_data["terminated"], env_data["truncated"])
            episode_ends = done.nonzero()[0].tolist()
            if len(episode_ends) == 0:
                self._open_episodes[env].append(env_data)
                continue
            episode_ends.append(len(done))
            start = 0
            for ep_end_idx in episode_ends:
                stop = ep_end_idx
                episode = {k: env_data[k][start : stop + 1] for k in env_data.keys()}
                if len(np.logical_or(episode["terminated"], episode["truncated"])) > 0:
                    self._open_episodes[env].append(episode)
                start = stop + 1
                should_save = len(self._open_episodes[env]) > 0 and np.logical_or(
                    self._open_episodes[env][-1]["terminated"][-1],
                    self._open_episodes[env][-1]["truncated"][-1],
                )
                if should_save:
                    self._save_episode(self._open_episodes[env])
                    self._open_episodes[env] = []

    def _save_episode(self, episode_chunks: Sequence[Dict[str, np.ndarray]]) -> None:
        if len(episode_chunks) == 0:
            raise RuntimeError("Invalid episode, an empty sequence is given")
        episode = {
            k: np.concatenate([chunk[k] for chunk in episode_chunks], axis=0) for k in episode_chunks[0].keys()
        }
        ends = np.logical_or(episode["terminated"], episode["truncated"])
        ep_len = ends.shape[0]
        if len(ends.nonzero()[0]) != 1 or not ends[-1]:
            raise RuntimeError(f"The episode must contain exactly one done at its end")
        if ep_len < self._minimum_episode_length:
            raise RuntimeError(f"Episode too short (at least {self._minimum_episode_length} steps), got: {ep_len}")
        if ep_len > self._buffer_size:
            raise RuntimeError(f"Episode too long (at most {self._buffer_size} steps), got: {ep_len}")

        if self.full or len(self) + ep_len > self._buffer_size:
            cum_lengths = np.array(self._cum_lengths)
            mask = (len(self) - cum_lengths + ep_len) <= self._buffer_size
            last_to_remove = int(mask.argmax())
            if self._memmap and self._memmap_dir is not None:
                for _ in range(last_to_remove + 1):
                    dirname = os.path.dirname(self._buf[0][next(iter(self._buf[0].keys()))].filename)
                    del self._buf[0]
                    shutil.rmtree(dirname, ignore_errors=True)
            else:
                self._buf = self._buf[last_to_remove + 1 :]
            self._episode_ids = self._episode_ids[last_to_remove + 1 :]
            cum_lengths = cum_lengths[last_to_remove + 1 :] - cum_lengths[last_to_remove]
            self._cum_lengths = cum_lengths.tolist()
        self._cum_lengths.append(len(self) + ep_len)
        episode_to_store = episode
        if self._memmap:
            episode_dir = Path(self._memmap_dir) / f"episode_{uuid.uuid4()}"
            episode_dir.mkdir(parents=True, exist_ok=True)
            episode_to_store = {
                k: MemmapArray.from_array(v, filename=episode_dir / f"{k}.memmap", mode=self._memmap_mode)
                for k, v in episode.items()
            }
        self._buf.append(episode_to_store)
        self._episode_ids.append(self._episodes_saved)
        self._episodes_saved += 1

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0:
            raise ValueError(f"Batch size must be greater than 0, got: {batch_size}")
        if n_samples <= 0:
            raise ValueError(f"The number of samples must be greater than 0, got: {n_samples}")
        ep_lengths = np.array(self._cum_lengths) - np.array([0] + self._cum_lengths[:-1])
        if sample_next_obs:
            valid_mask = ep_lengths > sequence_length
        else:
            valid_mask = ep_lengths >= sequence_length
        valid_episodes = [ep for ep, ok in zip(self._buf, valid_mask) if ok]
        if len(valid_episodes) == 0:
            raise RuntimeError(
                "No valid episodes in the buffer: add at least one episode of length >= " f"{sequence_length}"
            )
        chunk = np.arange(sequence_length, dtype=np.intp).reshape(1, -1)
        nsample_per_eps = np.bincount(
            self._rng.integers(0, len(valid_episodes), (batch_size * n_samples,)), minlength=len(valid_episodes)
        )
        gathered: Dict[str, List[np.ndarray]] = {k: [] for k in valid_episodes[0].keys()}
        if sample_next_obs:
            gathered.update({f"next_{k}": [] for k in self._obs_keys})
        for i, n in enumerate(nsample_per_eps):
            if n == 0:
                continue
            ep = valid_episodes[i]
            ep_len = np.logical_or(np.asarray(ep["terminated"]), np.asarray(ep["truncated"])).shape[0]
            if sample_next_obs:
                ep_len -= 1
            upper = ep_len - sequence_length + 1
            if self._prioritize_ends:
                upper += sequence_length
            start_idxes = np.minimum(
                self._rng.integers(0, upper, size=(n,)).reshape(-1, 1), ep_len - sequence_length
            ).astype(np.intp)
            indices = start_idxes + chunk
            for k in valid_episodes[0].keys():
                arr = np.asarray(ep[k])
                gathered[k].append(
                    np.take(arr, indices.ravel(), axis=0).reshape(n, sequence_length, *arr.shape[1:])
                )
                if sample_next_obs and k in self._obs_keys:
                    gathered[f"next_{k}"].append(np.asarray(ep[k])[indices + 1])
        samples: Dict[str, np.ndarray] = {}
        for k, v in gathered.items():
            if len(v) > 0:
                samples[k] = np.moveaxis(
                    np.concatenate(v, axis=0).reshape(n_samples, batch_size, sequence_length, *v[0].shape[2:]),
                    2,
                    1,
                )
                if clone:
                    samples[k] = samples[k].copy()
        return samples

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        dtype: Any = None,
        device: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(batch_size, sample_next_obs, n_samples, clone, sequence_length)
        return to_device(samples, device=device, dtype=dtype)

    def footprint(self) -> Dict[str, int]:
        """Stored episodes by residence + the still-open per-env episode
        chunks (always host RAM)."""
        host = 0
        disk = 0
        for ep in self._buf:
            for v in ep.values():
                if isinstance(v, MemmapArray):
                    disk += v.nbytes
                else:
                    host += int(np.asarray(v).nbytes)
        for chunks in self._open_episodes:
            for chunk in chunks:
                host += sum(int(np.asarray(v).nbytes) for v in chunk.values())
        out = {"host_bytes": host, "disk_bytes": disk}
        if self.dataset_disk_bytes:
            out["dataset_disk"] = int(self.dataset_disk_bytes)
        return out

    def state_dict(self) -> Dict[str, Any]:
        return {
            "buffer": [{k: np.asarray(v).copy() for k, v in ep.items()} for ep in self._buf],
            "cum_lengths": list(self._cum_lengths),
            "open_episodes": self._open_episodes,
            "episode_ids": list(self._episode_ids),
            "episodes_saved": self._episodes_saved,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "EpisodeBuffer":
        episodes = state["buffer"]
        self._buf = []
        self._cum_lengths = list(state["cum_lengths"])
        self._episode_ids = list(state.get("episode_ids", range(len(episodes))))
        self._episodes_saved = int(state.get("episodes_saved", len(episodes)))
        for ep in episodes:
            if self._memmap:
                episode_dir = Path(self._memmap_dir) / f"episode_{uuid.uuid4()}"
                episode_dir.mkdir(parents=True, exist_ok=True)
                self._buf.append(
                    {
                        k: MemmapArray.from_array(v, filename=episode_dir / f"{k}.memmap", mode=self._memmap_mode)
                        for k, v in ep.items()
                    }
                )
            else:
                self._buf.append({k: v.copy() for k, v in ep.items()})
        self._open_episodes = state.get("open_episodes", [[] for _ in range(self._n_envs)])
        return self
