"""Replay-buffer selection shared by the Dreamer-family training loops
(dreamer_v1/dreamer_v2's own mains and the shared ``_dreamer_main``).

Centralizes the ``buffer.device`` decision — HBM-resident ring
(``device_buffer.DeviceSequentialReplayBuffer``) vs host
``EnvIndependentReplayBuffer``/``EpisodeBuffer`` — including the loud
fallbacks when the device path cannot be used.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional, Sequence, Tuple

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, SequentialReplayBuffer


def make_dreamer_replay_buffer(
    cfg,
    world_size: int,
    num_envs: int,
    obs_keys: Sequence[str],
    log_dir: str,
    buffer_size: int,
    buffer_type: str = "sequential",
    minimum_episode_length: Optional[int] = None,
    mesh=None,
) -> Tuple[object, bool]:
    """Returns ``(rb, device_resident)``.

    ``buffer.device=True`` selects the HBM-resident ring when eligible
    (sequential sampling; multi-device needs a ``mesh`` with
    ``num_envs % world_size == 0`` — the ring is then env-sharded over the
    data axis).  Ineligible combinations fall back to the host buffers with a
    warning so the performance-critical option is never dropped silently.
    """
    want_device = bool(cfg.buffer.get("device", False))
    if want_device and world_size > 1 and (mesh is None or num_envs % world_size != 0):
        warnings.warn(
            f"buffer.device=True with {world_size} devices needs the mesh and "
            f"env.num_envs ({num_envs}) divisible by the device count; falling back to the host buffer"
        )
        want_device = False
    if want_device and buffer_type != "sequential":
        warnings.warn(
            f"buffer.device=True requires sequential sampling, got buffer.type={buffer_type!r}; "
            "falling back to the host buffer"
        )
        want_device = False
    if want_device:
        from sheeprl_tpu.data.device_buffer import DeviceSequentialReplayBuffer

        # the constructor ignores size-1 meshes, so pass it unconditionally
        return (
            DeviceSequentialReplayBuffer(
                buffer_size, n_envs=num_envs, obs_keys=tuple(obs_keys), mesh=mesh
            ),
            True,
        )
    if buffer_type == "sequential":
        rb = EnvIndependentReplayBuffer(
            buffer_size,
            n_envs=num_envs,
            obs_keys=tuple(obs_keys),
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", "rank_0"),
            buffer_cls=SequentialReplayBuffer,
        )
    elif buffer_type == "episode":
        if minimum_episode_length is None:
            raise ValueError("buffer_type='episode' requires minimum_episode_length")
        rb = EpisodeBuffer(
            buffer_size,
            minimum_episode_length=minimum_episode_length,
            n_envs=num_envs,
            obs_keys=tuple(obs_keys),
            prioritize_ends=cfg.buffer.prioritize_ends,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", "rank_0"),
        )
    else:
        raise ValueError(f"Unrecognized buffer type: must be one of `sequential` or `episode`: {buffer_type}")
    return rb, False
