"""Durable replay datasets: sharded on-disk experience + a streaming loader.

The replay buffers in :mod:`sheeprl_tpu.data.buffers` are scratch space — a
run's collected experience dies with its memmap directory.  This module is
the durable half of the offline-RL subsystem (howto/offline_rl.md): an
RLDS-style *dataset* of experience shards that any later run (or the
``sheeprl-export`` converter over a finished run dir) can produce, and the
``algo.offline`` training mode can stream batches from without an env loop.

Layout (one directory per dataset)::

    dataset/
      dataset.json                      # format version + free-form run meta
      shard-00000-0000000000.npz        # stream 0, logical steps [0, T0)
      shard-00000-0000000000.npz.manifest.json
      shard-00001-0000000000.npz        # stream 1, ...
      ...

A **stream** is one ordered sequence of transitions: one per environment for
the step-buffer classes (their sub-buffers desync on episode-end bookkeeping
rows, so streams cannot share a time axis), one per stored episode for
:class:`~sheeprl_tpu.data.buffers.EpisodeBuffer`.  Every shard holds a
contiguous ``[T, ...]`` slice of its stream per key and carries a manifest
sidecar reusing the checkpoint-manifest pattern
(:mod:`sheeprl_tpu.resilience.manifest`): content sha256 + byte size, the
logical step range, per-key shapes/dtypes and the code fingerprint.  Opening
a dataset verifies every shard and *skips* torn/corrupt ones exactly like
resume selection skips corrupt checkpoints — each skip is a
``dataset_shard_skipped`` record the caller journals, never a crash.

:class:`OfflineDataset` then serves batches: deterministic seeded windowed
shuffles (same seed ⇒ bit-identical batch sequence, prefetch on or off),
flat transition batches for the SAC family and contiguous ``[T, B, ...]``
sequence windows (segment- and, optionally, episode-boundary honoring,
``rssm_*`` stored-state keys included) for the Dreamer family, with an
optional background host-prefetch thread feeding the existing
``device_put`` staging path.
"""

from __future__ import annotations

import json
import os
import queue
import re
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

DATASET_META_NAME = "dataset.json"
DATASET_FORMAT = 1
SHARD_MANIFEST_SUFFIX = ".manifest.json"

_SHARD_RE = re.compile(r"^shard-(\d+)-(\d+)\.npz$")


def shard_name(stream: int, start: int) -> str:
    return f"shard-{int(stream):05d}-{int(start):010d}.npz"


def shard_manifest_path(shard_path: str) -> str:
    return str(shard_path) + SHARD_MANIFEST_SUFFIX


def _key_spec(arrays: Mapping[str, np.ndarray]) -> Dict[str, List[Any]]:
    """``{key: [per-step shape, dtype]}`` — the manifest's structural record
    (the dataset-side analogue of ``resilience.manifest.tree_spec``)."""
    return {k: [list(v.shape[1:]), str(v.dtype)] for k, v in arrays.items()}


def write_shard(root: str, stream: int, start: int, arrays: Mapping[str, np.ndarray]) -> Dict[str, Any]:
    """Write one ``[T, ...]``-per-key shard + manifest sidecar (both atomic
    tmp+rename; the shard lands first, so a crash can only leave a shard
    *without* a manifest — which open-time verification then skips, exactly
    like a legacy/torn checkpoint).  Returns the manifest entry."""
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    if not arrays:
        raise ValueError("cannot write an empty shard")
    rows = {k: v.shape[0] for k, v in arrays.items()}
    if len(set(rows.values())) != 1:
        raise ValueError(f"every shard key must agree on the time axis, got {rows}")
    n_rows = next(iter(rows.values()))
    if n_rows <= 0:
        raise ValueError("cannot write a zero-row shard")
    from sheeprl_tpu.resilience.manifest import _code_fingerprint, _file_digest

    os.makedirs(root, exist_ok=True)
    path = os.path.join(str(root), shard_name(stream, start))
    tmp = path + ".tmp"
    # savez appends ".npz" to plain string paths — hand it a file object so
    # the tmp name is exactly what os.replace sees
    with open(tmp, "wb") as fp:
        np.savez(fp, **arrays)
        fp.flush()
        try:
            os.fsync(fp.fileno())
        except OSError:  # pragma: no cover - exotic filesystems
            pass
    os.replace(tmp, path)
    entry: Dict[str, Any] = {
        "format": DATASET_FORMAT,
        "stream": int(stream),
        "start": int(start),
        "stop": int(start) + int(n_rows),
        "rows": int(n_rows),
        "bytes": os.path.getsize(path),
        "sha256": _file_digest(path),
        "keys": _key_spec(arrays),
        "fingerprint": _code_fingerprint(),
        "written_t": round(time.time(), 3),
    }
    man_path = shard_manifest_path(path)
    man_tmp = man_path + ".tmp"
    with open(man_tmp, "w", encoding="utf-8") as fp:
        json.dump(entry, fp)
        fp.flush()
        os.fsync(fp.fileno())
    os.replace(man_tmp, man_path)
    return entry


def read_shard_manifest(shard_path: str) -> Optional[Dict[str, Any]]:
    path = shard_manifest_path(shard_path)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fp:
            entry = json.load(fp)
        return entry if isinstance(entry, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def verify_shard(shard_path: str, deep: bool = True) -> Tuple[bool, str]:
    """``(ok, reason)`` for one shard file — the checkpoint verification
    contract (every failure mode is a reason string, never an exception):
    ``no_manifest`` (torn write), ``size_mismatch`` (truncated),
    ``digest_mismatch`` (corrupt, deep only), ``verified``."""
    shard_path = str(shard_path)
    if not os.path.isfile(shard_path):
        return False, "missing"
    size = os.path.getsize(shard_path)
    if size == 0:
        return False, "empty"
    entry = read_shard_manifest(shard_path)
    if entry is None:
        return False, "no_manifest"
    if entry.get("bytes") != size:
        return False, "size_mismatch"
    if deep:
        from sheeprl_tpu.resilience.manifest import _file_digest

        if entry.get("sha256") != _file_digest(shard_path):
            return False, "digest_mismatch"
    return True, "verified"


def write_dataset_meta(root: str, meta: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
    """Write (or merge-update) the dataset's top-level ``dataset.json``."""
    os.makedirs(str(root), exist_ok=True)
    path = os.path.join(str(root), DATASET_META_NAME)
    entry: Dict[str, Any] = {"format": DATASET_FORMAT, "created_t": round(time.time(), 3), "meta": {}}
    existing = read_dataset_meta(root)
    if existing is not None:
        entry.update(existing)
    if meta:
        merged = dict(entry.get("meta") or {})
        merged.update({k: v for k, v in meta.items() if v is not None})
        entry["meta"] = merged
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(entry, fp, indent=1)
        fp.flush()
        os.fsync(fp.fileno())
    os.replace(tmp, path)
    return entry


def read_dataset_meta(root: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(str(root), DATASET_META_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fp:
            entry = json.load(fp)
        return entry if isinstance(entry, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def discover_shards(
    root: str, deep: bool = True
) -> Tuple[List[Dict[str, Any]], List[Dict[str, str]]]:
    """All verified shard manifests under ``root`` (sorted by stream then
    start) plus a ``{path, reason}`` skip record per rejected shard — the
    dataset-side ``newest_verified_checkpoint`` contract: torn/corrupt data
    is skipped and reported, never crashed on."""
    good: List[Dict[str, Any]] = []
    skipped: List[Dict[str, str]] = []
    p = Path(str(root))
    if not p.is_dir():
        return good, skipped
    for shard in sorted(p.iterdir()):
        match = _SHARD_RE.match(shard.name)
        if match is None:
            continue
        ok, reason = verify_shard(str(shard), deep=deep)
        if not ok:
            skipped.append({"path": str(shard), "reason": reason})
            continue
        entry = read_shard_manifest(str(shard))
        # trust the filename over a (verified but conceivably relocated)
        # manifest for stream/start identity
        entry["stream"] = int(match.group(1))
        entry["start"] = int(match.group(2))
        entry.setdefault("stop", entry["start"] + int(entry.get("rows", 0)))
        entry["path"] = str(shard)
        good.append(entry)
    good.sort(key=lambda e: (e["stream"], e["start"]))
    return good, skipped


class _Segment:
    """A contiguous run of verified shards within one stream: logical steps
    ``[start, stop)`` with no gaps (a skipped shard splits its stream into
    two segments — sequence windows never span the hole)."""

    __slots__ = ("stream", "start", "stop", "shards")

    def __init__(self, stream: int, start: int):
        self.stream = int(stream)
        self.start = int(start)
        self.stop = int(start)
        self.shards: List[Dict[str, Any]] = []

    @property
    def rows(self) -> int:
        return self.stop - self.start


class OfflineDataset:
    """Manifest-validated streaming view over an exported dataset.

    * shard discovery skips torn/corrupt shards (``self.skipped`` carries the
      records for the caller to journal as ``dataset_shard_skipped``);
    * :meth:`gather` / :meth:`gather_window` are the exact-index read path
      (the loader-parity tests pin them bit-identical to the live buffers);
    * :meth:`batches` is the training feed: deterministic seeded windowed
      shuffle, flat or sequence mode, optional background prefetch thread
      (``prefetch=N`` keeps up to N host batches staged ahead; the batch
      *sequence* is identical with prefetch on or off).
    """

    def __init__(
        self,
        root: str,
        deep_verify: bool = True,
        cache_shards: int = 8,
    ):
        self.root = str(root)
        self.meta = read_dataset_meta(self.root) or {}
        shards, self.skipped = discover_shards(self.root, deep=deep_verify)
        if not shards:
            raise FileNotFoundError(
                f"No verifiable dataset shards under '{self.root}' "
                f"({len(self.skipped)} rejected: {[s['reason'] for s in self.skipped[:5]]})"
            )
        self.segments: List[_Segment] = []
        current: Optional[_Segment] = None
        for entry in shards:
            if current is None or entry["stream"] != current.stream or entry["start"] != current.stop:
                current = _Segment(entry["stream"], entry["start"])
                self.segments.append(current)
            current.shards.append(entry)
            current.stop = entry["stop"]
        self.keys: Tuple[str, ...] = tuple(shards[0].get("keys") or ())
        self.key_specs: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {
            k: (tuple(spec[0]), np.dtype(spec[1])) for k, spec in (shards[0].get("keys") or {}).items()
        }
        self.streams: Tuple[int, ...] = tuple(sorted({s.stream for s in self.segments}))
        self._cache: "OrderedDict[str, Dict[str, np.ndarray]]" = OrderedDict()
        self._cache_shards = max(1, int(cache_shards))
        self._cache_lock = threading.Lock()

    # -- introspection ------------------------------------------------------
    @property
    def total_rows(self) -> int:
        return sum(seg.rows for seg in self.segments)

    @property
    def total_bytes(self) -> int:
        return sum(int(sh.get("bytes", 0)) for seg in self.segments for sh in seg.shards)

    @property
    def n_shards(self) -> int:
        return sum(len(seg.shards) for seg in self.segments)

    def summary(self) -> Dict[str, Any]:
        """The ``dataset_open`` journal payload."""
        return {
            "path": self.root,
            "streams": len(self.streams),
            "segments": len(self.segments),
            "shards": self.n_shards,
            "rows": self.total_rows,
            "bytes": self.total_bytes,
            "skipped": len(self.skipped),
            "keys": sorted(self.keys),
        }

    # -- raw read path ------------------------------------------------------
    def _load_shard(self, entry: Mapping[str, Any], keys: Sequence[str]) -> Dict[str, np.ndarray]:
        """Decode (only) ``keys`` of one shard, merging into the LRU cache —
        a metadata scan over done flags/rewards never decompresses the pixel
        arrays living in the same shards (tools/dataset_report.py relies on
        this to stay safe on datasets far bigger than RAM)."""
        path = entry["path"]
        with self._cache_lock:
            cached = self._cache.get(path)
            if cached is not None and all(k in cached for k in keys):
                self._cache.move_to_end(path)
                return cached
        arrays = dict(cached or {})
        with np.load(path, allow_pickle=False) as payload:
            for k in keys:
                if k not in arrays:
                    arrays[k] = payload[k]
        with self._cache_lock:
            self._cache[path] = arrays
            self._cache.move_to_end(path)
            while len(self._cache) > self._cache_shards:
                self._cache.popitem(last=False)
        return arrays

    def _segment_rows(self, seg: _Segment, steps: np.ndarray, keys: Sequence[str]) -> Dict[str, np.ndarray]:
        """Gather arbitrary logical ``steps`` of one segment (grouped by
        owning shard, order preserved)."""
        out = {
            k: np.empty((len(steps), *self.key_specs[k][0]), self.key_specs[k][1]) for k in keys
        }
        starts = np.asarray([sh["start"] for sh in seg.shards])
        owner = np.searchsorted(starts, steps, side="right") - 1
        for shard_idx in np.unique(owner):
            entry = seg.shards[int(shard_idx)]
            mask = owner == shard_idx
            local = steps[mask] - entry["start"]
            arrays = self._load_shard(entry, keys)
            for k in keys:
                out[k][mask] = arrays[k][local]
        return out

    def _find_segment(self, stream: int, step: int) -> _Segment:
        for seg in self.segments:
            if seg.stream == stream and seg.start <= step < seg.stop:
                return seg
        raise IndexError(f"step {step} of stream {stream} is not covered by any verified shard")

    def gather(self, stream: int, steps: Sequence[int], keys: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        """``{key: [N, ...]}`` for arbitrary logical steps of one stream."""
        keys = tuple(keys or self.keys)
        steps = np.asarray(steps, dtype=np.int64)
        out = {k: np.empty((len(steps), *self.key_specs[k][0]), self.key_specs[k][1]) for k in keys}
        seg_of = [self._find_segment(stream, int(s)) for s in steps]
        for seg in {id(s): s for s in seg_of}.values():
            mask = np.asarray([sg is seg for sg in seg_of])
            part = self._segment_rows(seg, steps[mask], keys)
            for k in keys:
                out[k][mask] = part[k]
        return out

    def gather_window(self, stream: int, start: int, length: int, keys: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        """``{key: [length, ...]}`` contiguous window (must lie inside one
        segment — the episode/hole discipline sequence sampling enforces)."""
        seg = self._find_segment(stream, int(start))
        if int(start) + int(length) > seg.stop:
            raise IndexError(
                f"window [{start}, {start + length}) of stream {stream} crosses the end of its "
                f"contiguous segment [{seg.start}, {seg.stop})"
            )
        steps = np.arange(int(start), int(start) + int(length), dtype=np.int64)
        return self._segment_rows(seg, steps, tuple(keys or self.keys))

    # -- sampling index spaces ---------------------------------------------
    def _flat_index(self, need_next: bool) -> List[Tuple[_Segment, int, int]]:
        """(segment, first_step, n_valid) per segment for flat sampling;
        deriving next-obs from step+1 drops each segment's last row."""
        out = []
        for seg in self.segments:
            n = seg.rows - (1 if need_next else 0)
            if n > 0:
                out.append((seg, seg.start, n))
        return out

    def _sequence_index(
        self, sequence_length: int, respect_episodes: bool
    ) -> List[Tuple[_Segment, np.ndarray]]:
        """(segment, valid start steps) per segment for sequence sampling.

        A start is valid when the full window fits inside the segment;
        ``respect_episodes`` additionally rejects windows with an episode
        boundary strictly inside them (``is_first`` after position 0 when the
        dataset stores it, else a done row before the window's last step).
        """
        out = []
        T = int(sequence_length)
        for seg in self.segments:
            if seg.rows < T:
                continue
            starts = np.arange(seg.start, seg.stop - T + 1, dtype=np.int64)
            if respect_episodes and seg.rows > 0:
                boundary = self._episode_boundaries(seg)
                if boundary is not None:
                    # window [s, s+T) is valid iff no boundary in (s, s+T)
                    bad = np.zeros(len(starts), dtype=bool)
                    for b in np.nonzero(boundary)[0]:
                        step = seg.start + int(b)
                        lo = max(seg.start, step - T + 1)
                        bad[max(0, lo - seg.start) : max(0, step - seg.start)] = True
                    starts = starts[~bad]
            if len(starts):
                out.append((seg, starts))
        return out

    def _episode_boundaries(self, seg: _Segment) -> Optional[np.ndarray]:
        """Per-row bool: row STARTS a new episode (``is_first``) — derived
        from dones when the dataset predates ``is_first``."""
        if "is_first" in self.key_specs:
            rows = self.gather_window(seg.stream, seg.start, seg.rows, keys=("is_first",))
            return np.asarray(rows["is_first"]).reshape(seg.rows, -1).any(axis=-1)
        if "terminated" in self.key_specs or "truncated" in self.key_specs:
            keys = [k for k in ("terminated", "truncated") if k in self.key_specs]
            rows = self.gather_window(seg.stream, seg.start, seg.rows, keys=keys)
            done = np.zeros(seg.rows, dtype=bool)
            for k in keys:
                done |= np.asarray(rows[k]).reshape(seg.rows, -1).any(axis=-1)
            first = np.zeros(seg.rows, dtype=bool)
            first[1:] = done[:-1]
            return first
        return None

    # -- deterministic batch feed ------------------------------------------
    def batches(
        self,
        batch_size: int,
        *,
        seed: int,
        mode: str = "flat",
        sequence_length: int = 1,
        keys: Optional[Sequence[str]] = None,
        derive_next_obs: bool = False,
        next_obs_keys: Sequence[str] = ("observations",),
        respect_episodes: bool = False,
        shuffle_window: int = 1 << 16,
        prefetch: int = 0,
        on_epoch: Optional[Callable[[int], None]] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Infinite deterministic batch iterator.

        ``mode="flat"`` yields ``{key: [B, ...]}`` transition batches
        (``derive_next_obs`` adds ``next_<k>`` for ``next_obs_keys`` from the
        stream successor row — the live ``sample_next_obs`` semantics);
        ``mode="sequence"`` yields ``{key: [T, B, ...]}`` contiguous windows
        (time-major, the Dreamer train-batch layout).

        Batch ``i`` for a given ``(seed, mode, batch_size, ...)`` is the same
        arrays no matter how the iterator is driven — the windowed shuffle is
        a pure function of ``(seed, epoch)`` and prefetching (``prefetch>0``)
        only moves WHERE batches are assembled, never their order.  Partial
        trailing batches are dropped (stable shapes ⇒ no recompiles);
        ``on_epoch(epoch)`` fires when a new epoch's permutation starts.
        """
        if batch_size <= 0:
            raise ValueError(f"'batch_size' must be > 0, got {batch_size}")
        if mode not in ("flat", "sequence"):
            raise ValueError(f"mode must be 'flat' or 'sequence', got {mode!r}")
        source = self._batch_source(
            batch_size,
            seed=int(seed),
            mode=mode,
            sequence_length=int(sequence_length),
            keys=tuple(keys or self.keys),
            derive_next_obs=bool(derive_next_obs),
            next_obs_keys=tuple(next_obs_keys),
            respect_episodes=bool(respect_episodes),
            shuffle_window=max(1, int(shuffle_window)),
            on_epoch=on_epoch,
        )
        if prefetch and int(prefetch) > 0:
            return _prefetch_iter(source, depth=int(prefetch))
        return source

    def _batch_source(
        self,
        batch_size: int,
        *,
        seed: int,
        mode: str,
        sequence_length: int,
        keys: Tuple[str, ...],
        derive_next_obs: bool,
        next_obs_keys: Tuple[str, ...],
        respect_episodes: bool,
        shuffle_window: int,
        on_epoch: Optional[Callable[[int], None]],
    ) -> Iterator[Dict[str, np.ndarray]]:
        if mode == "flat":
            index = self._flat_index(need_next=derive_next_obs)
            n_total = sum(n for _, _, n in index)
            if n_total < batch_size:
                raise ValueError(
                    f"dataset has only {n_total} usable transitions but the batch size is {batch_size}"
                )
            spans = np.cumsum([0] + [n for _, _, n in index])
        else:
            seq_index = self._sequence_index(sequence_length, respect_episodes)
            n_total = sum(len(starts) for _, starts in seq_index)
            if n_total < batch_size:
                raise ValueError(
                    f"dataset has only {n_total} valid length-{sequence_length} windows but the "
                    f"batch size is {batch_size}"
                )
            spans = np.cumsum([0] + [len(starts) for _, starts in seq_index])

        def assemble(flat_ids: np.ndarray) -> Dict[str, np.ndarray]:
            owner = np.searchsorted(spans, flat_ids, side="right") - 1
            if mode == "flat":
                out = {
                    k: np.empty((len(flat_ids), *self.key_specs[k][0]), self.key_specs[k][1])
                    for k in keys
                }
                if derive_next_obs:
                    for k in next_obs_keys:
                        out[f"next_{k}"] = np.empty(
                            (len(flat_ids), *self.key_specs[k][0]), self.key_specs[k][1]
                        )
                for seg_idx in np.unique(owner):
                    seg, first, _ = index[int(seg_idx)]
                    mask = owner == seg_idx
                    steps = first + (flat_ids[mask] - spans[seg_idx])
                    part = self._segment_rows(seg, steps, keys)
                    for k in keys:
                        out[k][mask] = part[k]
                    if derive_next_obs:
                        nxt = self._segment_rows(seg, steps + 1, next_obs_keys)
                        for k in next_obs_keys:
                            out[f"next_{k}"][mask] = nxt[k]
                return out
            out = {
                k: np.empty(
                    (sequence_length, len(flat_ids), *self.key_specs[k][0]), self.key_specs[k][1]
                )
                for k in keys
            }
            for seg_idx in np.unique(owner):
                seg, starts = seq_index[int(seg_idx)]
                for col in np.nonzero(owner == seg_idx)[0]:
                    start = int(starts[flat_ids[col] - spans[seg_idx]])
                    window = self._segment_rows(
                        seg, np.arange(start, start + sequence_length, dtype=np.int64), keys
                    )
                    for k in keys:
                        out[k][:, col] = window[k]
            return out

        epoch = 0
        while True:
            if on_epoch is not None:
                on_epoch(epoch)
            rng = np.random.default_rng([int(seed), int(epoch)])
            pending: List[np.ndarray] = []
            pending_n = 0
            for w0 in range(0, n_total, shuffle_window):
                window = np.arange(w0, min(w0 + shuffle_window, n_total), dtype=np.int64)
                rng.shuffle(window)
                pending.append(window)
                pending_n += len(window)
                while pending_n >= batch_size:
                    flat = np.concatenate(pending) if len(pending) > 1 else pending[0]
                    yield assemble(flat[:batch_size])
                    rest = flat[batch_size:]
                    pending = [rest] if len(rest) else []
                    pending_n = len(rest)
            epoch += 1  # partial tail dropped: stable batch shapes


def _prefetch_iter(source: Iterator[Dict[str, np.ndarray]], depth: int) -> Iterator[Dict[str, np.ndarray]]:
    """Background host-prefetch: a daemon thread drains ``source`` into a
    bounded queue so batch assembly (shard reads, gathers) overlaps the
    consumer's device step.  Order-preserving by construction — one producer,
    one FIFO — so prefetch-on streams the identical batch sequence."""
    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    _END = object()

    def worker() -> None:
        try:
            for item in source:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.5)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put(_END)
        except BaseException as err:  # surface loader errors to the consumer
            try:
                q.put(err, timeout=5.0)
            except queue.Full:  # pragma: no cover - consumer gone
                pass

    thread = threading.Thread(target=worker, name="sheeprl-dataset-prefetch", daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
