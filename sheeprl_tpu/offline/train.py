"""Env-free offline training (``algo.offline.enabled=true``).

``cli.run_algorithm`` routes here instead of the registered online
entrypoint: no env or player is ever constructed (``pipelined_vector_env``
actively refuses to run in this mode), and the EXISTING guarded train steps
are driven from the :class:`~sheeprl_tpu.data.datasets.OfflineDataset`
streaming loader instead of a live replay buffer:

* **SAC / DroQ** — flat transition batches (D4RL-style fixed-dataset
  off-policy training; ``algo.offline.cql_alpha > 0`` adds the conservative
  Q penalty the train-step builders grew for exactly this mode);
* **DreamerV3** — contiguous ``[T, B]`` sequence windows drive the full
  dynamic-learning step (world model + imagination actor/critic) — offline
  world-model pretraining from any exported Dreamer dataset, ``rssm_*``
  stored-state keys included.

The full diagnostics stack stays live: the run journals ``dataset_open`` (+
one ``dataset_shard_skipped`` per torn/corrupt shard), gauges
``Telemetry/dataset_read_sps`` / ``Telemetry/dataset_epoch`` ride the metric
intervals and ``/metrics``, checkpoints flow through the resilience layer
(async writer + manifest sidecars) and the sentinel/health hooks see every
update.  The step counter of an offline run counts *gradient steps*
(``algo.total_steps`` = total optimizer steps; there are no env frames).
"""

from __future__ import annotations

import os
import warnings
from math import prod
from typing import Any, Dict, List, Tuple

import numpy as np

#: Algorithms the offline mode can drive (validated in ``cli.check_configs``).
OFFLINE_ALGOS: Tuple[str, ...] = ("sac", "droq", "dreamer_v3")


def offline_main(runtime, cfg):
    """Entry point ``cli.run_algorithm`` launches when ``algo.offline.enabled``."""
    name = cfg.algo.name
    if name in ("sac", "droq"):
        return _offline_flat(runtime, cfg)
    if name == "dreamer_v3":
        return _offline_dreamer(runtime, cfg)
    raise ValueError(
        f"algo.offline.enabled=true supports {sorted(OFFLINE_ALGOS)}, got algo.name={name!r}"
    )


# ---------------------------------------------------------------------------
# shared scaffold


def _open_run(runtime, cfg):
    """Logger + log dir + diagnostics + verified dataset — the env-free
    replacement for every online loop's env/player preamble."""
    from sheeprl_tpu.config import instantiate
    from sheeprl_tpu.data.datasets import OfflineDataset
    from sheeprl_tpu.utils.logger import get_log_dir, get_logger
    from sheeprl_tpu.utils.utils import get_diagnostics, save_configs

    offline = cfg.algo.get("offline") or {}
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    dataset = OfflineDataset(
        str(offline.get("dataset_dir")),
        deep_verify=bool(offline.get("deep_verify", True)),
    )
    # the dataset-side ckpt_skipped analogue: one journaled record per
    # torn/corrupt shard, then the open summary — training continues on the
    # verified remainder
    for skip in dataset.skipped:
        diag._journal_event("dataset_shard_skipped", **skip)
    diag._journal_event("dataset_open", **dataset.summary())
    aggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    if cfg.algo.get("run_test"):
        warnings.warn(
            "algo.run_test is ignored in offline mode (there is no environment to test in); "
            "evaluate the final checkpoint with sheeprl-eval instead",
            UserWarning,
        )
    return logger, log_dir, diag, dataset, aggregator, offline


def _offline_action_space(act_dim: int, offline: Dict[str, Any]):
    """Action space for the dataset's actions: bounds from the
    ``algo.offline.action_low/high`` knobs, canonical ±1 otherwise (tanh
    policies need finite bounds; the collect env's exact bounds are not part
    of the dataset record)."""
    import gymnasium as gym

    low = offline.get("action_low")
    high = offline.get("action_high")
    low = -1.0 if low is None else low
    high = 1.0 if high is None else high
    low_arr = np.broadcast_to(np.asarray(low, np.float32), (act_dim,)).copy()
    high_arr = np.broadcast_to(np.asarray(high, np.float32), (act_dim,)).copy()
    if not (np.isfinite(low_arr).all() and np.isfinite(high_arr).all()):
        raise ValueError(
            "algo.offline.action_low/high must be finite (tanh policies rescale by them), "
            f"got {low!r} / {high!r}"
        )
    return gym.spaces.Box(low_arr, high_arr, (act_dim,), np.float32)


def _grad_plan(cfg, offline: Dict[str, Any]) -> Tuple[int, int]:
    """(iterations, gradient steps per iteration): ``algo.total_steps`` is
    the total optimizer-step budget in offline mode."""
    per_iter = int(offline.get("grad_steps_per_iter", 16) or 16)
    if cfg.dry_run:
        return 1, 1
    total = max(1, int(cfg.algo.total_steps))
    per_iter = max(1, min(per_iter, total))
    return max(1, total // per_iter), per_iter


def _resume_counters(state) -> Tuple[int, int, int, int]:
    """(start_iter, policy_step, last_log, last_checkpoint) for a resumed
    run.  Only checkpoints written BY the offline mode continue the offline
    schedule: an online collect run's ``iter_num``/``policy_step`` count env
    iterations, and reinterpreting them as gradient-step counters would make
    fine-tuning a no-op (the loop would start past ``total_iters``).  Online
    checkpoints therefore restore agent/optimizer state but start a fresh
    offline budget at step 0."""
    if state and state.get("offline"):
        return state["iter_num"] + 1, state["policy_step"], state["last_log"], state["last_checkpoint"]
    return 1, 0, 0, 0


def _save_offline_checkpoint(runtime, diag, cfg, log_dir, state, policy_step, iter_num, preempt):
    ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_0.ckpt")
    with diag.span("checkpoint"):
        runtime.call(
            "on_checkpoint_coupled", ckpt_path=ckpt_path, state=state, replay_buffer=None
        )
    diag.on_checkpoint(policy_step, ckpt_path)
    if preempt:
        diag.on_preempted(policy_step, iter_num, ckpt_path)
    return ckpt_path


# ---------------------------------------------------------------------------
# SAC / DroQ: flat transition batches


def _offline_flat(runtime, cfg):
    import gymnasium as gym
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.envs.player import fetch_values
    from sheeprl_tpu.parallel.dp import local_sample_size
    from sheeprl_tpu.parallel.mesh import replicated_sharding
    from sheeprl_tpu.parallel.precision import cast_floating
    from sheeprl_tpu.config import instantiate
    from sheeprl_tpu.utils.timer import timer

    name = cfg.algo.name
    world_size = runtime.world_size
    rng_key = runtime.seed_everything(cfg.seed)
    logger, log_dir, diag, dataset, aggregator, offline = _open_run(runtime, cfg)
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    for key in ("observations", "actions", "rewards", "terminated"):
        if key not in dataset.key_specs:
            raise ValueError(
                f"offline {name} needs the '{key}' key; the dataset at "
                f"'{dataset.root}' carries {sorted(dataset.keys)}"
            )
    obs_dim = int(prod(dataset.key_specs["observations"][0]))
    act_dim = int(prod(dataset.key_specs["actions"][0]))
    mlp_keys = list(cfg.algo.mlp_keys.encoder) or ["state"]
    if len(mlp_keys) > 1:
        # the dataset stores the FLAT concat the collect loop built — one
        # synthetic key carries it whole (bit-identical network input)
        warnings.warn(
            f"offline {name}: dataset observations are pre-flattened; collapsing "
            f"algo.mlp_keys.encoder={mlp_keys} onto '{mlp_keys[0]}'",
            UserWarning,
        )
        cfg.algo.mlp_keys.encoder = mlp_keys[:1]
    obs_space = gym.spaces.Dict(
        {mlp_keys[0]: gym.spaces.Box(-np.inf, np.inf, (obs_dim,), np.float32)}
    )
    action_space = _offline_action_space(act_dim, offline)

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    if name == "droq":
        from sheeprl_tpu.algos.droq.agent import build_agent
        from sheeprl_tpu.algos.droq.droq import make_train_step
    else:
        from sheeprl_tpu.algos.sac.agent import build_agent
        from sheeprl_tpu.algos.sac.sac import make_train_step
    actor_def, critic_def, params, target_entropy = build_agent(
        runtime, cfg, obs_space, action_space, state["agent"] if state else None
    )
    params = cast_floating(params, runtime.param_dtype)
    optimizers = {
        "actor": instantiate(cfg.algo.actor.optimizer),
        "critic": instantiate(cfg.algo.critic.optimizer),
        "alpha": instantiate(cfg.algo.alpha.optimizer),
    }
    opt_states = {
        "actor": optimizers["actor"].init(params["actor"]),
        "critic": optimizers["critic"].init(params["critic"]),
        "alpha": optimizers["alpha"].init(params["log_alpha"]),
    }
    if state and "opt_states" in state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            state["opt_states"],
        )
    if world_size > 1:
        params = jax.device_put(params, replicated_sharding(runtime.mesh))
        opt_states = jax.device_put(opt_states, replicated_sharding(runtime.mesh))

    if name == "droq":
        train_step = diag.instrument(
            "train_step",
            make_train_step(
                actor_def, critic_def, optimizers, cfg, target_entropy,
                mesh=runtime.mesh if world_size > 1 else None,
            ),
            kind="train",
            donate_argnums=(0, 1),
        )
    else:
        train_step = diag.instrument(
            "train_step",
            make_train_step(actor_def, critic_def, optimizers, cfg, runtime.mesh, target_entropy),
            kind="train",
            donate_argnums=(0, 1),
        )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_states)

    total_iters, grad_per_iter = _grad_plan(cfg, offline)
    batch_rows = local_sample_size(cfg.algo.per_rank_batch_size * world_size)
    train_keys = [
        k
        for k in ("observations", "next_observations", "actions", "rewards", "terminated")
        if k in dataset.key_specs
    ]
    derive_next = "next_observations" not in dataset.key_specs
    epoch_box = {"epoch": 0}

    def feed(seed_salt: int, keys: List[str], derive: bool):
        return dataset.batches(
            batch_rows * grad_per_iter,
            seed=int(cfg.seed) + seed_salt,
            mode="flat",
            keys=keys,
            derive_next_obs=derive,
            next_obs_keys=("observations",),
            shuffle_window=int(offline.get("shuffle_window") or (1 << 16)),
            prefetch=int(offline.get("prefetch", 2) or 0),
            on_epoch=lambda e: epoch_box.__setitem__("epoch", e),
        )

    batches = feed(0, train_keys, derive_next)
    actor_batches = feed(1, ["observations"], False) if name == "droq" else None

    start_iter, policy_step_count, last_log, last_checkpoint = _resume_counters(state)

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/train_time"):
            with diag.span("buffer-sample"):
                host = next(batches)
                rows = batch_rows * grad_per_iter
                data = {
                    k: jnp.asarray(np.asarray(v), jnp.float32).reshape(
                        grad_per_iter, batch_rows, *np.asarray(v).shape[1:]
                    )
                    for k, v in host.items()
                }
                if name == "droq":
                    actor_host = next(actor_batches)
                    rows += batch_rows * grad_per_iter  # the second stream counts too
                    actor_data = {
                        k: jnp.asarray(np.asarray(v), jnp.float32).reshape(
                            grad_per_iter, batch_rows, *np.asarray(v).shape[1:]
                        )
                        for k, v in actor_host.items()
                    }
            data = diag.maybe_inject_nan(iter_num, data)
            with diag.span("train"):
                rng_key, scan_key = jax.random.split(rng_key)
                keys = jax.random.split(scan_key, grad_per_iter)
                if name == "droq":
                    params, opt_states, losses = train_step(params, opt_states, data, actor_data, keys)
                    losses, health_host = np.asarray(losses), {}
                    nonfinite = float(np.sum(~np.isfinite(losses)))
                else:
                    params, opt_states, losses, health = train_step(params, opt_states, data, keys)
                    losses, health_host = fetch_values(losses, health)
                    nonfinite = float(losses[4])
        policy_step_count += grad_per_iter
        diag.note_dataset_read(rows)
        diag.note_dataset_epoch(epoch_box["epoch"])
        diag.on_health(policy_step_count, health_host)
        stats = {
            "Loss/value_loss": float(losses[0]),
            "Loss/policy_loss": float(losses[1]),
            "Loss/alpha_loss": float(losses[2]),
        }
        if name != "droq":
            stats["Grads/global_norm"] = float(losses[3])
        for key, value in stats.items():
            aggregator.update(key, value)
        diag.on_update(policy_step_count, stats, nonfinite=nonfinite)

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/train_time", 0) > 0:
                metrics["Time/sps_train"] = (policy_step_count - last_log) / timers["Time/train_time"]
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                "agent": jax.tree_util.tree_map(np.asarray, params),
                "opt_states": jax.tree_util.tree_map(np.asarray, opt_states),
                "offline": True,  # counters below are gradient-step counters
                "iter_num": iter_num,
                "policy_step": policy_step_count,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
            }
            _save_offline_checkpoint(
                runtime, diag, cfg, log_dir, ckpt_state, policy_step_count, iter_num, preempt_now
            )

    logger.finalize()
    diag.close("completed")


# ---------------------------------------------------------------------------
# DreamerV3: sequence windows drive the dynamic-learning step


def _offline_dreamer(runtime, cfg):
    import gymnasium as gym
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import (
        METRIC_ORDER,
        _build_agent_from_state,
        _default_make_optimizers,
        make_train_step,
    )
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments_state, rssm_scan_spec
    from sheeprl_tpu.config import instantiate
    from sheeprl_tpu.parallel.dp import local_sample_size, normalize_staged, stage
    from sheeprl_tpu.parallel.mesh import replicated_sharding
    from sheeprl_tpu.parallel.precision import cast_floating
    from sheeprl_tpu.utils.timer import timer

    world_size = runtime.world_size
    rng_key = runtime.seed_everything(cfg.seed)
    logger, log_dir, diag, dataset, aggregator, offline = _open_run(runtime, cfg)
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    needed = obs_keys + ["actions", "rewards", "terminated", "is_first"]
    if rssm_scan_spec(cfg)[0] > 1:
        needed += ["rssm_recurrent", "rssm_posterior", "rssm_valid"]
    missing = [k for k in needed if k not in dataset.key_specs]
    if missing:
        raise ValueError(
            f"offline dreamer_v3 needs dataset keys {missing} which '{dataset.root}' does not "
            f"carry (have {sorted(dataset.keys)}); for rssm_* keys re-collect with "
            "algo.rssm_chunks > 1 or train with algo.rssm_chunks=1"
        )

    spaces: Dict[str, gym.spaces.Space] = {}
    for k in obs_keys:
        shape, dtype = dataset.key_specs[k]
        if np.dtype(dtype) == np.uint8:
            spaces[k] = gym.spaces.Box(0, 255, shape, np.uint8)
        else:
            # the collect loop stores mlp keys with a trailing feature axis
            spaces[k] = gym.spaces.Box(-np.inf, np.inf, shape, np.float32)
    obs_space = gym.spaces.Dict(spaces)
    stored_act_dim = int(prod(dataset.key_specs["actions"][0]))
    actions_dim = offline.get("actions_dim")
    actions_dim = tuple(int(d) for d in actions_dim) if actions_dim else (stored_act_dim,)
    if int(sum(actions_dim)) != stored_act_dim:
        raise ValueError(
            f"algo.offline.actions_dim={list(actions_dim)} sums to {sum(actions_dim)} but the "
            f"dataset stores {stored_act_dim}-dim actions"
        )
    is_continuous = offline.get("is_continuous")
    if is_continuous is None:
        # no explicit family: an un-annotated dataset is treated as one flat
        # continuous action vector (the exporter stores the raw action concat)
        is_continuous = not offline.get("actions_dim")
    is_continuous = bool(is_continuous)

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    world_model_def, actor_def, critic_def, params = _build_agent_from_state(
        runtime, actions_dim, is_continuous, cfg, obs_space, state
    )
    params = cast_floating(params, runtime.param_dtype)
    optimizers, opt_states = _default_make_optimizers(cfg, params, state)
    moments_state = init_moments_state()
    if state and "moments" in state:
        moments_state = jax.tree_util.tree_map(jnp.asarray, state["moments"])
    if world_size > 1:
        params = jax.device_put(params, replicated_sharding(runtime.mesh))
        opt_states = jax.device_put(opt_states, replicated_sharding(runtime.mesh))
        moments_state = jax.device_put(moments_state, replicated_sharding(runtime.mesh))

    train_step = diag.instrument(
        "train_step",
        make_train_step(
            world_model_def,
            actor_def,
            critic_def,
            optimizers,
            cfg,
            actions_dim,
            is_continuous,
            mesh=runtime.mesh if world_size > 1 else None,
        ),
        kind="train",
        donate_argnums=(0, 1, 2),
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_states)
    diag.register_footprint("moments", moments_state)

    total_iters, grad_per_iter = _grad_plan(cfg, offline)
    seq_len = int(offline.get("sequence_length") or cfg.algo.per_rank_sequence_length)
    batch_cols = local_sample_size(cfg.algo.per_rank_batch_size * world_size)
    epoch_box = {"epoch": 0}
    batches = dataset.batches(
        batch_cols,
        seed=int(cfg.seed),
        mode="sequence",
        sequence_length=seq_len,
        keys=needed,
        respect_episodes=bool(offline.get("respect_episodes", False)),
        shuffle_window=int(offline.get("shuffle_window") or (1 << 16)),
        prefetch=int(offline.get("prefetch", 2) or 0),
        on_epoch=lambda e: epoch_box.__setitem__("epoch", e),
    )
    mesh = runtime.mesh if world_size > 1 else None

    start_iter, policy_step_count, last_log, last_checkpoint = _resume_counters(state)
    cumulative_grad_steps = 0

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/train_time"):
            metric_rows: List[np.ndarray] = []
            for _ in range(grad_per_iter):
                with diag.span("buffer-sample"):
                    host = next(batches)
                    batch = normalize_staged(stage(host, mesh, batch_axis=1), cnn_keys)
                batch = diag.maybe_inject_nan(iter_num, batch)
                with diag.span("train"):
                    target_freq = cfg.algo.critic.get("per_rank_target_network_update_freq", 0)
                    if target_freq and cumulative_grad_steps % target_freq == 0:
                        tau = 1.0 if cumulative_grad_steps == 0 else cfg.algo.critic.get("tau", 1.0)
                    else:
                        tau = 0.0
                    rng_key, train_key = jax.random.split(rng_key)
                    out = train_step(
                        params, opt_states, moments_state, batch, train_key, jnp.float32(tau)
                    )
                    params, opt_states, moments_state, metrics = out[:4]
                    step_health = out[4] if len(out) > 4 else {}
                    cumulative_grad_steps += 1
                metric_rows.append(np.asarray(metrics))
                if step_health:
                    from sheeprl_tpu.envs.player import fetch_values

                    (health_host,) = fetch_values(step_health)
                    diag.on_health(policy_step_count, health_host)
        policy_step_count += grad_per_iter
        diag.note_dataset_read(grad_per_iter * batch_cols * seq_len)
        diag.note_dataset_epoch(epoch_box["epoch"])
        diag.observe_rows(policy_step_count, METRIC_ORDER, metric_rows)
        for row in metric_rows:
            for key, value in zip(METRIC_ORDER, row):
                if np.isfinite(value):
                    aggregator.update(key, float(value))

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics_dict = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/train_time", 0) > 0:
                metrics_dict["Time/sps_train"] = (policy_step_count - last_log) / timers["Time/train_time"]
            if runtime.is_global_zero:
                logger.log_metrics(metrics_dict, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                **{k: jax.tree_util.tree_map(np.asarray, v) for k, v in params.items()},
                "opt_states": jax.tree_util.tree_map(np.asarray, opt_states),
                "moments": jax.tree_util.tree_map(np.asarray, moments_state),
                "offline": True,  # counters below are gradient-step counters
                "iter_num": iter_num,
                "policy_step": policy_step_count,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            _save_offline_checkpoint(
                runtime, diag, cfg, log_dir, ckpt_state, policy_step_count, iter_num, preempt_now
            )

    logger.finalize()
    diag.close("completed")
