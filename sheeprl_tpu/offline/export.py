"""Replay → durable dataset export.

Three producers share one writer:

* **live export** (``buffer.export=True``): ``CheckpointCallback`` calls
  :func:`checkpoint_export` at every checkpoint boundary.  The critical path
  pays only the row *copies* of the not-yet-exported window (the same cost
  class as the checkpoint's own host snapshot); shard serialization, content
  digests and the ``dataset_export`` journal event ride the resilience
  async-writer thread when one is armed;
* **run-dir converter** (``sheeprl-export`` / ``tools/export_dataset.py`` /
  ``python -m sheeprl_tpu export``): ingests a finished (or crashed) run dir
  — the replay state of its newest *verified* checkpoint plus the run
  journal's identity/reward metadata — so runs collected before this
  subsystem existed are not lost;
* **direct API** (:func:`export_buffer`): tests, benches, notebooks.

Stream mapping (see :mod:`sheeprl_tpu.data.datasets`): step buffers export
one stream per environment (their per-env sub-buffers legitimately desync on
episode-end bookkeeping rows); ``EpisodeBuffer`` exports one stream per
stored episode, so episode boundaries are structural, not inferred.
Incremental exports are cursor-based on the buffers' monotone ``added_steps``
counters: re-exporting is idempotent, and rows that fell out of the ring
between exports surface as a segment gap the loader refuses to sample
sequences across (never as silently glued discontinuities).
"""

from __future__ import annotations

import argparse
import os
import sys
import warnings
from pathlib import Path
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from sheeprl_tpu.data.datasets import (
    discover_shards,
    write_dataset_meta,
    write_shard,
)

DEFAULT_SHARD_ROWS = 4096
#: The default dataset directory inside a run dir (next to `checkpoint/`).
DATASET_DIRNAME = "dataset"


# ---------------------------------------------------------------------------
# small buffer helpers (work across every sheeprl_tpu.data buffer class)


def flush_buffer(rb: Any) -> None:
    """Flush memmap-backed storage to disk before any export/snapshot read —
    the buffers' own ``flush()`` when present (all host buffer classes grew
    one), silently nothing for plain-RAM/device storage."""
    flush = getattr(rb, "flush", None)
    if callable(flush):
        flush()


def note_dataset_bytes(rb: Any, n_bytes: int) -> None:
    """Accumulate exported-dataset disk bytes on the buffer so
    ``footprint()`` reports them under the ``dataset_disk`` key (tracked per
    metric interval via ``diag.track_buffer``)."""
    try:
        rb.dataset_disk_bytes = int(getattr(rb, "dataset_disk_bytes", 0) or 0) + int(n_bytes)
    except Exception:  # pragma: no cover - exotic buffer doubles
        pass


# ---------------------------------------------------------------------------
# writer


class DatasetWriter:
    """Cursor-tracking shard writer for one dataset directory.

    Cursors (per-stream high-water marks) are recovered from the on-disk
    shard manifests at construction and *reserved* synchronously by
    :meth:`reserve`, so a caller may copy rows on the critical path and
    serialize them later on a background thread without a second export
    racing into the same range.
    """

    def __init__(
        self,
        root: str,
        meta: Optional[Mapping[str, Any]] = None,
        shard_rows: int = DEFAULT_SHARD_ROWS,
    ):
        self.root = str(root)
        self.shard_rows = max(1, int(shard_rows))
        write_dataset_meta(self.root, meta)
        shards, _ = discover_shards(self.root, deep=False)
        self._cursor: Dict[int, int] = {}
        for entry in shards:
            stream = int(entry["stream"])
            self._cursor[stream] = max(self._cursor.get(stream, 0), int(entry["stop"]))
        self.rows_written = 0
        self.bytes_written = 0
        self.shards_written = 0

    def cursor(self, stream: int) -> Optional[int]:
        """Steps of ``stream`` already exported (None = stream untouched)."""
        return self._cursor.get(int(stream))

    def reserve(self, stream: int, start: int, rows: int) -> Tuple[int, int]:
        """Claim ``[start, start+rows)`` of ``stream``; returns the effective
        ``(start, rows)`` after trimming the already-exported overlap (rows
        may be 0).  The cursor advances NOW — writes may happen later."""
        stream, start, rows = int(stream), int(start), int(rows)
        cur = self._cursor.get(stream)
        if cur is not None and start < cur:
            trim = min(rows, cur - start)
            start += trim
            rows -= trim
        if rows > 0:
            self._cursor[stream] = start + rows
        return start, rows

    def write(self, stream: int, start: int, arrays: Mapping[str, np.ndarray]) -> Dict[str, Any]:
        """Serialize one reserved chunk as ``shard_rows``-sized shards.
        Returns ``{rows, bytes, shards}``."""
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
        n_rows = next(iter(arrays.values())).shape[0]
        out = {"rows": 0, "bytes": 0, "shards": 0}
        for off in range(0, n_rows, self.shard_rows):
            chunk = {k: v[off : off + self.shard_rows] for k, v in arrays.items()}
            entry = write_shard(self.root, stream, int(start) + off, chunk)
            out["rows"] += entry["rows"]
            out["bytes"] += entry["bytes"]
            out["shards"] += 1
        self.rows_written += out["rows"]
        self.bytes_written += out["bytes"]
        self.shards_written += out["shards"]
        return out


# ---------------------------------------------------------------------------
# chunk collection: (stream, start, arrays) copies of the unexported window


def _replay_chunks(rb: Any, writer: DatasetWriter, stream_base: int = 0) -> List[Tuple[int, int, Dict[str, np.ndarray]]]:
    """Unexported window of a (possibly wrapped) ``ReplayBuffer``: one chunk
    per env stream, rows in logical stream order."""
    if rb.empty:
        return []
    size = rb.buffer_size
    added = int(getattr(rb, "added_steps", 0) or 0)
    if added <= 0:
        # restored buffers predating the counter: fall back to the stored span
        added = size if rb.full else int(rb._pos)
    window_start = max(0, added - size)
    chunks: List[Tuple[int, int, Dict[str, np.ndarray]]] = []
    for env in range(rb.n_envs):
        stream = stream_base + env
        start, rows = writer.reserve(stream, window_start, added - window_start)
        if rows <= 0:
            continue
        slots = (np.arange(start, start + rows, dtype=np.int64)) % size
        arrays = {k: np.take(np.asarray(v), slots, axis=0)[:, env] for k, v in rb.buffer.items()}
        chunks.append((stream, start, arrays))
    return chunks


def _episode_chunks(rb: Any, writer: DatasetWriter) -> List[Tuple[int, int, Dict[str, np.ndarray]]]:
    """One stream per stored episode (monotone episode ids — evicted
    episodes never reuse a stream)."""
    chunks: List[Tuple[int, int, Dict[str, np.ndarray]]] = []
    ids = list(getattr(rb, "episode_ids", range(len(rb.buffer))))
    for eid, episode in zip(ids, rb.buffer):
        ep_len = next(iter(episode.values())).shape[0]
        start, rows = writer.reserve(int(eid), 0, ep_len)
        if rows <= 0:
            continue
        chunks.append((int(eid), start, {k: np.asarray(v)[start : start + rows].copy() for k, v in episode.items()}))
    return chunks


def _device_chunks(rb: Any, writer: DatasetWriter) -> List[Tuple[int, int, Dict[str, np.ndarray]]]:
    """HBM-resident ring: one fetched host snapshot (its ``state_dict``),
    then per-env logical windows from the per-env ``added_steps`` counters."""
    state = rb.state_dict()
    storage = {k: np.asarray(v) for k, v in state["buffer"].items()}
    size = rb.buffer_size
    added = np.asarray(getattr(rb, "added_steps", state.get("filled")), dtype=np.int64)
    filled = np.asarray(state["filled"], dtype=np.int64)
    chunks: List[Tuple[int, int, Dict[str, np.ndarray]]] = []
    for env in range(rb.n_envs):
        # clamped: buffers restored from pre-export checkpoints fall back to
        # added == filled, and a negative logical start must never escape
        # into shard names
        window_start = max(0, int(added[env] - min(filled[env], size)))
        start, rows = writer.reserve(env, window_start, int(added[env]) - window_start)
        if rows <= 0:
            continue
        slots = np.arange(start, start + rows, dtype=np.int64) % size
        chunks.append((env, start, {k: v[slots, env] for k, v in storage.items()}))
    return chunks


def collect_buffer_chunks(rb: Any, writer: DatasetWriter) -> List[Tuple[int, int, Dict[str, np.ndarray]]]:
    """Copy-on-the-caller-thread export chunks for any buffer class (the
    ranges are reserved in ``writer`` as a side effect)."""
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, ReplayBuffer

    flush_buffer(rb)
    if isinstance(rb, EpisodeBuffer):
        return _episode_chunks(rb, writer)
    if isinstance(rb, EnvIndependentReplayBuffer):
        chunks: List[Tuple[int, int, Dict[str, np.ndarray]]] = []
        for env, sub in enumerate(rb.buffer):
            for _, start, arrays in _replay_chunks(sub, _SubWriter(writer, env)):
                chunks.append((env, start, arrays))
        return chunks
    if isinstance(rb, ReplayBuffer):
        return _replay_chunks(rb, writer)
    try:
        from sheeprl_tpu.data.device_buffer import DeviceSequentialReplayBuffer

        if isinstance(rb, DeviceSequentialReplayBuffer):
            return _device_chunks(rb, writer)
    except Exception:  # pragma: no cover - jax-less probes
        pass
    raise TypeError(f"Unsupported replay buffer type for dataset export: {type(rb).__name__}")


class _SubWriter:
    """Redirect a sub-buffer's stream-0 reservation onto the parent stream
    (``EnvIndependentReplayBuffer`` sub-buffers are n_envs=1 rings)."""

    def __init__(self, writer: DatasetWriter, stream: int):
        self._writer = writer
        self._stream = int(stream)

    def reserve(self, _stream: int, start: int, rows: int) -> Tuple[int, int]:
        return self._writer.reserve(self._stream, start, rows)


# ---------------------------------------------------------------------------
# the three producers


class BufferDatasetExporter:
    """Persistent incremental exporter for one (buffer, dataset dir) pair —
    the object behind ``buffer.export=True``.

    ``export`` copies the unexported rows synchronously (reserving their
    ranges) and serializes them either inline or on ``submit`` (the
    resilience async-writer's task lane).  ``journal_fn`` receives one
    ``dataset_export`` event per export that wrote rows.
    """

    def __init__(
        self,
        root: str,
        meta: Optional[Mapping[str, Any]] = None,
        shard_rows: int = DEFAULT_SHARD_ROWS,
        journal_fn: Optional[Callable[..., None]] = None,
    ):
        self.writer = DatasetWriter(root, meta=meta, shard_rows=shard_rows)
        self._journal_fn = journal_fn

    def _journal(self, kind: str, **fields: Any) -> None:
        if self._journal_fn is not None:
            self._journal_fn(kind, **fields)

    def export(
        self,
        rb: Any,
        step: Optional[int] = None,
        submit: Optional[Callable[[Callable[[], None]], Any]] = None,
    ) -> int:
        """Returns the rows queued/written by this call (0 = up to date)."""
        chunks = collect_buffer_chunks(rb, self.writer)
        pending = sum(arrays[next(iter(arrays))].shape[0] for _, _, arrays in chunks)
        if pending == 0:
            return 0

        def work() -> None:
            totals = {"rows": 0, "bytes": 0, "shards": 0}
            for stream, start, arrays in chunks:
                out = self.writer.write(stream, start, arrays)
                for key in totals:
                    totals[key] += out[key]
            note_dataset_bytes(rb, totals["bytes"])
            self._journal(
                "dataset_export",
                path=self.writer.root,
                step=step,
                **totals,
                total_rows=self.writer.rows_written,
                total_bytes=self.writer.bytes_written,
            )

        if submit is not None:
            submit(work)
        else:
            work()
        return pending


def export_buffer(
    rb: Any,
    root: str,
    meta: Optional[Mapping[str, Any]] = None,
    shard_rows: int = DEFAULT_SHARD_ROWS,
    journal_fn: Optional[Callable[..., None]] = None,
    step: Optional[int] = None,
) -> Dict[str, Any]:
    """One-shot synchronous export of a live buffer; returns the writer
    totals ``{rows, bytes, shards, path}``."""
    exporter = BufferDatasetExporter(root, meta=meta, shard_rows=shard_rows, journal_fn=journal_fn)
    exporter.export(rb, step=step)
    writer = exporter.writer
    return {
        "path": writer.root,
        "rows": writer.rows_written,
        "bytes": writer.bytes_written,
        "shards": writer.shards_written,
    }


def checkpoint_export(callback: Any, runtime: Any, ckpt_path: str, rb: Any) -> None:
    """The ``buffer.export=True`` checkpoint-boundary hook (called by
    ``CheckpointCallback.on_checkpoint_coupled`` right after the checkpoint
    save).  Copies ride the caller; serialization rides the resilience
    async-writer thread when the run has one."""
    from sheeprl_tpu.resilience.manifest import checkpoint_step

    log_dir = str(Path(str(ckpt_path)).parent.parent)
    root = os.path.join(log_dir, DATASET_DIRNAME)
    diagnostics = getattr(runtime, "diagnostics", None)
    journal_fn = None
    submit = None
    if diagnostics is not None:
        journal_fn = diagnostics._journal_event
        resilience = getattr(diagnostics, "resilience", None)
        writer = getattr(resilience, "_writer", None) if resilience is not None else None
        if writer is not None and hasattr(writer, "submit_task"):
            submit = writer.submit_task
    exporter = getattr(callback, "_dataset_exporter", None)
    if exporter is None or exporter.writer.root != root:
        cfg = getattr(diagnostics, "_cfg", None) if diagnostics is not None else None
        meta = {"source": log_dir, "kind": "live_export"}
        if isinstance(cfg, Mapping):
            meta.update(_meta_from_cfg(cfg))
        exporter = BufferDatasetExporter(root, meta=meta, journal_fn=journal_fn)
        callback._dataset_exporter = exporter
    exporter._journal_fn = journal_fn  # late-opened journals attach here
    exporter.export(rb, step=checkpoint_step(str(ckpt_path)), submit=submit)


# ---------------------------------------------------------------------------
# run-dir converter


def _meta_from_cfg(cfg: Mapping[str, Any]) -> Dict[str, Any]:
    algo = cfg.get("algo") or {}
    env = cfg.get("env") or {}
    mlp = (algo.get("mlp_keys") or {}).get("encoder")
    cnn = (algo.get("cnn_keys") or {}).get("encoder")
    return {
        "algo": algo.get("name"),
        "env_id": env.get("id"),
        "num_envs": env.get("num_envs"),
        "seed": cfg.get("seed"),
        "mlp_keys": list(mlp) if mlp else None,
        "cnn_keys": list(cnn) if cnn else None,
    }


def dataset_meta_from_run(run_dir: str) -> Dict[str, Any]:
    """Per-run dataset metadata: the archived config + the run journal's
    identity and reward summary (the journal is the durable record — it
    survives every crash the checkpoint survives)."""
    import yaml

    meta: Dict[str, Any] = {"source": str(run_dir), "kind": "run_dir_convert"}
    cfg_path = None
    for candidate in (Path(run_dir) / "config.yaml", *sorted(Path(run_dir).glob("*/config.yaml"))):
        if candidate.is_file():
            cfg_path = candidate
            break
    if cfg_path is not None:
        try:
            with open(cfg_path) as fp:
                meta.update(_meta_from_cfg(yaml.safe_load(fp) or {}))
        except Exception as err:  # pragma: no cover - corrupt archives
            warnings.warn(f"could not read archived config '{cfg_path}': {err!r}")
    from sheeprl_tpu.diagnostics.journal import find_journal, iter_journal

    journal = find_journal(str(run_dir))
    if journal is not None:
        rewards: List[float] = []
        last_step = None
        for event in iter_journal(journal):
            kind = event.get("event")
            if kind == "run_start":
                meta.setdefault("run_id", event.get("run_id"))
                meta.setdefault("config_hash", event.get("config_hash"))
                meta.setdefault("algo", event.get("algo"))
                meta.setdefault("env_id", event.get("env"))
                meta.setdefault("seed", event.get("seed"))
            elif kind == "metrics":
                step = event.get("step")
                if isinstance(step, (int, float)):
                    last_step = int(step)
                reward = (event.get("metrics") or {}).get("Rewards/rew_avg")
                if isinstance(reward, (int, float)):
                    rewards.append(float(reward))
        meta["journal"] = {
            "path": journal,
            "last_step": last_step,
            "episodes_logged": len(rewards),
            "reward_mean": round(float(np.mean(rewards)), 6) if rewards else None,
            "reward_min": round(float(np.min(rewards)), 6) if rewards else None,
            "reward_max": round(float(np.max(rewards)), 6) if rewards else None,
        }
    return meta


def _rb_state_chunks(state: Mapping[str, Any]) -> List[Tuple[int, int, Dict[str, np.ndarray]]]:
    """Streams from a checkpointed replay-buffer ``state_dict`` (every
    buffer class's format).  Logical step numbering restarts at 0 — the
    converter has no monotone add counter, only the stored window."""
    chunks: List[Tuple[int, int, Dict[str, np.ndarray]]] = []
    if "buffers" in state:  # EnvIndependentReplayBuffer
        for env, sub in enumerate(state["buffers"]):
            for _, start, arrays in _rb_state_chunks(sub):
                chunks.append((env, start, arrays))
        return chunks
    buffer = state.get("buffer")
    if isinstance(buffer, list):  # EpisodeBuffer: one stream per episode
        for eid, episode in enumerate(buffer):
            arrays = {k: np.asarray(v) for k, v in episode.items()}
            if arrays and next(iter(arrays.values())).shape[0] > 0:
                chunks.append((eid, 0, arrays))
        return chunks
    if not isinstance(buffer, Mapping) or not buffer:
        return chunks
    storage = {k: np.asarray(v) for k, v in buffer.items()}
    size = next(iter(storage.values())).shape[0]
    n_envs = next(iter(storage.values())).shape[1]
    if "filled" in state:  # DeviceSequentialReplayBuffer host snapshot
        pos = np.asarray(state["pos"], dtype=np.int64)
        filled = np.asarray(state["filled"], dtype=np.int64)
        for env in range(n_envs):
            rows = int(min(filled[env], size))
            if rows <= 0:
                continue
            first = (pos[env] - rows) % size
            slots = (first + np.arange(rows, dtype=np.int64)) % size
            chunks.append((env, 0, {k: v[slots, env] for k, v in storage.items()}))
        return chunks
    # plain ReplayBuffer / SequentialReplayBuffer
    full = bool(state.get("full"))
    pos = int(state.get("pos", 0))
    rows = size if full else pos
    if rows <= 0:
        return chunks
    first = pos % size if full else 0
    slots = (first + np.arange(rows, dtype=np.int64)) % size
    for env in range(n_envs):
        chunks.append((env, 0, {k: v[slots, env] for k, v in storage.items()}))
    return chunks


def export_run_dir(
    run_dir: str,
    out_dir: Optional[str] = None,
    shard_rows: int = DEFAULT_SHARD_ROWS,
    journal_fn: Optional[Callable[..., None]] = None,
) -> Dict[str, Any]:
    """Convert an existing run dir into a dataset: the replay state of its
    newest manifest-verified checkpoint (``buffer.checkpoint=True`` runs —
    the durable copy of the live memmap buffer) + journal metadata.

    Returns the writer totals; raises when the run has no verifiable
    checkpoint or its checkpoints carry no replay state.
    """
    from sheeprl_tpu.resilience.manifest import checkpoint_step, newest_verified_checkpoint
    from sheeprl_tpu.utils.checkpoint import load_state

    best, skipped = newest_verified_checkpoint(str(run_dir), deep=True)
    if best is None:
        raise FileNotFoundError(
            f"No verifiable checkpoint under '{run_dir}' "
            f"({len(skipped)} rejected: {[s['reason'] for s in skipped[:5]]})"
        )
    state = load_state(best)
    rb_state = state.get("rb")
    if rb_state is None:
        raise ValueError(
            f"Checkpoint '{best}' carries no replay state ('rb'): the run was collected with "
            "buffer.checkpoint=False — re-collect with it on, or export live with buffer.export=True"
        )
    root = str(out_dir) if out_dir else os.path.join(str(run_dir), DATASET_DIRNAME)
    meta = dataset_meta_from_run(run_dir)
    meta["checkpoint"] = {"path": best, "step": checkpoint_step(best, state)}
    writer = DatasetWriter(root, meta=meta, shard_rows=shard_rows)
    for stream, start, arrays in _rb_state_chunks(rb_state):
        start, rows = writer.reserve(stream, start, next(iter(arrays.values())).shape[0])
        if rows <= 0:
            continue
        writer.write(stream, start, {k: v[-rows:] for k, v in arrays.items()})
    out = {
        "path": writer.root,
        "rows": writer.rows_written,
        "bytes": writer.bytes_written,
        "shards": writer.shards_written,
        "checkpoint": best,
    }
    if journal_fn is not None:
        journal_fn("dataset_export", step=meta["checkpoint"]["step"], **out)
    return out


# ---------------------------------------------------------------------------
# CLI (`sheeprl-export` / `tools/export_dataset.py` / `python -m sheeprl_tpu export`)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Export a run's replay experience as a durable sharded dataset "
        "(howto/offline_rl.md)."
    )
    parser.add_argument("run_dir", help="run directory (or any ancestor of its checkpoints)")
    parser.add_argument(
        "--out", default=None, help=f"dataset directory (default: <run_dir>/{DATASET_DIRNAME})"
    )
    parser.add_argument(
        "--shard-rows", type=int, default=DEFAULT_SHARD_ROWS, help="max steps per shard file"
    )
    args = parser.parse_args(argv)
    try:
        out = export_run_dir(args.run_dir, out_dir=args.out, shard_rows=args.shard_rows)
    except (FileNotFoundError, ValueError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    print(
        f"exported {out['rows']} steps in {out['shards']} shard(s) "
        f"({out['bytes']} bytes) from {out['checkpoint']}\n -> {out['path']}"
    )
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
