"""Offline RL subsystem (howto/offline_rl.md).

Three layers on top of :mod:`sheeprl_tpu.data.datasets`:

* :mod:`~sheeprl_tpu.offline.export` — turn replay experience into durable
  sharded datasets: a checkpoint-boundary hook (``buffer.export=True``, the
  serialization riding the resilience async-writer thread off the critical
  path), a run-dir converter for finished runs (``sheeprl-export`` /
  ``tools/export_dataset.py``), and the direct ``export_buffer`` API;
* :mod:`~sheeprl_tpu.offline.train` — the env-free training mode behind
  ``algo.offline.enabled=true``: ``cli.run`` skips env/player construction
  entirely and drives the EXISTING guarded train steps (SAC/DroQ flat
  batches with an optional conservative-Q penalty, DV3 dynamic learning on
  sequence windows) from the streaming loader, full diagnostics stack live;
* ``tools/dataset_report.py`` — shard table / episode histogram / reward
  summary over a dataset's manifests and the source run journal.
"""

from sheeprl_tpu.offline.export import (
    BufferDatasetExporter,
    export_buffer,
    export_run_dir,
)

__all__ = ["BufferDatasetExporter", "export_buffer", "export_run_dir"]
