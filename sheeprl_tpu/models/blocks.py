"""Reusable NN blocks (flax.linen).

TPU-native re-design of /root/reference/sheeprl/models/models.py:16-524.
Differences from the reference that are deliberate TPU choices:

- Convolutions run in NHWC (XLA's native TPU layout).  Observations keep the
  reference's CHW uint8 convention on the host/buffer side; ``cnn_forward``
  transposes once inside the jitted graph.
- ``LayerNormGRUCell`` is written as a ``(carry, x) -> (carry, y)`` cell so it
  drops straight into ``jax.lax.scan`` — the reference steps it from a Python
  loop (algos/dreamer_v3/dreamer_v3.py:134-145); here the whole sequence is one
  XLA while-loop with the gate matmuls batched onto the MXU.
- Norm layers default to eps=1e-3 like Dreamer's (models.py:506-524 uses
  torch LN defaults overridden per-algo; DV3 configs set eps=1e-3).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


def get_activation(name: str | Callable | None) -> Callable:
    """Map reference activation names (e.g. ``torch.nn.SiLU``) to jax fns."""
    if name is None:
        return lambda x: x
    if callable(name):
        return name
    key = name.rsplit(".", 1)[-1].lower()
    table = {
        "relu": jax.nn.relu,
        "silu": jax.nn.silu,
        "swish": jax.nn.silu,
        "tanh": jnp.tanh,
        "elu": jax.nn.elu,
        "gelu": jax.nn.gelu,
        "leakyrelu": jax.nn.leaky_relu,
        "sigmoid": jax.nn.sigmoid,
        "identity": lambda x: x,
    }
    if key not in table:
        raise ValueError(f"Unknown activation '{name}'")
    return table[key]


class MLP(nn.Module):
    """Dense stack with per-layer norm/activation/dropout
    (reference models.py:16-119)."""

    hidden_sizes: Sequence[int]
    output_dim: Optional[int] = None
    activation: str | Callable = "tanh"
    layer_norm: bool = False
    norm_eps: float = 1e-3
    dropout: float = 0.0
    flatten_input: bool = False
    dtype: Optional[Dtype] = None
    param_dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    output_kernel_init: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        act = get_activation(self.activation)
        if self.flatten_input:
            x = x.reshape(x.shape[0], -1)
        for size in self.hidden_sizes:
            x = nn.Dense(size, dtype=self.dtype, param_dtype=self.param_dtype, kernel_init=self.kernel_init)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype, param_dtype=self.param_dtype)(x)
            x = act(x)
            if self.dropout > 0.0:
                x = nn.Dropout(rate=self.dropout, deterministic=deterministic)(x)
        if self.output_dim is not None:
            kinit = self.output_kernel_init or self.kernel_init
            x = nn.Dense(self.output_dim, dtype=self.dtype, param_dtype=self.param_dtype, kernel_init=kinit)(x)
        return x


def cnn_forward(module: nn.Module, x: jax.Array, input_hwc: bool = False) -> jax.Array:
    """Apply a conv module to input with arbitrary leading dims, flattening
    them into a single batch (reference utils/model.py ``cnn_forward``).
    Input is CHW (buffer convention) unless ``input_hwc``; converted to NHWC."""
    lead = x.shape[:-3]
    x = x.reshape((-1,) + x.shape[-3:])
    if not input_hwc:
        x = jnp.transpose(x, (0, 2, 3, 1))
    y = module(x)
    return y.reshape(lead + y.shape[1:])


class CNN(nn.Module):
    """Conv stack (reference models.py:122-202).  NHWC on TPU."""

    channels: Sequence[int]
    kernel_sizes: Sequence[int]
    strides: Sequence[int]
    paddings: Sequence[Any] | None = None
    activation: str | Callable = "relu"
    layer_norm: bool = False
    norm_eps: float = 1e-3
    flatten_output: bool = True
    dtype: Optional[Dtype] = None
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = get_activation(self.activation)
        paddings = self.paddings or ["SAME"] * len(self.channels)
        for ch, k, s, p in zip(self.channels, self.kernel_sizes, self.strides, paddings):
            pad = p if isinstance(p, str) else [(p, p), (p, p)]
            x = nn.Conv(
                ch, (k, k), strides=(s, s), padding=pad, dtype=self.dtype, param_dtype=self.param_dtype
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype, param_dtype=self.param_dtype)(x)
            x = act(x)
        if self.flatten_output:
            x = x.reshape(x.shape[0], -1)
        return x


class DeCNN(nn.Module):
    """Transposed-conv stack (reference models.py:205-285)."""

    channels: Sequence[int]
    kernel_sizes: Sequence[int]
    strides: Sequence[int]
    paddings: Sequence[Any] | None = None
    activation: str | Callable = "relu"
    layer_norm: bool = False
    norm_eps: float = 1e-3
    final_activation: Optional[str] = None
    dtype: Optional[Dtype] = None
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = get_activation(self.activation)
        n = len(self.channels)
        paddings = self.paddings or ["SAME"] * n
        for i, (ch, k, s, p) in enumerate(zip(self.channels, self.kernel_sizes, self.strides, paddings)):
            pad = p if isinstance(p, str) else [(p, p), (p, p)]
            x = nn.ConvTranspose(
                ch, (k, k), strides=(s, s), padding=pad, dtype=self.dtype, param_dtype=self.param_dtype
            )(x)
            last = i == n - 1
            if not last:
                if self.layer_norm:
                    x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype, param_dtype=self.param_dtype)(x)
                x = act(x)
            elif self.final_activation is not None:
                x = get_activation(self.final_activation)(x)
        return x


class NatureCNN(nn.Module):
    """DQN-Nature conv backbone + dense head (reference models.py:288-328)."""

    features_dim: int = 512
    dtype: Optional[Dtype] = None
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for ch, k, s in ((32, 8, 4), (64, 4, 2), (64, 3, 1)):
            x = nn.Conv(ch, (k, k), strides=(s, s), padding="VALID", dtype=self.dtype, param_dtype=self.param_dtype)(x)
            x = jax.nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(self.features_dim, dtype=self.dtype, param_dtype=self.param_dtype)(x)
        return jax.nn.relu(x)


class LayerNormGRUCell(nn.Module):
    """GRU cell with LayerNorm on the joint projection and -1 update-gate bias
    (reference models.py:331-410, after danijar's dreamerv2 nets.py).

    Call as ``new_h = cell(h, x)`` — scan-ready: the concatenated
    ``[h, x] @ W`` projection is a single MXU matmul per step.

    ``fused=True`` routes eligible shapes through the Pallas TPU kernel
    (``sheeprl_tpu/ops/pallas_gru.py``): projection + LayerNorm + gates in one
    VMEM-resident ``pallas_call``, with the weight matrix pinned in VMEM
    across the batch grid.  The parameter tree is identical to the unfused
    path, so the flag is a pure runtime choice.  ``fused_interpret`` runs the
    kernel in interpreter mode (CPU tests).
    """

    hidden_size: int
    use_bias: bool = True
    layer_norm: bool = True
    norm_eps: float = 1e-3
    dtype: Optional[Dtype] = None
    param_dtype: Dtype = jnp.float32
    fused: bool = False
    fused_interpret: bool = False

    @nn.compact
    def __call__(self, h: jax.Array, x: jax.Array) -> jax.Array:
        joint = jnp.concatenate([h, x], axis=-1)
        dense = nn.Dense(
            3 * self.hidden_size,
            use_bias=self.use_bias,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="Dense_0",
        )
        ln = (
            nn.LayerNorm(
                epsilon=self.norm_eps, dtype=self.dtype, param_dtype=self.param_dtype, name="LayerNorm_0"
            )
            if self.layer_norm
            else None
        )

        use_fused = self.fused and self.layer_norm and joint.ndim == 2
        if use_fused and not self.is_initializing():
            from sheeprl_tpu.ops.pallas_gru import fused_gru_supported, fused_layernorm_gru

            if fused_gru_supported(joint.shape[-1], self.hidden_size) and (
                self.fused_interpret or jax.default_backend() == "tpu"
            ):
                params = self.variables["params"]
                w = params["Dense_0"]["kernel"]
                b = (
                    params["Dense_0"]["bias"]
                    if self.use_bias
                    else jnp.zeros((3 * self.hidden_size,), w.dtype)
                )
                return fused_layernorm_gru(
                    joint,
                    w,
                    b,
                    params["LayerNorm_0"]["scale"],
                    params["LayerNorm_0"]["bias"],
                    h,
                    float(self.norm_eps),
                    self.fused_interpret,
                )

        z = dense(joint)
        if ln is not None:
            z = ln(z)
        reset, cand, update = jnp.split(z, 3, axis=-1)
        reset = jax.nn.sigmoid(reset)
        cand = jnp.tanh(reset * cand)
        update = jax.nn.sigmoid(update - 1)
        return update * cand + (1 - update) * h


class MultiEncoder(nn.Module):
    """Fuse a CNN encoder over stacked pixel keys with an MLP encoder over
    stacked vector keys (reference models.py:413-460)."""

    cnn_encoder: Optional[nn.Module]
    mlp_encoder: Optional[nn.Module]
    cnn_keys: Sequence[str] = ()
    mlp_keys: Sequence[str] = ()

    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self.cnn_encoder is not None and self.cnn_keys:
            x = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-3)
            feats.append(cnn_forward(self.cnn_encoder, x))
        if self.mlp_encoder is not None and self.mlp_keys:
            x = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            feats.append(self.mlp_encoder(x))
        if not feats:
            raise ValueError("MultiEncoder needs at least one of cnn/mlp encoders")
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]


class MultiDecoder(nn.Module):
    """Fan a latent out to per-key reconstructions (reference models.py:478-503).
    Tolerates both decoders being ``None`` (JEPA world model)."""

    cnn_decoder: Optional[nn.Module]
    mlp_decoder: Optional[nn.Module]
    cnn_keys: Sequence[str] = ()
    cnn_channels: Sequence[int] = ()
    mlp_keys: Sequence[str] = ()
    mlp_dims: Sequence[int] = ()

    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None and self.cnn_keys:
            recon = self.cnn_decoder(latent)  # (..., C_total, H, W) CHW by decoder contract
            start = 0
            for k, c in zip(self.cnn_keys, self.cnn_channels):
                out[k] = recon[..., start : start + c, :, :]
                start += c
        if self.mlp_decoder is not None and self.mlp_keys:
            recon = self.mlp_decoder(latent)
            start = 0
            for k, d in zip(self.mlp_keys, self.mlp_dims):
                out[k] = recon[..., start : start + d]
                start += d
        return out
