from sheeprl_tpu.models.blocks import (
    CNN,
    DeCNN,
    LayerNormGRUCell,
    MLP,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
)

__all__ = ["CNN", "DeCNN", "LayerNormGRUCell", "MLP", "MultiDecoder", "MultiEncoder", "NatureCNN"]
