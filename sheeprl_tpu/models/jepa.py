"""JEPA self-supervised blocks (fork feature, reference
/root/reference/sheeprl/models/jepa.py:10-124).

Functional re-design: the reference's `JEPAHead` holds a deep-copied frozen
EMA target branch as module state; here the online projector/predictor and
the target encoder/projector are separate params subtrees, the EMA update is
an `optax.incremental_update` with rate ``1 - ema_m``, and the masking
augmentations are pure keyed functions.  The projector uses LayerNorm in
place of the reference's BatchNorm1d (no mutable batch statistics inside the
jitted step; BYOL-style heads are robust to this substitution).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def _erase_rectangles(x: jax.Array, erase_frac: float) -> jax.Array:
    """Center-crop mask: keep a centered (1-erase_frac) rectangle
    (reference jepa.py:10-22).  ``x`` is (T, B, C, H, W)."""
    T, B, C, H, W = x.shape
    h = max(1, min(H, int(H * (1 - erase_frac))))
    w = max(1, min(W, int(W * (1 - erase_frac))))
    top = (H - h) // 2
    left = (W - w) // 2
    mask = jnp.zeros((1, 1, 1, H, W), dtype=x.dtype)
    mask = mask.at[..., top : top + h, left : left + w].set(1.0)
    return x * mask


def make_two_views(
    obs: Dict[str, jax.Array], key: jax.Array, erase_frac: float = 0.6, vec_dropout: float = 0.2
) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
    """Two stochastic views (reference jepa.py:26-41)."""
    obs_q: Dict[str, jax.Array] = {}
    obs_k: Dict[str, jax.Array] = {}
    for i, (k, v) in enumerate(sorted(obs.items())):
        k1, k2 = jax.random.split(jax.random.fold_in(key, i))
        if v.ndim == 5:  # (T, B, C, H, W)
            obs_q[k] = _erase_rectangles(v, erase_frac)
            obs_k[k] = _erase_rectangles(v, erase_frac)
        else:
            obs_q[k] = v + jax.random.normal(k1, v.shape, v.dtype) * vec_dropout
            obs_k[k] = v + jax.random.normal(k2, v.shape, v.dtype) * vec_dropout
    return obs_q, obs_k


class JEPAProjector(nn.Module):
    """Dense → LayerNorm → ReLU → Dense, mean-pooled over time
    (reference jepa.py:44-60)."""

    proj_dim: int = 1024
    hidden: int = 1024

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        if z.ndim == 3:
            z = jnp.mean(z, axis=0)
        z = nn.Dense(self.hidden)(z)
        z = nn.LayerNorm()(z)
        z = jax.nn.relu(z)
        return nn.Dense(self.proj_dim)(z)


class JEPAPredictor(nn.Module):
    """Dense → ReLU → Dense (reference jepa.py:63-73)."""

    proj_dim: int = 1024
    hidden: int = 1024

    @nn.compact
    def __call__(self, p: jax.Array) -> jax.Array:
        p = nn.Dense(self.hidden)(p)
        p = jax.nn.relu(p)
        return nn.Dense(self.proj_dim)(p)


def l2_normalize(x: jax.Array, eps: float = 1e-6) -> jax.Array:
    return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)


def jepa_loss(
    encode_q,  # callable(obs) -> embeddings using ONLINE encoder params (differentiable)
    encode_k,  # callable(obs) -> embeddings using TARGET encoder params
    projector_def: JEPAProjector,
    predictor_def: JEPAPredictor,
    projector_params,
    predictor_params,
    target_projector_params,
    obs_q: Dict[str, jax.Array],
    obs_k: Dict[str, jax.Array],
) -> jax.Array:
    """Cosine (BYOL-style) loss 2 - 2 <pq, zk> (reference JEPAHead.forward,
    jepa.py:104-117)."""
    zq = encode_q(obs_q)
    zk = jax.lax.stop_gradient(encode_k(obs_k))
    pq = predictor_def.apply(predictor_params, projector_def.apply(projector_params, zq))
    zk = jax.lax.stop_gradient(projector_def.apply(target_projector_params, zk))
    pq = l2_normalize(pq)
    zk = l2_normalize(zk)
    return 2.0 - 2.0 * jnp.mean(jnp.sum(pq * zk, axis=-1))
