"""Hydra-compatible YAML config composition, self-contained.

The reference drives everything through Hydra 1.3 (see
/root/reference/sheeprl/configs/config.yaml and /root/reference/sheeprl/cli.py:358-366).
Hydra/OmegaConf are not available in this image, so this module implements the
subset of Hydra semantics the config tree actually uses:

- a root ``config.yaml`` with a ``defaults`` list of ``group: option`` entries;
- group config files, each optionally with its own ``defaults`` list supporting
  relative entries (``- default``), absolute entries with package relocation
  (``- /optim@optimizer: adam``) and ``- _self_`` ordering;
- ``# @package _global_`` experiment overlays with ``override /group: option``;
- CLI overrides: ``group=option`` to pick a group file, ``a.b.c=value`` for
  dotted value overrides (``+a.b=v`` also accepted);
- ``${a.b}`` absolute interpolation and the ``${now:%fmt}`` resolver;
- ``???`` mandatory-value markers (validated eagerly after composition);
- ``_target_``-based recursive instantiation (:func:`instantiate`).

A ``SHEEPRL_SEARCH_PATH``-style extension point is kept: the env var
``SHEEPRL_TPU_SEARCH_PATH`` may hold a ``:``-separated list of extra config
directories searched *before* the built-in tree (mirrors
/root/reference/hydra_plugins/sheeprl_search_path.py:10-33).
"""

from __future__ import annotations

import datetime
import importlib
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import yaml

from sheeprl_tpu.utils.utils import dotdict

CONFIG_DIR = Path(__file__).parent / "configs"
_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


class _Yaml12Loader(yaml.SafeLoader):
    """SafeLoader with YAML-1.2 float semantics: PyYAML (YAML 1.1) parses
    ``1e-4`` as a *string* because it requires a dot before the exponent;
    Hydra/OmegaConf accept it as a float and the reference's configs rely on
    that (e.g. ``eps: 1e-04`` in configs/algo/ppo.yaml)."""


_Yaml12Loader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:[-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
        |\.[0-9_]+(?:[eE][-+][0-9]+)?
        |[-+]?\.(?:inf|Inf|INF)
        |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def yaml_load(text: str) -> Any:
    return yaml.load(text, Loader=_Yaml12Loader)


class ConfigError(RuntimeError):
    pass


def _search_dirs(extra_dirs: Optional[Sequence[str]] = None) -> List[Path]:
    dirs: List[Path] = []
    env_paths = os.environ.get("SHEEPRL_TPU_SEARCH_PATH", "")
    for p in list(extra_dirs or []) + [d for d in env_paths.split(":") if d]:
        p = Path(p)
        if p.is_dir():
            dirs.append(p)
    dirs.append(CONFIG_DIR)
    return dirs


def _find_config_file(group: str, option: str, dirs: Sequence[Path]) -> Path:
    option = option[:-5] if option.endswith(".yaml") else option
    for d in dirs:
        candidate = d / group / f"{option}.yaml"
        if candidate.is_file():
            return candidate
    raise ConfigError(f"Config '{group}/{option}.yaml' not found in {[str(d) for d in dirs]}")


def _load_yaml(path: Path) -> Tuple[Dict[str, Any], bool]:
    """Load a YAML file. Returns (content, is_global_package)."""
    text = path.read_text()
    is_global = False
    for line in text.splitlines():
        stripped = line.strip()
        if stripped.startswith("# @package"):
            is_global = "_global_" in stripped
            break
        if stripped and not stripped.startswith("#"):
            break
    data = yaml_load(text) or {}
    if not isinstance(data, dict):
        raise ConfigError(f"Top-level YAML in {path} must be a mapping")
    return data, is_global


def deep_merge(base: Dict[str, Any], overlay: Mapping[str, Any]) -> Dict[str, Any]:
    """Merge ``overlay`` into ``base`` (dicts merge recursively, rest replaces)."""
    for k, v in overlay.items():
        if isinstance(v, Mapping) and isinstance(base.get(k), dict):
            deep_merge(base[k], v)
        else:
            base[k] = v.copy() if isinstance(v, dict) else (list(v) if isinstance(v, list) else v)
    return base


def _compose_group_file(group: str, option: str, dirs: Sequence[Path]) -> Dict[str, Any]:
    """Load a group option, recursively resolving its own defaults list."""
    path = _find_config_file(group, option, dirs)
    data, _ = _load_yaml(path)
    defaults = data.pop("defaults", None)
    if defaults is None:
        return data
    result: Dict[str, Any] = {}
    self_merged = False
    for entry in defaults:
        if entry == "_self_":
            deep_merge(result, data)
            self_merged = True
        elif isinstance(entry, str):
            # relative entry within the same group
            deep_merge(result, _compose_group_file(group, entry, dirs))
        elif isinstance(entry, dict):
            for key, value in entry.items():
                key = str(key)
                if key.startswith("override"):
                    raise ConfigError(f"'override' not valid inside group file {path}")
                pkg = None
                src = key
                if "@" in key:
                    src, pkg = key.split("@", 1)
                src = src.lstrip("/")
                sub = _compose_group_file(src, str(value), dirs)
                if pkg is None or pkg == "_here_":
                    deep_merge(result, sub)
                elif pkg == "_global_":
                    deep_merge(result, sub)
                else:
                    node = result
                    for part in pkg.split("."):
                        node = node.setdefault(part, {})
                    deep_merge(node, sub)
        else:
            raise ConfigError(f"Unsupported defaults entry {entry!r} in {path}")
    if not self_merged:
        deep_merge(result, data)
    return result


def _parse_overrides(
    overrides: Sequence[str], dirs: Sequence[Path] = (CONFIG_DIR,)
) -> Tuple[Dict[str, str], Dict[str, Any]]:
    """Split CLI overrides into group selections and dotted value overrides."""
    group_sel: Dict[str, str] = {}
    dotted: Dict[str, Any] = {}
    for ov in overrides:
        if "=" not in ov:
            raise ConfigError(f"Override '{ov}' is not of the form key=value")
        key, _, value = ov.partition("=")
        key = key.lstrip("+~")
        parsed = yaml_load(value) if value != "" else None
        if "." not in key and any((d / key).is_dir() for d in dirs):
            group_sel[key] = str(value)
        else:
            dotted[key] = parsed
    return group_sel, dotted


def _set_dotted(cfg: Dict[str, Any], key: str, value: Any) -> None:
    node = cfg
    parts = key.split(".")
    for part in parts[:-1]:
        nxt = node.get(part)
        if not isinstance(nxt, dict):
            nxt = {}
            node[part] = nxt
        node = nxt
    node[parts[-1]] = value


def _get_dotted(cfg: Mapping[str, Any], key: str) -> Any:
    node: Any = cfg
    for part in key.split("."):
        if not isinstance(node, Mapping) or part not in node:
            raise KeyError(key)
        node = node[part]
    return node


def _resolve_value(value: Any, root: Mapping[str, Any], depth: int = 0) -> Any:
    if depth > 20:
        raise ConfigError(f"Interpolation loop while resolving {value!r}")
    if not isinstance(value, str):
        return value
    matches = list(_INTERP_RE.finditer(value))
    if not matches:
        return value

    def repl(expr: str) -> Any:
        if expr.startswith("now:"):
            return datetime.datetime.now().strftime(expr[4:])
        if expr.startswith("oc.env:") or expr.startswith("env:"):
            parts = expr.split(":", 1)[1].split(",", 1)
            return os.environ.get(parts[0], parts[1] if len(parts) > 1 else "")
        if expr.startswith("eval:"):
            raise ConfigError("eval resolver not supported")
        return _resolve_value(_get_dotted(root, expr), root, depth + 1)

    if len(matches) == 1 and matches[0].span() == (0, len(value)):
        try:
            return repl(matches[0].group(1))
        except KeyError:
            return value
    out = value
    for m in matches:
        try:
            out = out.replace(m.group(0), str(repl(m.group(1))))
        except KeyError:
            pass
    return out


def resolve_interpolations(cfg: Dict[str, Any], root: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
    root = root if root is not None else cfg

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return _resolve_value(node, root)

    return walk(cfg)


def _missing_keys(cfg: Mapping[str, Any], prefix: str = "") -> List[str]:
    missing = []
    for k, v in cfg.items():
        path = f"{prefix}{k}"
        if isinstance(v, Mapping):
            missing.extend(_missing_keys(v, path + "."))
        elif isinstance(v, str) and v == "???":
            missing.append(path)
    return missing


def compose(
    overrides: Sequence[str] = (),
    config_name: str = "config",
    extra_dirs: Optional[Sequence[str]] = None,
    check_missing: bool = True,
) -> dotdict:
    """Compose the full config tree the way ``@hydra.main`` does in the
    reference CLI (/root/reference/sheeprl/cli.py:358-366)."""
    dirs = _search_dirs(extra_dirs)
    root_path = None
    for d in dirs:
        cand = d / f"{config_name}.yaml"
        if cand.is_file():
            root_path = cand
            break
    if root_path is None:
        raise ConfigError(f"Root config '{config_name}.yaml' not found")
    root_data, _ = _load_yaml(root_path)
    root_defaults = root_data.pop("defaults", [])

    group_sel, dotted = _parse_overrides(overrides, dirs)

    # Pass 1: figure out which option each group uses.
    selections: Dict[str, str] = {}
    order: List[str] = []
    self_first = True
    seen_self = False
    for entry in root_defaults:
        if entry == "_self_":
            seen_self = True
            continue
        if isinstance(entry, dict):
            for g, opt in entry.items():
                g = str(g)
                selections[g] = str(opt)
                order.append(g)
            if not seen_self:
                self_first = False
    selections.update(group_sel)
    for g in group_sel:
        if g not in order:
            order.append(g)

    # Experiment overlays are @package _global_ and may override group choices.
    # An exp file's defaults list may also include *sibling* exp files by bare
    # name (e.g. exp/ppo_recurrent.yaml starts from `- ppo`): those merge
    # first, recursively, each applying its own `override /group:` entries.
    exp_entries: List[Tuple[str, Dict[str, Any]]] = []

    def _collect_exp(option: str) -> None:
        path = _find_config_file("exp", option, dirs)
        data, _ = _load_yaml(path)
        for d_entry in data.get("defaults", []):
            if isinstance(d_entry, str):
                if d_entry != "_self_":
                    _collect_exp(d_entry)
            elif isinstance(d_entry, dict):
                for key, value in d_entry.items():
                    key = str(key)
                    if key.startswith("override"):
                        target = key.split("/", 1)[1].strip()
                        # CLI group selections beat the experiment file
                        if target not in group_sel:
                            selections[target] = str(value)
        exp_entries.append(("exp", data))

    for g in list(order):
        opt = selections.get(g, "???")
        if opt == "???":
            continue
        path_try = _find_config_file(g, opt, dirs)
        _, is_global = _load_yaml(path_try)
        if is_global and g in ("exp",):
            _collect_exp(opt)

    missing_groups = [g for g in order if selections.get(g) == "???" and g not in ("exp",)]
    if selections.get("exp") == "???" and not any(g == "exp" for g, _ in exp_entries):
        if "exp" in order and "algo" in group_sel:
            selections.pop("exp", None)
            order.remove("exp")
        elif "exp" in order:
            raise ConfigError("You must specify an experiment: add exp=<name> (e.g. exp=ppo)")

    cfg: Dict[str, Any] = {}
    if self_first:
        deep_merge(cfg, root_data)
    for g in order:
        opt = selections.get(g)
        if opt is None or opt == "???":
            continue
        if g == "exp":
            continue  # merged last, at global package
        try:
            sub = _compose_group_file(g, opt, dirs)
        except ConfigError:
            if g in ("hydra",):  # hydra's own runtime group is not used in this build
                continue
            raise
        deep_merge(cfg.setdefault(g, {}), sub)
    if not self_first:
        deep_merge(cfg, root_data)
    if missing_groups:
        pass  # groups left '???' are tolerated until value validation below

    # Experiment overlay at _global_ package (minus its defaults list).
    for _, data in exp_entries:
        overlay = {k: v for k, v in data.items() if k != "defaults"}
        deep_merge(cfg, overlay)

    # Dotted CLI overrides win over everything.
    for key, value in dotted.items():
        _set_dotted(cfg, key, value)

    cfg = resolve_interpolations(cfg)
    if check_missing:
        missing = _missing_keys(cfg)
        if missing:
            raise ConfigError(f"Mandatory config values left unset (???): {missing}")
    return dotdict(cfg)


def compose_group(
    group: str, option: str = "default", extra_dirs: Optional[Sequence[str]] = None
) -> dotdict:
    """Compose ONE group option outside a full run config (its own defaults
    list resolved, interpolations against itself).  The serve CLI uses this to
    backfill the ``serving`` block for checkpoints archived before the group
    existed."""
    dirs = _search_dirs(extra_dirs)
    sub = _compose_group_file(group, option, dirs)
    return dotdict(resolve_interpolations(sub))


def instantiate(node: Mapping[str, Any] | Any, *args: Any, **kwargs: Any) -> Any:
    """Recursive ``_target_`` instantiation (Hydra's ``hydra.utils.instantiate``).

    ``_partial_: true`` returns a ``functools.partial`` instead of calling.
    """
    import functools

    if not isinstance(node, Mapping) or "_target_" not in node:
        return node
    target = node["_target_"]
    module_name, _, attr = target.rpartition(".")
    obj = getattr(importlib.import_module(module_name), attr)
    def _inst(v: Any) -> Any:
        if isinstance(v, Mapping):
            if "_target_" in v:
                return instantiate(v)
            return {kk: _inst(vv) for kk, vv in v.items()}
        if isinstance(v, list):
            return [_inst(item) for item in v]
        return v

    call_kwargs: Dict[str, Any] = {}
    for k, v in node.items():
        if k in ("_target_", "_partial_", "_convert_"):
            continue
        call_kwargs[k] = _inst(v)
    call_kwargs.update(kwargs)
    if node.get("_partial_", False):
        return functools.partial(obj, *args, **call_kwargs)
    return obj(*args, **call_kwargs)


def get_callable(target: str) -> Any:
    """Import ``module.attr`` from a dotted string (for activation fns etc.)."""
    module_name, _, attr = target.rpartition(".")
    return getattr(importlib.import_module(module_name), attr)
