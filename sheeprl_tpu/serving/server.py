"""The policy server: AOT-compiled batched inference + health-gated
checkpoint hot-reload behind a stdlib HTTP tier.

Cooperating pieces, one process:

* :class:`PolicyService` — owns one model's params (hot-swappable under a
  lock), the per-``(bucket, mode)`` AOT executable cache, and the dispatch
  the batcher drives: assemble the padded slab, snapshot params ONCE, run
  one compiled device step, slice the valid rows.  ``promote`` swaps params
  atomically between dispatches — same shapes hit the existing executables,
  so a promotion never recompiles (a shape-changing checkpoint is rejected
  instead of poisoning the cache).  For stateful policies (``ppo_recurrent``
  LSTM carries, ``dreamer_v3`` RSSM state) the service owns a
  :class:`~sheeprl_tpu.serving.sessions.SessionStore`: recurrent state lives
  in a device-resident slab, gathered/scattered inside the SAME compiled
  step, keyed by the request's ``session`` id (SEED-RL's server-side state,
  R2D2's stored-state discipline);
* :class:`ServeApp` — ``ThreadingHTTPServer`` (the
  ``diagnostics/metrics_server.py`` pattern: handler threads only touch
  lock-protected state) serving ``POST /act``, ``GET /metrics`` (Prometheus
  text, ``sheeprl_serve_*`` / ``sheeprl_sessions_*`` families) and ``GET
  /healthz``.  The app holds a :class:`~sheeprl_tpu.serving.registry.
  ModelRegistry` of N resident models — ``/act`` routes on the request's
  ``model`` field, each model has its own service/watcher/request log, and
  ``/metrics`` renders per-model ``{model="..."}`` series plus unlabeled
  aggregates;
* the watcher — polls a training run's checkpoint dir PER MODEL, gates every
  new checkpoint on the run's health journal
  (:func:`~sheeprl_tpu.serving.loader.checkpoint_health`) and journals the
  decision as ``ckpt_promote`` / ``ckpt_reject`` (with a ``model`` field) in
  the serving run's own reused
  :class:`~sheeprl_tpu.diagnostics.journal.RunJournal`;
* the request log — when ``serving.request_log.enabled``, every dispatched
  batch is appended to a per-model offline dataset stream
  (:class:`~sheeprl_tpu.serving.request_log.RequestLog`) that
  ``OfflineDataset`` opens directly (howto/offline_rl.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from sheeprl_tpu.diagnostics.tracing import TRACE_SERVE_NAME, NullTracer, PhaseTracer
from sheeprl_tpu.serving.batcher import (
    DEFAULT_BUCKETS,
    DynamicBatcher,
    ServeError,
    _percentile,
    pick_bucket,
)
from sheeprl_tpu.serving.loader import (
    PolicyHandle,
    agent_state_from_checkpoint,
    checkpoint_health,
    checkpoint_step,
    latest_checkpoint,
    load_policy,
)
from sheeprl_tpu.serving.registry import ModelEntry, ModelRegistry, render_registry_metrics
from sheeprl_tpu.serving.sessions import SessionStore, make_slab_step

SERVE_GAUGE_PREFIX = "Telemetry/serve/"
SESSIONS_GAUGE_PREFIX = "Telemetry/sessions/"

#: fallback when ``serving.slo.buckets_ms`` is absent
#: (``configs/serving/default.yaml`` mirrors this).  An ALL-CAPS module
#: constant is the one place lint TRC502 allows bucket boundaries to live
#: outside config — call sites must take them from here or from cfg.
DEFAULT_SLO_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0)


class PhaseStats:
    """Per-phase latency accounting for one model: rolling p50/p99 windows
    for the live gauges plus cumulative Prometheus histogram counts
    (``sheeprl_serve_latency_ms_bucket{phase,le}``) with fixed bucket
    boundaries from ``serving.slo.buckets_ms`` — fixed, so series from
    different scrapes/models stay mergeable."""

    PHASES = ("queue", "batch_form", "dispatch", "scatter", "total")

    def __init__(self, buckets_ms: Any = None):
        self.buckets_ms = tuple(float(b) for b in (buckets_ms or DEFAULT_SLO_BUCKETS_MS))
        if list(self.buckets_ms) != sorted(self.buckets_ms):
            raise ValueError(f"serving.slo.buckets_ms must be ascending, got {list(self.buckets_ms)}")
        self._lock = threading.Lock()
        self._window: Dict[str, deque] = {p: deque(maxlen=1024) for p in self.PHASES}
        # per-bin (non-cumulative) counts; the +1 bin is +Inf
        self._bins: Dict[str, List[int]] = {p: [0] * (len(self.buckets_ms) + 1) for p in self.PHASES}
        self._sum: Dict[str, float] = {p: 0.0 for p in self.PHASES}
        self._count: Dict[str, int] = {p: 0 for p in self.PHASES}

    def observe(self, phase: str, value_ms: float) -> None:
        value = max(0.0, float(value_ms))
        bin_i = len(self.buckets_ms)
        for i, le in enumerate(self.buckets_ms):
            if value <= le:
                bin_i = i
                break
        with self._lock:
            self._window[phase].append(value)
            self._bins[phase][bin_i] += 1
            self._sum[phase] += value
            self._count[phase] += 1

    def percentiles(self) -> Dict[str, Tuple[float, float]]:
        """``{phase: (p50_ms, p99_ms)}`` over the rolling window (phases with
        no observations yet are omitted)."""
        out: Dict[str, Tuple[float, float]] = {}
        with self._lock:
            windows = {p: sorted(w) for p, w in self._window.items() if w}
        for phase, values in windows.items():
            out[phase] = (
                round(_percentile(values, 50.0), 3),
                round(_percentile(values, 99.0), 3),
            )
        return out

    def histogram(self) -> Dict[str, Dict[str, Any]]:
        """Cumulative-bucket snapshot per phase:
        ``{phase: {"buckets": [(le, cum_count), ..., ("+Inf", total)],
        "sum": ms, "count": n}}`` — exactly the Prometheus histogram shape."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for phase in self.PHASES:
                if not self._count[phase]:
                    continue
                cum = 0
                buckets: List[Tuple[Any, int]] = []
                for le, n in zip(self.buckets_ms, self._bins[phase]):
                    cum += n
                    buckets.append((le, cum))
                cum += self._bins[phase][-1]
                buckets.append(("+Inf", cum))
                out[phase] = {
                    "buckets": buckets,
                    "sum": round(self._sum[phase], 3),
                    "count": self._count[phase],
                }
        return out


class SloMonitor:
    """Rolling-window latency SLO: burn rate + flood-controlled breach
    journaling.

    Every completed request is classified against ``target_ms``; the burn
    rate is ``bad_fraction / (1 - objective)`` over the last ``window``
    requests (>1.0 = the error budget is being spent faster than the
    objective allows).  Breaches follow the ``diagnostics/health.py``
    confirm-window discipline: ``confirm`` consecutive burn>1 observations
    journal ONE fsync'd ``slo_breach``, recovery journals ``slo_breach_end``,
    and nothing repeats while the breach is active."""

    def __init__(
        self,
        target_ms: Optional[float] = None,
        objective: float = 0.99,
        window: int = 256,
        confirm: int = 8,
        journal: Any = None,
        model: Optional[str] = None,
    ):
        self.target_ms = None if target_ms is None else float(target_ms)
        self.objective = min(0.99999, max(0.0, float(objective)))
        self._journal = journal
        self.model = model
        self._window: deque = deque(maxlen=max(1, int(window)))
        self._confirm = max(1, int(confirm))
        self._lock = threading.Lock()
        self._breaches = 0
        self.active = False
        self.breaches_total = 0
        self.burn = 0.0
        self._active_since_t: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.target_ms is not None

    def observe(self, total_ms: float) -> float:
        if self.target_ms is None:
            return 0.0
        # state transitions decide under the lock; the journal write (and its
        # fsync) happens after release — observe() runs on the batcher thread
        # that snapshot()/healthz contend with, and the journal's own lock
        # already serializes writers. One caller per monitor keeps the
        # breach -> breach_end order on disk.
        breach = None
        breach_end = None
        with self._lock:
            self._window.append(float(total_ms) > self.target_ms)
            bad = sum(self._window)
            budget = max(1e-9, 1.0 - self.objective)
            self.burn = (bad / len(self._window)) / budget
            burn = self.burn
            if burn > 1.0:
                self._breaches += 1
                if self._breaches >= self._confirm and not self.active:
                    self.active = True
                    self.breaches_total += 1
                    self._active_since_t = time.time()
                    breach = {
                        "model": self.model,
                        "burn": round(burn, 4),
                        "target_ms": self.target_ms,
                        "objective": self.objective,
                        "window": len(self._window),
                        "confirm": self._confirm,
                    }
            else:
                self._breaches = 0
                if self.active:
                    self.active = False
                    since = self._active_since_t
                    self._active_since_t = None
                    breach_end = {
                        "model": self.model,
                        "burn": round(burn, 4),
                        "breach_s": None if since is None else round(time.time() - since, 3),
                    }
        if self._journal is not None:
            if breach is not None:
                self._journal.write("slo_breach", **breach)
                self._journal.sync()
            if breach_end is not None:
                self._journal.write("slo_breach_end", **breach_end)
        return burn


class PolicyService:
    """Batched inference over one hot-swappable params tree.

    ``aot=True`` (the default) pre-lowers and compiles one executable per
    ``(bucket width, greedy)`` signature via the same ``lower().compile()``
    path the telemetry layer uses, donating the obs slab's device buffer on
    backends that support donation; ``aot=False`` calls the pure step
    directly (the test seam for host-side fake policies).

    Stateful handles (``handle.stateful``) get a :class:`SessionStore`: the
    compiled step becomes ``(params, state_slab, idx, obs, is_first, key) ->
    (actions, new_slab)`` — gather, recurrent step and scatter fused into the
    one device call, with the slab buffer donated alongside the obs slab.
    """

    def __init__(
        self,
        handle: PolicyHandle,
        serving_cfg: Optional[Mapping[str, Any]] = None,
        journal: Any = None,
        aot: bool = True,
        model: Optional[str] = None,
        tracer: Any = None,
        inject_slow_iter: Optional[int] = None,
    ):
        cfg = dict(serving_cfg or {})
        self.handle = handle
        self._journal = journal
        self._aot = bool(aot)
        self.model = model
        self._tracer = tracer if tracer is not None else NullTracer()
        self.default_greedy = bool(cfg.get("greedy", True))
        buckets = cfg.get("batch_buckets") or list(DEFAULT_BUCKETS)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        # latency breakdown + SLO layer (ISSUE 19): the batcher reports every
        # completed request's phase tiling back through _on_request_done
        slo_cfg = dict(cfg.get("slo") or {})
        self.phase_stats = PhaseStats(slo_cfg.get("buckets_ms"))
        self.slo = SloMonitor(
            target_ms=slo_cfg.get("target_ms"),
            objective=float(slo_cfg.get("objective", 0.99)),
            window=int(slo_cfg.get("window", 256)),
            confirm=int(slo_cfg.get("confirm", 8)),
            journal=journal,
            model=model,
        )
        self.slow_trace_ms = (
            None if slo_cfg.get("slow_trace_ms") is None else float(slo_cfg["slow_trace_ms"])
        )
        self.slow_requests_total = 0
        self.last_slow_request_id: Optional[str] = None
        self._inject_slow_iter = None if inject_slow_iter is None else int(inject_slow_iter)
        if self._inject_slow_iter is not None and self.slow_trace_ms is None:
            # a drill that can never journal its slow_request is a config
            # error, not a silent no-op (the health.py inject discipline)
            raise ValueError(
                "diagnostics.serving.inject_slow_iter requires serving.slo.slow_trace_ms "
                "to be set; the drill exists to fire the slow_request path"
            )
        self.batcher = DynamicBatcher(
            self._dispatch,
            buckets=self.buckets,
            max_delay_ms=float(cfg.get("max_delay_ms", 5.0)),
            max_queue=int(cfg.get("max_queue", 4096)),
            tracer=self._tracer,
            on_request_done=self._on_request_done,
        )
        self.sessions: Optional[SessionStore] = None
        if getattr(handle, "stateful", False):
            sessions_cfg = dict(cfg.get("sessions") or {})
            self.sessions = SessionStore(
                handle.state_spec,
                capacity=int(sessions_cfg.get("capacity", 64)),
                journal=journal,
                model=model,
                device=self._aot,
                tracer=self._tracer,
            )
        # set by ServeApp when serving.request_log.enabled; the dispatch
        # appends every valid row after slicing off the padding
        self.request_log: Any = None
        self._params_lock = threading.Lock()
        self._params = handle.params
        self._params_version = 0
        self.ckpt_step = int(handle.ckpt_step)
        self.ckpt_path = str(handle.ckpt_path)
        self._compile_lock = threading.Lock()
        self._compiled: Dict[Tuple[int, bool], Callable] = {}
        self.compile_count = 0
        # serving counters + self.info mutate from the watcher thread
        # (promote/reject) and the batcher callback (_on_request_done) while
        # snapshot() reads them from HTTP handler threads — one dedicated
        # leaf lock, never held across dispatch, journal, or compile work
        self._stats_lock = threading.Lock()
        self.promotions_total = 0
        self.rejections_total = 0
        self.last_promote_rejected = False
        self._dispatch_counter = 0
        self._base_key = None
        # test seam: a per-dispatch sleep AFTER the params snapshot, so the
        # hot-reload race test can deterministically overlap a promotion with
        # an in-flight batch
        self._step_delay_s: Optional[float] = None
        self.info: Dict[str, Any] = {
            "algo": handle.algo,
            "role": "serve",
            "ckpt_path": self.ckpt_path or None,
            "model": model,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "PolicyService":
        self.batcher.start()
        return self

    def warmup(self) -> None:
        """Compile every (bucket, mode) executable up front so no request —
        including the first ``{"greedy": false}`` one — ever pays an XLA
        compile on the dispatcher thread (which would stall every queued
        request behind it)."""
        for bucket in self.buckets:
            for greedy in (True, False):
                self._compiled_step(bucket, greedy)

    def close(self) -> None:
        self.batcher.close()
        if self.request_log is not None:
            self.request_log.close()
            self.request_log = None

    # -- the compiled step -------------------------------------------------
    def _compiled_step(self, width: int, greedy: bool) -> Callable:
        key = (int(width), bool(greedy))
        fn = self._compiled.get(key)
        if fn is not None:
            return fn
        with self._compile_lock:
            fn = self._compiled.get(key)
            if fn is not None:
                return fn
            if self.sessions is not None:
                compiled = self._build_stateful_step(int(width), bool(greedy))
            else:
                compiled = self._build_stateless_step(int(width), bool(greedy))
            self._compiled[key] = compiled
            return compiled

    def _build_stateless_step(self, width: int, greedy: bool) -> Callable:
        pure = self.handle.make_step(greedy)
        if not self._aot:
            return pure
        import jax

        # the obs slab is consumed by the step — donate its buffer where the
        # backend supports donation (CPU does not; donating there only emits
        # warnings)
        donate = () if jax.default_backend() == "cpu" else (1,)
        jitted = jax.jit(pure, donate_argnums=donate)
        obs0 = self.handle.zero_obs(width)
        key0 = jax.random.PRNGKey(0)
        with self._params_lock:
            params = self._params
        compiled = jitted.lower(params, obs0, key0).compile()
        self.compile_count += 1
        return compiled

    def _build_stateful_step(self, width: int, greedy: bool) -> Callable:
        assert self.sessions is not None
        state_pure = self.handle.make_state_step(greedy)
        if not self._aot:
            # host path (fake-handle tests): the dispatcher gathers/scatters
            # with numpy and calls the per-row step directly
            return state_pure
        import jax
        import jax.numpy as jnp

        pure = make_slab_step(state_pure)
        # both the state slab (arg 1) and the obs slab (arg 3) are consumed:
        # the scatter rebuilds the slab and the obs never outlive the step
        donate = () if jax.default_backend() == "cpu" else (1, 3)
        jitted = jax.jit(pure, donate_argnums=donate)
        obs0 = self.handle.zero_obs(width)
        idx0 = jnp.full((width,), self.sessions.scratch, dtype=jnp.int32)
        isf0 = jnp.ones((width, 1), dtype=jnp.float32)
        key0 = jax.random.PRNGKey(0)
        with self._params_lock:
            params = self._params
        compiled = jitted.lower(params, self.sessions.slab, idx0, obs0, isf0, key0).compile()
        self.compile_count += 1
        return compiled

    def _next_key(self):
        import jax

        if self._base_key is None:
            self._base_key = jax.random.PRNGKey(int(time.time_ns() % (2**31)))
        return jax.random.fold_in(self._base_key, self._dispatch_counter)

    # -- dispatch (called from the batcher thread) -------------------------
    def _maybe_inject_slow(self) -> None:
        """``diagnostics.serving.inject_slow_iter`` fault drill: make exactly
        one dispatch (the Nth) sleep well past ``slo.slow_trace_ms`` so the
        slow_request -> slo_breach -> slo_breach_end chain fires through the
        real request path (journaled like every other injected fault)."""
        if self._inject_slow_iter is None or self._dispatch_counter != self._inject_slow_iter:
            return
        delay_s = max(0.05, 2.0 * float(self.slow_trace_ms or 0.0) / 1000.0)
        if self._journal is not None:
            self._journal.write(
                "fault_injection",
                kind="slow_dispatch",
                model=self.model,
                dispatch_id=self._dispatch_counter,
                delay_s=round(delay_s, 3),
            )
        time.sleep(delay_s)

    def _dispatch(self, rows: List[Dict[str, Any]], greedy: bool) -> Tuple[Any, Dict[str, Any]]:
        width = pick_bucket(len(rows), self.buckets)
        if self.sessions is not None:
            return self._dispatch_stateful(rows, greedy, width)
        obs = self.handle.assemble(rows, width)
        # ONE params snapshot per dispatch: a concurrent promote() swaps the
        # reference for the NEXT dispatch; this batch is internally consistent
        with self._params_lock:
            params = self._params
            version = self._params_version
            step = self.ckpt_step
        if self._step_delay_s:
            time.sleep(self._step_delay_s)
        self._dispatch_counter += 1
        self._maybe_inject_slow()
        fn = self._compiled_step(width, greedy)
        if self._aot:
            import jax

            key = self._next_key() if not greedy else jax.random.PRNGKey(0)
        else:
            key = None
        out = np.asarray(fn(params, obs, key))
        meta = {
            "ckpt_step": step,
            "params_version": version,
            "batch_width": width,
            "batch_rows": len(rows),
            "dispatch_id": self._dispatch_counter,
        }
        valid = out[: len(rows)]
        if self.request_log is not None:
            self.request_log.append(rows, valid)
        return valid, meta

    def _dispatch_stateful(
        self, rows: List[Dict[str, Any]], greedy: bool, width: int
    ) -> Tuple[Any, Dict[str, Any]]:
        """One stateful dispatch: resolve each row's slab slot (LRU checkout
        journals any eviction), then gather/step/scatter in the one compiled
        call.  Padding and sessionless rows ride the scratch slot with
        ``is_first`` forced to 1, so they can never read another session's
        state."""
        assert self.sessions is not None
        obs_rows = [r["obs"] for r in rows]
        obs = self.handle.assemble(obs_rows, width)
        with self._params_lock:
            params = self._params
            version = self._params_version
            step = self.ckpt_step
        if self._step_delay_s:
            time.sleep(self._step_delay_s)
        self._dispatch_counter += 1
        self._maybe_inject_slow()
        idx, is_first, evicted = self.sessions.checkout(
            [r.get("session") for r in rows], [bool(r.get("reset")) for r in rows], width
        )
        fn = self._compiled_step(width, greedy)
        if self._aot:
            import jax
            import jax.numpy as jnp

            key = self._next_key() if not greedy else jax.random.PRNGKey(0)
            actions, new_slab = fn(
                params, self.sessions.slab, jnp.asarray(idx), obs, jnp.asarray(is_first), key
            )
            self.sessions.slab = new_slab
            out = np.asarray(actions)
        else:
            state = self.sessions.gather_np(idx)
            actions, new_state = fn(params, state, obs, is_first, None)
            self.sessions.scatter_np(idx, {k: np.asarray(v) for k, v in new_state.items()})
            out = np.asarray(actions)
        meta = {
            "ckpt_step": step,
            "params_version": version,
            "batch_width": width,
            "batch_rows": len(rows),
            "dispatch_id": self._dispatch_counter,
            "sessions_active": self.sessions.active,
            "session_evictions": len(evicted),
        }
        valid = out[: len(rows)]
        if self.request_log is not None:
            self.request_log.append(obs_rows, valid, is_first[: len(rows)])
        return valid, meta

    # -- request entry (called from HTTP handler threads) ------------------
    def act(
        self,
        obs: Any,
        greedy: Optional[bool] = None,
        timeout_s: float = 30.0,
        session: Optional[str] = None,
        reset: bool = False,
        request_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        row = self.handle.validate(obs)
        use_greedy = self.default_greedy if greedy is None else bool(greedy)
        if self.sessions is None:
            if session is not None:
                raise ServeError(
                    400,
                    f"algorithm {self.handle.algo!r} serves statelessly; "
                    "'session' is only valid for recurrent/model-based policies",
                )
            return self.batcher.submit(row, use_greedy, timeout_s=timeout_s, request_id=request_id)
        sid = None if session is None else str(session)
        # a non-None group key keeps one session's rows out of the same
        # dispatch: its slab slot is gathered at most once per batch, so
        # per-session ordering is exact FIFO (R2D2 stored-state discipline)
        return self.batcher.submit(
            {"obs": row, "session": sid, "reset": bool(reset)},
            use_greedy,
            timeout_s=timeout_s,
            group_key=None if sid is None else ("session", sid),
            request_id=request_id,
        )

    # -- latency/SLO accounting (called from the batcher thread) -----------
    def _on_request_done(self, done: Dict[str, Any]) -> None:
        """Per-completed-request hook: feed the phase histograms, the SLO
        burn window, and — past ``slo.slow_trace_ms`` — journal the one
        fsync'd ``slow_request`` forensics event with the full breakdown."""
        phases = dict(done.get("phases") or {})
        total_ms = float(done.get("total_ms") or 0.0)
        self.phase_stats.observe("queue", phases.get("queue_ms", 0.0))
        self.phase_stats.observe("batch_form", phases.get("batch_form_ms", 0.0))
        self.phase_stats.observe("dispatch", phases.get("dispatch_ms", 0.0))
        self.phase_stats.observe("scatter", phases.get("scatter_ms", 0.0))
        self.phase_stats.observe("total", total_ms)
        self.slo.observe(total_ms)
        if self.slow_trace_ms is None or total_ms <= self.slow_trace_ms:
            return
        meta = dict(done.get("meta") or {})
        rid = done.get("request_id")
        with self._stats_lock:
            self.slow_requests_total += 1
            self.last_slow_request_id = rid
            self.info["last_slow_request_id"] = rid
        if self._journal is not None:
            self._journal.write(
                "slow_request",
                request_id=rid,
                model=self.model,
                total_ms=round(total_ms, 3),
                phases={k: round(float(v), 3) for k, v in phases.items()},
                batch_width=done.get("width"),
                batch_rows=done.get("rows"),
                queue_depth=done.get("queue_depth"),
                sessions_active=meta.get("sessions_active"),
                session_evictions=meta.get("session_evictions"),
                dispatch_id=meta.get("dispatch_id"),
                ckpt_step=meta.get("ckpt_step"),
                timed_out=bool(done.get("abandoned")),
            )
            # forensics must survive a crash right after the slow request
            self._journal.sync()

    def drop_session(self, session: str) -> bool:
        """Explicit session release (``/act`` is fire-and-forget; LRU evicts
        the forgetful)."""
        if self.sessions is None:
            return False
        return self.sessions.drop(str(session))

    # -- hot reload --------------------------------------------------------
    def promote(self, params: Any, step: int, path: str, source: str = "watch") -> bool:
        """Atomically swap the served params.  Same-shaped trees keep every
        compiled executable (AOT cache hit — params are call arguments, not
        trace constants); a different tree is rejected, never half-installed.
        """
        mismatch = self._shape_mismatch(params)
        if mismatch:
            self.reject(path, f"param tree mismatch: {mismatch}")
            return False
        with self._params_lock:
            self._params = params
            self._params_version += 1
            self.ckpt_step = int(step)
            self.ckpt_path = str(path)
        with self._stats_lock:
            self.promotions_total += 1
            self.last_promote_rejected = False
            self.info["ckpt_path"] = str(path)
        if self._journal is not None:
            self._journal.write(
                "ckpt_promote", step=int(step), path=str(path), source=source,
                params_version=self._params_version, model=self.model,
            )
        # a full-height marker on the serving trace: after the trace_report
        # merge, the promotion is visible IN BETWEEN request spans, on the
        # same absolute clock as the training run that wrote the checkpoint
        self._tracer.instant("ckpt_promote", step=int(step), model=self.model)
        return True

    def reject(self, path: str, reason: str, anomalies: Optional[List[Dict[str, Any]]] = None) -> None:
        with self._stats_lock:
            self.rejections_total += 1
            self.last_promote_rejected = True
        if self._journal is not None:
            self._journal.write(
                "ckpt_reject",
                step=checkpoint_step(path),
                path=str(path),
                reason=str(reason),
                model=self.model,
                anomalies=[
                    {"kind": e.get("kind"), "subject": e.get("subject"), "step": e.get("step")}
                    for e in (anomalies or [])
                ],
            )

    def _shape_mismatch(self, params: Any) -> Optional[str]:
        import jax

        with self._params_lock:
            current = self._params
        old_leaves, old_def = jax.tree_util.tree_flatten(current)
        new_leaves, new_def = jax.tree_util.tree_flatten(params)
        if old_def != new_def:
            return "pytree structure changed"
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            o_shape, n_shape = getattr(o, "shape", None), getattr(n, "shape", None)
            if o_shape != n_shape:
                return f"leaf[{i}] shape {o_shape} -> {n_shape}"
            # dtype matters as much as shape: the AOT executables are
            # specialized to the old avals, and a bf16-retrained tree would
            # fail every dispatch AFTER the old params were discarded
            o_dtype, n_dtype = getattr(o, "dtype", None), getattr(n, "dtype", None)
            if o_dtype != n_dtype:
                return f"leaf[{i}] dtype {o_dtype} -> {n_dtype}"
        return None

    # -- observability -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Metrics-server-shaped snapshot: ``render_prometheus`` exports the
        gauges/counters as the ``sheeprl_serve_*`` / ``sheeprl_sessions_*``
        families (schema-registered in ``diagnostics/schema.py``)."""
        stats = self.batcher.stats()
        # one consistent copy of the promote/slow-request stats: the watcher
        # and the batcher callback mutate them under the same lock, so a
        # snapshot never pairs a new counter with a stale info dict
        with self._stats_lock:
            promotions_total = self.promotions_total
            rejections_total = self.rejections_total
            slow_requests_total = self.slow_requests_total
            last_promote_rejected = self.last_promote_rejected
            info = dict(self.info)
        gauges: Dict[str, Any] = {
            SERVE_GAUGE_PREFIX + "queue_depth": stats["queue_depth"],
            SERVE_GAUGE_PREFIX + "ckpt_step": self.ckpt_step,
            SERVE_GAUGE_PREFIX + "last_promote_rejected": int(last_promote_rejected),
        }
        for src, name in (
            ("latency_p50_ms", "latency_p50_ms"),
            ("latency_p99_ms", "latency_p99_ms"),
            ("requests_per_sec", "requests_per_sec"),
            ("batch_width_mean", "batch_width_mean"),
            ("shed_wait_ms", "shed_wait_ms"),
        ):
            if src in stats:
                gauges[SERVE_GAUGE_PREFIX + name] = stats[src]
        # per-phase p50/p99 gauges ("total" already headlines as
        # latency_p50/p99_ms above — from the same accounting window)
        for phase, (p50, p99) in self.phase_stats.percentiles().items():
            if phase == "total":
                continue
            gauges[SERVE_GAUGE_PREFIX + f"{phase}_ms_p50"] = p50
            gauges[SERVE_GAUGE_PREFIX + f"{phase}_ms_p99"] = p99
        if self.slo.enabled:
            gauges[SERVE_GAUGE_PREFIX + "slo_burn"] = round(self.slo.burn, 4)
        counters: Dict[str, Any] = {
            "serve_requests_total": stats["requests_total"],
            "serve_dispatches_total": stats["dispatches_total"],
            "serve_request_errors_total": stats["errors_total"],
            "serve_shed_total": stats["shed_total"],
            "serve_ckpt_promotions_total": promotions_total,
            "serve_ckpt_rejections_total": rejections_total,
            "serve_slow_requests_total": slow_requests_total,
            "serve_slo_breaches_total": self.slo.breaches_total,
        }
        if self.sessions is not None:
            gauges[SESSIONS_GAUGE_PREFIX + "active"] = self.sessions.active
            gauges[SESSIONS_GAUGE_PREFIX + "capacity"] = self.sessions.capacity
            counters["sessions_created_total"] = self.sessions.created_total
            counters["sessions_evictions_total"] = self.sessions.evictions_total
            counters["sessions_overflow_total"] = self.sessions.overflow_total
        if self.request_log is not None:
            rl = self.request_log.stats()
            counters["serve_request_log_rows_total"] = rl["rows_total"]
            counters["serve_request_log_shards_total"] = rl["shards_total"]
        return {
            "info": {k: v for k, v in info.items() if v is not None},
            "gauges": gauges,
            "counters": counters,
            "batch_width_hist": stats["width_hist"],
            "latency_hist": self.phase_stats.histogram(),
        }


def render_serving_metrics(snapshot: Mapping[str, Any]) -> str:
    """Prometheus text for a SINGLE service snapshot: the shared renderer
    plus the batch-width histogram as a labeled counter family.  The app's
    ``/metrics`` endpoint renders the whole registry instead
    (:func:`~sheeprl_tpu.serving.registry.render_registry_metrics`)."""
    from sheeprl_tpu.diagnostics.metrics_server import latency_histogram_lines, render_prometheus

    body = render_prometheus(snapshot)
    hist = snapshot.get("batch_width_hist") or {}
    if hist:
        lines = ["# TYPE sheeprl_serve_batch_width_total counter"]
        for width, count in sorted(hist.items()):
            lines.append(f'sheeprl_serve_batch_width_total{{width="{int(width)}"}} {int(count)}')
        body += "\n".join(lines) + "\n"
    lat_hist = snapshot.get("latency_hist") or {}
    if lat_hist:
        lines = ["# TYPE sheeprl_serve_latency_ms histogram"]
        lines.extend(latency_histogram_lines(lat_hist))
        body += "\n".join(lines) + "\n"
    return body


class CheckpointWatcher(threading.Thread):
    """Poll the checkpoint dir; promote new healthy checkpoints, journal the
    verdict either way.  Also the serving journal's metrics heartbeat."""

    def __init__(
        self,
        service: PolicyService,
        watch_dir: str,
        poll_s: float = 2.0,
        health_gate: bool = True,
        allow_unjournaled: bool = True,
        journal: Any = None,
        journal_every_s: float = 10.0,
    ):
        name = f"sheeprl-serve-watcher-{service.model}" if service.model else "sheeprl-serve-watcher"
        super().__init__(name=name, daemon=True)
        self.service = service
        self.watch_dir = str(watch_dir)
        self.poll_s = max(0.05, float(poll_s))
        self.health_gate = bool(health_gate)
        self.allow_unjournaled = bool(allow_unjournaled)
        self._journal = journal
        self.journal_every_s = max(0.0, float(journal_every_s))
        # health rejections are RETRYABLE — the gate re-evaluates every poll
        # (an anomaly that later journals anomaly_end unblocks the ckpt) but
        # journals ckpt_reject only once per path; shape mismatches are
        # permanent for a path (re-loading the file every poll buys nothing)
        self._rejected_logged: set = set()
        self._rejected_permanent: set = set()
        # newness fallback for foreign filenames (registry artifacts without
        # a ckpt_{step}_{rank} name): promotable iff newer than whatever was
        # installed last — seeded from the initially served checkpoint
        try:
            self._promoted_mtime: Optional[float] = os.path.getmtime(service.ckpt_path)
        except OSError:
            self._promoted_mtime = None
        # NOT named _stop: threading.Thread.join() calls an internal
        # self._stop() on 3.10 and an Event there shadows it
        self._stop_event = threading.Event()
        self._last_journal_t = time.monotonic()

    def stop(self) -> None:
        self._stop_event.set()
        self.join(timeout=5)

    def run(self) -> None:
        while not self._stop_event.wait(self.poll_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 - the watcher must outlive bad files
                pass
            if self._journal is not None and self.journal_every_s:
                now = time.monotonic()
                if now - self._last_journal_t >= self.journal_every_s:
                    self._last_journal_t = now
                    snap = self.service.snapshot()
                    stats = self.service.batcher.stats()
                    self._journal.write(
                        "metrics",
                        step=stats["requests_total"],
                        metrics=snap["gauges"],
                        model=self.service.model,
                    )

    def check_once(self) -> Optional[bool]:
        """One poll: returns True on promote, False on a newly journaled
        reject, None on no-op (exposed for deterministic tests)."""
        candidate = latest_checkpoint(self.watch_dir)
        if candidate is None or candidate in self._rejected_permanent:
            return None
        step = checkpoint_step(candidate)
        try:
            mtime = os.path.getmtime(candidate)
        except OSError:
            return None  # vanished between listing and stat
        if step is not None:
            if step <= self.service.ckpt_step:
                return None
        elif self._promoted_mtime is not None and mtime <= self._promoted_mtime:
            # foreign filename: "newer" falls back to mtime vs the last
            # install, mirroring latest_checkpoint's own ordering fallback
            return None
        ok, reason, anomalies = checkpoint_health(
            candidate, health_gate=self.health_gate, allow_unjournaled=self.allow_unjournaled
        )
        if not ok:
            if candidate in self._rejected_logged:
                return None  # still unhealthy: no reject spam, retry next poll
            self._rejected_logged.add(candidate)
            self.service.reject(candidate, reason, anomalies)
            return False
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(candidate)
        params = self.service.handle.load_params(agent_state_from_checkpoint(state))
        promoted = self.service.promote(
            params, step if step is not None else self.service.ckpt_step, candidate
        )
        if not promoted:
            self._rejected_permanent.add(candidate)
        else:
            self._promoted_mtime = mtime
            self._rejected_logged.discard(candidate)
        return promoted


def _serve_log_dir(cfg) -> str:
    """Versioned serving run dir (``logs/serve/<run_name>/version_N``) —
    the same layout training uses, so journal tooling walks both."""
    base = os.path.join("logs", "serve", str(cfg.get("run_name") or "serve"))
    os.makedirs(base, exist_ok=True)
    versions = [
        int(d.split("_")[1])
        for d in os.listdir(base)
        if d.startswith("version_") and d.split("_")[1].isdigit()
    ]
    log_dir = os.path.join(base, f"version_{max(versions) + 1 if versions else 0}")
    os.makedirs(log_dir, exist_ok=True)
    return log_dir


def _archived_model_cfg(app_cfg, ckpt_path: str):
    """Compose one extra model's run config: its OWN archived ``config.yaml``
    (the checkpoint dir's parent, same layout ``cli.serve`` reads) when
    present, the app config otherwise — always with the app's ``serving``
    block, so every resident model shares one batching/reload policy."""
    import yaml

    from sheeprl_tpu.utils.utils import dotdict

    cfg_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(ckpt_path))), "config.yaml"
    )
    if os.path.isfile(cfg_path):
        with open(cfg_path) as fp:
            cfg = dotdict(yaml.safe_load(fp))
    else:
        cfg = dotdict(dict(app_cfg))
    cfg["serving"] = dict(app_cfg.get("serving") or {})
    return cfg


class ServeApp:
    """Everything the ``serve`` CLI runs: N policies + services + HTTP +
    watchers.

    Built from a composed run config (the checkpoint's archived config with a
    ``serving`` block merged in — ``cli.serve`` does that).  The required
    ``ckpt_path`` becomes the ``default`` model; ``serving.models`` — a
    mapping of ``name: checkpoint_path`` (or ``name: {checkpoint_path,
    watch_dir}``) — adds more residents, each with its own archived config,
    watcher and request log.  ``start`` returns the bound ``(host, port)``;
    tests drive it in-process.  ``app.service`` / ``app.handle`` /
    ``app.watcher`` are the DEFAULT model's (single-model callers never see
    the registry).
    """

    def __init__(self, cfg, ckpt_path: str, watch_dir: Optional[str] = None):
        self.cfg = cfg
        serving_cfg = dict(cfg.get("serving") or {})
        self.host = str(serving_cfg.get("host", "127.0.0.1"))
        self.port = int(serving_cfg.get("port", 0))
        self.request_timeout_s = float(serving_cfg.get("request_timeout_s", 30.0))
        self.log_dir = _serve_log_dir(cfg)
        from sheeprl_tpu.diagnostics.journal import JOURNAL_NAME, RunJournal

        self.journal = RunJournal(os.path.join(self.log_dir, JOURNAL_NAME))
        # per-request serving trace (trace_serve.json): the batcher dispatcher
        # threads and the HTTP handler threads all write spans into this one
        # tracer; its clock_sync anchor (role=server) is what lets
        # tools/trace_report.py merge the serving timeline with the training
        # run's trace.json onto one absolute clock
        trace_cfg = dict(serving_cfg.get("trace") or {})
        if trace_cfg.get("enabled", True):
            self.tracer: Any = PhaseTracer(
                os.path.join(self.log_dir, TRACE_SERVE_NAME),
                run_id=os.path.basename(self.log_dir),
                role="server",
                max_events=trace_cfg.get("max_events"),
                rotate_keep=int(trace_cfg.get("rotate_keep", 2)),
            )
        else:
            self.tracer = NullTracer()
        self.registry = ModelRegistry()
        self._add_model("default", cfg, str(ckpt_path), watch_dir=watch_dir, default=True)
        for name in sorted(serving_cfg.get("models") or {}):
            spec = (serving_cfg.get("models") or {})[name]
            if isinstance(spec, str):
                extra_ckpt, extra_watch = spec, None
            else:
                spec = dict(spec or {})
                extra_ckpt = spec.get("checkpoint_path")
                extra_watch = spec.get("watch_dir")
            if not extra_ckpt:
                raise ValueError(f"serving.models.{name}: checkpoint_path is required")
            self._add_model(
                str(name),
                _archived_model_cfg(cfg, str(extra_ckpt)),
                str(extra_ckpt),
                watch_dir=extra_watch,
            )
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._warmup = bool(serving_cfg.get("warmup", True))

    def _add_model(
        self,
        name: str,
        cfg,
        ckpt_path: str,
        watch_dir: Optional[str] = None,
        default: bool = False,
    ) -> ModelEntry:
        serving_cfg = dict(cfg.get("serving") or {})
        reload_cfg = dict(serving_cfg.get("reload") or {})
        diag_serving = dict((cfg.get("diagnostics") or {}).get("serving") or {})
        handle = load_policy(cfg, ckpt_path)
        service = PolicyService(
            handle,
            serving_cfg,
            journal=self.journal,
            model=name,
            tracer=self.tracer,
            inject_slow_iter=diag_serving.get("inject_slow_iter"),
        )
        service.info["env"] = (cfg.get("env") or {}).get("id")
        service.info["run_id"] = os.path.basename(self.log_dir)
        request_log = None
        rl_cfg = dict(serving_cfg.get("request_log") or {})
        if rl_cfg.get("enabled"):
            from sheeprl_tpu.serving.request_log import RequestLog

            root = rl_cfg.get("dir") or os.path.join(self.log_dir, "requests")
            request_log = RequestLog(
                os.path.join(str(root), name),
                handle,
                model=name,
                rotate_rows=int(rl_cfg.get("rotate_rows", 4096)),
                journal=self.journal,
                tracer=self.tracer,
            )
            service.request_log = request_log
        watcher = None
        if reload_cfg.get("enabled", True):
            watcher = CheckpointWatcher(
                service,
                watch_dir or reload_cfg.get("watch_dir") or os.path.dirname(os.path.abspath(ckpt_path)),
                poll_s=float(reload_cfg.get("poll_s", 2.0)),
                health_gate=bool(reload_cfg.get("health_gate", True)),
                allow_unjournaled=bool(reload_cfg.get("allow_unjournaled", True)),
                journal=self.journal,
                journal_every_s=float(serving_cfg.get("journal_every_s", 10.0)),
            )
        return self.registry.add(
            ModelEntry(
                name=name,
                service=service,
                handle=handle,
                watcher=watcher,
                request_log=request_log,
                meta={"ckpt_path": str(ckpt_path)},
            ),
            default=default,
        )

    # single-model accessors: the default model's pieces (the shape every
    # pre-registry caller and test knows)
    @property
    def service(self) -> PolicyService:
        return self.registry.default.service

    @property
    def handle(self) -> PolicyHandle:
        return self.registry.default.handle

    @property
    def watcher(self) -> Optional[CheckpointWatcher]:
        return self.registry.default.watcher

    @property
    def request_log(self):
        return self.registry.default.request_log

    def start(self) -> Tuple[str, int]:
        registry = self.registry
        timeout_s = self.request_timeout_s
        tracer = self.tracer
        for entry in registry.entries():
            entry.service.start()
            if self._warmup:
                entry.service.warmup()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr spam
                pass

            def _reply(
                self,
                status: int,
                body: bytes,
                content_type: str = "application/json",
                headers: Optional[Dict[str, str]] = None,
            ) -> None:
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for header, value in (headers or {}).items():
                    self.send_header(header, value)
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:  # noqa: N802 - stdlib API
                if self.path.partition("?")[0] != "/act":
                    self._reply(404, b'{"error": "not found"}')
                    return
                # request identity for tracing/forensics: honor the client's
                # X-Request-Id (so an edge proxy's id threads through to the
                # slow_request journal and the trace spans), generate else;
                # always echoed back as a response header
                request_id = str(self.headers.get("X-Request-Id") or uuid.uuid4().hex[:16])
                rid_header = {"X-Request-Id": request_id}
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    entry = registry.get(payload.get("model"))
                    result = entry.service.act(
                        payload.get("obs"),
                        greedy=payload.get("greedy"),
                        timeout_s=min(timeout_s, float(payload.get("timeout_s") or timeout_s)),
                        session=payload.get("session"),
                        reset=bool(payload.get("reset", False)),
                        request_id=request_id,
                    )
                except ServeError as err:
                    headers = dict(rid_header)
                    if err.retry_after is not None:
                        headers["Retry-After"] = str(err.retry_after)
                    self._reply(
                        err.status, json.dumps({"error": str(err)}).encode(), headers=headers
                    )
                    return
                except (ValueError, TypeError, json.JSONDecodeError) as err:
                    self._reply(400, json.dumps({"error": str(err)}).encode(), headers=rid_header)
                    return
                except Exception as err:  # noqa: BLE001 - handler must answer
                    self._reply(500, json.dumps({"error": repr(err)}).encode(), headers=rid_header)
                    return
                with tracer.span("serve-serialize", request_id=request_id):
                    body = {
                        "action": np.asarray(result["action"]).tolist(),
                        **{k: v for k, v in result.items() if k != "action"},
                    }
                    encoded = json.dumps(body).encode()
                self._reply(200, encoded, headers=rid_header)

            def do_GET(self) -> None:  # noqa: N802 - stdlib API
                path = self.path.partition("?")[0]
                try:
                    if path == "/metrics":
                        from sheeprl_tpu.diagnostics.metrics_server import PROMETHEUS_CONTENT_TYPE

                        self._reply(
                            200,
                            render_registry_metrics(registry).encode(),
                            PROMETHEUS_CONTENT_TYPE,
                        )
                    elif path == "/healthz":
                        default = registry.default
                        stats = default.service.batcher.stats()
                        models: Dict[str, Any] = {}
                        for entry in registry.entries():
                            entry_stats = entry.service.batcher.stats()
                            row: Dict[str, Any] = {
                                "algo": entry.handle.algo,
                                "ckpt_step": entry.service.ckpt_step,
                                "ckpt_path": entry.service.ckpt_path,
                                "requests_total": entry_stats["requests_total"],
                                "last_promote_rejected": entry.service.last_promote_rejected,
                                "stateful": bool(getattr(entry.handle, "stateful", False)),
                            }
                            if entry.service.sessions is not None:
                                row["sessions"] = {
                                    "active": entry.service.sessions.active,
                                    "capacity": entry.service.sessions.capacity,
                                    "evictions_total": entry.service.sessions.evictions_total,
                                }
                            models[entry.name] = row
                        self._reply(
                            200,
                            json.dumps(
                                {
                                    "status": "ok",
                                    "algo": default.handle.algo,
                                    "ckpt_step": default.service.ckpt_step,
                                    "ckpt_path": default.service.ckpt_path,
                                    "requests_total": stats["requests_total"],
                                    "last_promote_rejected": default.service.last_promote_rejected,
                                    "models": models,
                                }
                            ).encode(),
                        )
                    else:
                        self._reply(404, b'{"error": "not found"}')
                except Exception as err:  # noqa: BLE001 - snapshot races
                    self._reply(500, json.dumps({"error": repr(err)}).encode())

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sheeprl-serve-http", daemon=True
        )
        self._thread.start()
        for entry in registry.entries():
            if entry.watcher is not None:
                entry.watcher.start()
        host, port = self._server.server_address[:2]
        default = registry.default
        self.journal.write(
            "serve_start",
            algo=default.handle.algo,
            env=(self.cfg.get("env") or {}).get("id"),
            ckpt=default.service.ckpt_path,
            ckpt_step=default.service.ckpt_step,
            host=str(host),
            port=int(port),
            buckets=list(default.service.buckets),
            watch_dir=default.watcher.watch_dir if default.watcher is not None else None,
            models=registry.names(),
        )
        return str(host), int(port)

    @property
    def address(self) -> Tuple[str, int]:
        assert self._server is not None, "ServeApp not started"
        host, port = self._server.server_address[:2]
        return str(host), int(port)

    def close(self, status: str = "completed") -> None:
        for entry in self.registry.entries():
            if entry.watcher is not None:
                entry.watcher.stop()
                entry.watcher = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for entry in self.registry.entries():
            entry.service.close()  # closes the request log too
            entry.request_log = None
        stats = self.service.batcher.stats()
        self.journal.write(
            "metrics",
            step=stats["requests_total"],
            metrics=self.service.snapshot()["gauges"],
            model=self.service.model,
        )
        self.journal.write("run_end", status=status)
        self.journal.close()
        self.tracer.close()


def serve_checkpoint(cfg, ckpt_path: str, watch_dir: Optional[str] = None) -> None:
    """Blocking CLI driver: start the app, print the address, serve until
    interrupted."""
    app = ServeApp(cfg, ckpt_path, watch_dir=watch_dir)
    host, port = app.start()
    extra = ""
    if len(app.registry) > 1:
        extra = f" [models: {', '.join(app.registry.names())}]"
    print(
        f"Serving {app.handle.algo} checkpoint (step {app.service.ckpt_step}) "
        f"at http://{host}:{port}/act  (metrics: /metrics, health: /healthz)" + extra,
        flush=True,
    )
    status = "completed"
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    except BaseException:
        status = "aborted"
        raise
    finally:
        app.close(status)
