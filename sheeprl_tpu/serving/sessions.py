"""The serving session layer: device-resident recurrent state for stateful
policies (ISSUE 16, ROADMAP item 2a).

SEED-RL keeps recurrent state on the inference server so clients stay thin;
R2D2's stored-state discipline says that state must travel WITH the policy
step, never be re-derived.  :class:`SessionStore` implements both for the
batching tier:

* a fixed-capacity **state slab** — one ``[capacity + 1, ...]`` array per
  ``state_spec`` key (the training-side RSSM slab idiom from
  ``data/slab.py``), resident on device under AOT serving.  Row ``capacity``
  is the **scratch slot**: padding rows and sessionless one-shot requests
  gather/scatter there with ``is_first = 1`` forced, so whatever garbage the
  slot holds is reset in-graph before it can influence an action — mixed
  stateless+stateful batches can never cross-contaminate;
* a host-side **LRU table** mapping client session ids to slots.  A new
  session takes the lowest free slot (deterministic allocation ⇒
  deterministic eviction order); when the slab is full the least-recently
  used session NOT in the current batch is evicted with a journaled
  ``session_evict``.  An evicted session that comes back is simply a new
  session: fresh slot, ``is_first = 1``, re-initialized in-graph — the
  re-init parity the golden tests pin.

The dispatcher is the only writer of the slab (one batcher thread), so slab
swaps need no lock; ``checkout`` runs under the table lock because HTTP
handler threads never touch it — they only submit rows.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SessionStore", "make_slab_step"]


def make_slab_step(state_step: Callable) -> Callable:
    """Wrap a pure per-row state step into the slab signature the service
    AOT-compiles: ``(params, slab, idx, obs, is_first, key) -> (actions,
    new_slab)``.  Gather, step and scatter fuse into ONE executable so a
    stateful dispatch is still a single device call; the slab buffer is
    donated on backends that support donation.

    Duplicate indices only ever point at the scratch slot (the batcher's
    session group-key keeps real sessions unique per batch), where
    last-writer-wins scatter is harmless — scratch is reset before every use.
    """
    import jax

    def step(params, slab, idx, obs, is_first, key):
        state = jax.tree_util.tree_map(lambda x: x[idx], slab)
        actions, new_state = state_step(params, state, obs, is_first, key)
        new_slab = jax.tree_util.tree_map(
            lambda s, n: s.at[idx].set(n.astype(s.dtype)), slab, new_state
        )
        return actions, new_slab

    return step


class SessionStore:
    """Fixed-capacity session table + state slab (host or device arrays).

    ``device=True`` keeps the slab as jax arrays for the AOT path;
    ``device=False`` (the fake-handle test seam) keeps numpy and steps with
    plain fancy indexing.
    """

    def __init__(
        self,
        state_spec: Dict[str, Tuple[Tuple[int, ...], str]],
        capacity: int,
        journal: Any = None,
        model: Optional[str] = None,
        device: bool = True,
        tracer: Any = None,
    ):
        if capacity <= 0:
            raise ValueError(f"sessions.capacity must be > 0, got {capacity}")
        self.capacity = int(capacity)
        self.scratch = self.capacity  # slot index of the scratch row
        self.state_spec = dict(state_spec)
        self._journal = journal
        self._tracer = tracer
        self.model = model
        self._device = bool(device)
        rows = self.capacity + 1
        slab = {
            k: np.zeros((rows,) + tuple(shape), dtype=dtype)
            for k, (shape, dtype) in self.state_spec.items()
        }
        if self._device:
            import jax.numpy as jnp

            slab = {k: jnp.asarray(v) for k, v in slab.items()}
        self.slab: Dict[str, Any] = slab
        self._lru: "OrderedDict[str, int]" = OrderedDict()  # session id -> slot
        self._free: List[int] = list(range(self.capacity))
        self._lock = threading.Lock()
        self.created_total = 0
        self.evictions_total = 0
        self.overflow_total = 0

    # -- table --------------------------------------------------------------
    def checkout(
        self,
        session_ids: Sequence[Optional[str]],
        resets: Sequence[bool],
        width: int,
    ) -> Tuple[np.ndarray, np.ndarray, List[Dict[str, Any]]]:
        """Resolve one batch: ``(idx [width] int32, is_first [width, 1]
        float32, evicted records)``.  Padding rows map to scratch with
        ``is_first = 1``; so do sessionless rows and — when every slot is
        pinned by this very batch — overflow sessions (which then simply are
        not resident yet; they allocate on a later dispatch)."""
        if self._tracer is not None:
            with self._tracer.span("serve-session-checkout", rows=len(session_ids)):
                return self._checkout(session_ids, resets, width)
        return self._checkout(session_ids, resets, width)

    def _checkout(
        self,
        session_ids: Sequence[Optional[str]],
        resets: Sequence[bool],
        width: int,
    ) -> Tuple[np.ndarray, np.ndarray, List[Dict[str, Any]]]:
        idx = np.full((int(width),), self.scratch, dtype=np.int32)
        is_first = np.ones((int(width), 1), dtype=np.float32)
        evicted: List[Dict[str, Any]] = []
        with self._lock:
            busy = {self._lru[s] for s in session_ids if s is not None and s in self._lru}
            for i, (sid, reset) in enumerate(zip(session_ids, resets)):
                if sid is None:
                    continue  # one-shot row: scratch + reset
                slot = self._lru.get(sid)
                if slot is None:
                    slot = self._allocate(sid, busy, evicted)
                    if slot is None:
                        self.overflow_total += 1
                        continue  # slab fully pinned by this batch: scratch
                    busy.add(slot)
                else:
                    self._lru.move_to_end(sid)
                    is_first[i, 0] = 1.0 if reset else 0.0
                idx[i] = slot
        for record in evicted:
            if self._journal is not None:
                self._journal.write("session_evict", **record)
        return idx, is_first, evicted

    def _allocate(
        self, sid: str, busy: set, evicted: List[Dict[str, Any]]
    ) -> Optional[int]:
        """Lowest free slot, else evict the LRU session not pinned by the
        current batch.  Caller holds the lock."""
        if self._free:
            slot = self._free.pop(0)
        else:
            victim = next((s for s in self._lru if self._lru[s] not in busy), None)
            if victim is None:
                return None
            slot = self._lru.pop(victim)
            self.evictions_total += 1
            evicted.append(
                {
                    "session": victim,
                    "slot": int(slot),
                    "model": self.model,
                    "resident": len(self._lru),
                    "capacity": self.capacity,
                }
            )
        self._lru[sid] = slot
        self.created_total += 1
        return slot

    def drop(self, session_id: str) -> bool:
        """Explicit release (client says goodbye); no eviction journal."""
        with self._lock:
            slot = self._lru.pop(session_id, None)
            if slot is None:
                return False
            self._free.append(slot)
            self._free.sort()
            return True

    @property
    def active(self) -> int:
        with self._lock:
            return len(self._lru)

    def sessions(self) -> List[str]:
        with self._lock:
            return list(self._lru)

    # -- slab (dispatcher thread only) --------------------------------------
    def gather_np(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v)[idx] for k, v in self.slab.items()}

    def scatter_np(self, idx: np.ndarray, new_state: Dict[str, np.ndarray]) -> None:
        for k, arr in self.slab.items():
            arr = np.asarray(arr)
            arr[idx] = np.asarray(new_state[k], dtype=arr.dtype)
            self.slab[k] = arr
