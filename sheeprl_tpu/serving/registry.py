"""The model registry: N resident policies on one serving mesh (ISSUE 16).

One :class:`ServeApp` process can hold several :class:`PolicyHandle`s — a
canary next to the stable policy, a Dreamer agent next to its SAC distiller —
each with its OWN service (params, AOT executable cache keyed per
``(model, bucket, mode)`` by construction, dynamic batcher, session slab,
request log) and its own checkpoint watcher + health gate, all journaling
into the one serving journal with a ``model`` field.  ``/act`` routes on the
request's ``model`` field (absent -> the default model); ``/metrics`` renders
every ``sheeprl_serve_*`` / ``sheeprl_sessions_*`` family twice — per-model
labeled series (``{model="..."}``) for dashboards, plus an unlabeled
aggregate so single-model tooling (run_monitor's serving panel) keeps
working unchanged.

Cross-model requests never share a dispatch — they run different params —
so per-model batchers lose nothing; what IS shared is the process, the mesh
and the journal.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from sheeprl_tpu.serving.batcher import ServeError

__all__ = ["ModelEntry", "ModelRegistry", "render_registry_metrics"]


@dataclass
class ModelEntry:
    """Everything one resident model owns."""

    name: str
    service: Any  # PolicyService
    handle: Any  # PolicyHandle
    watcher: Any = None  # Optional[CheckpointWatcher]
    request_log: Any = None  # Optional[RequestLog]
    meta: Dict[str, Any] = field(default_factory=dict)


class ModelRegistry:
    """Name -> :class:`ModelEntry`, with a default for model-less requests."""

    def __init__(self) -> None:
        self._entries: "Dict[str, ModelEntry]" = {}
        self.default_name: Optional[str] = None

    def add(self, entry: ModelEntry, default: bool = False) -> ModelEntry:
        if entry.name in self._entries:
            raise ValueError(f"model {entry.name!r} is already registered")
        self._entries[entry.name] = entry
        if default or self.default_name is None:
            self.default_name = entry.name
        return entry

    def get(self, name: Optional[str] = None) -> ModelEntry:
        key = str(name) if name else self.default_name
        entry = self._entries.get(key) if key else None
        if entry is None:
            raise ServeError(
                404, f"unknown model {name!r}; resident models: {sorted(self._entries)}"
            )
        return entry

    def names(self) -> List[str]:
        return sorted(self._entries)

    def entries(self) -> List[ModelEntry]:
        return [self._entries[n] for n in self.names()]

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    @property
    def default(self) -> ModelEntry:
        return self.get(None)


# ---------------------------------------------------------------------------
# /metrics rendering
# ---------------------------------------------------------------------------

#: gauge families whose unlabeled aggregate SUMS across models; the rest
#: aggregate by max (latencies: the worst model is the honest headline)
_SUM_GAUGES = {
    "serve_queue_depth",
    "serve_requests_per_sec",
    "sessions_active",
    "sessions_capacity",
}


def render_registry_metrics(registry: ModelRegistry) -> str:
    """Prometheus text for every resident model: one ``# TYPE`` line per
    family (a second TYPE line for the same name is a parse error), then the
    ``{model="..."}`` series, then the unlabeled aggregate LAST so a naive
    last-wins parser reads the fleet total."""
    from sheeprl_tpu.diagnostics.metrics_server import (
        METRIC_PREFIX,
        _escape_label,
        _metric_name,
        latency_histogram_lines,
    )

    entries = registry.entries()
    snaps = {e.name: e.service.snapshot() for e in entries}
    default_snap = snaps.get(registry.default_name) or next(iter(snaps.values()), {})
    lines: List[str] = []

    info = dict(default_snap.get("info") or {})
    info["models"] = ",".join(registry.names())
    lines.append("# HELP sheeprl_run_info Run identity (labels carry the data; value is 1).")
    lines.append("# TYPE sheeprl_run_info gauge")
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in sorted(info.items()) if v is not None)
    lines.append(f"sheeprl_run_info{{{inner}}} 1")
    lines.append("# TYPE sheeprl_up gauge")
    lines.append("sheeprl_up 1")
    lines.append("# TYPE sheeprl_serve_models gauge")
    lines.append(f"sheeprl_serve_models {len(registry)}")

    def _family(kind: str, name: str, per_model: Dict[str, float], aggregate: float) -> None:
        full = METRIC_PREFIX + name
        lines.append(f"# TYPE {full} {kind}")
        for model in sorted(per_model):
            lines.append(f'{full}{{model="{_escape_label(model)}"}} {per_model[model]:g}')
        lines.append(f"{full} {aggregate:g}")

    def _num(value: Any) -> Optional[float]:
        try:
            return float(value)
        except (TypeError, ValueError):
            return None

    # gauges (Telemetry/... keys -> exported names)
    gauge_names: List[str] = []
    gauge_values: Dict[str, Dict[str, float]] = {}
    for model, snap in snaps.items():
        for key, value in (snap.get("gauges") or {}).items():
            num = _num(value)
            if num is None:
                continue
            name = _metric_name(key)
            if name not in gauge_values:
                gauge_values[name] = {}
                gauge_names.append(name)
            gauge_values[name][model] = num
    for name in sorted(gauge_names):
        per_model = gauge_values[name]
        if name == "serve_ckpt_step":
            aggregate = per_model.get(registry.default_name, max(per_model.values()))
        elif name in _SUM_GAUGES:
            aggregate = sum(per_model.values())
        else:
            aggregate = max(per_model.values())
        _family("gauge", name, per_model, aggregate)

    # counters (sum-aggregated by definition)
    counter_names: List[str] = []
    counter_values: Dict[str, Dict[str, float]] = {}
    for model, snap in snaps.items():
        for key, value in (snap.get("counters") or {}).items():
            num = _num(value)
            if num is None:
                continue
            if key not in counter_values:
                counter_values[key] = {}
                counter_names.append(key)
            counter_values[key][model] = num
    for name in sorted(counter_names):
        per_model = counter_values[name]
        _family("counter", name, per_model, sum(per_model.values()))

    # the batch-width histogram: {model, width} series + width-only aggregate
    width_totals: Dict[int, float] = {}
    width_lines: List[str] = []
    for model in sorted(snaps):
        hist = snaps[model].get("batch_width_hist") or {}
        for width, count in sorted(hist.items()):
            width_lines.append(
                f'sheeprl_serve_batch_width_total{{model="{_escape_label(model)}",width="{int(width)}"}} '
                f"{int(count)}"
            )
            width_totals[int(width)] = width_totals.get(int(width), 0) + int(count)
    if width_lines:
        lines.append("# TYPE sheeprl_serve_batch_width_total counter")
        lines.extend(width_lines)
        for width, count in sorted(width_totals.items()):
            lines.append(f'sheeprl_serve_batch_width_total{{width="{width}"}} {count:g}')

    # the per-phase latency histogram: {model, phase, le} series + unlabeled
    # {phase, le} aggregate (bucket boundaries are fixed by config, so
    # cumulative counts sum across models without re-binning)
    agg: Dict[str, Dict[str, Any]] = {}
    hist_lines: List[str] = []
    for model in sorted(snaps):
        hist = snaps[model].get("latency_hist") or {}
        hist_lines.extend(latency_histogram_lines(hist, model=model))
        for phase, entry in hist.items():
            slot = agg.setdefault(phase, {"buckets": {}, "sum": 0.0, "count": 0})
            for le, count in entry.get("buckets") or []:
                key = str(le)
                slot["buckets"][key] = (le, slot["buckets"].get(key, (le, 0))[1] + count)
            slot["sum"] += float(entry.get("sum") or 0.0)
            slot["count"] += int(entry.get("count") or 0)
    if hist_lines:
        lines.append("# TYPE sheeprl_serve_latency_ms histogram")
        lines.extend(hist_lines)
        if len(snaps) > 1:
            agg_hist = {
                phase: {
                    "buckets": list(slot["buckets"].values()),
                    "sum": slot["sum"],
                    "count": slot["count"],
                }
                for phase, slot in agg.items()
            }
            lines.extend(latency_histogram_lines(agg_hist))
        else:
            # single model: the labeled series already tell the whole story;
            # re-render them unlabeled so single-model tooling needs no labels
            only = next(iter(snaps.values()), {})
            lines.extend(latency_histogram_lines(only.get("latency_hist") or {}))
    return "\n".join(lines) + "\n"
