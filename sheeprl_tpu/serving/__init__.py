"""Policy-as-a-service: a batched, hot-reloading, stateful multi-model
inference tier for checkpointed agents (ROADMAP item 3).

Training produces checkpoints; this package serves them.  The architecture is
SEED-RL-style centralized batched inference (Espeholt et al., 2020) adapted to
a single-process XLA server on the repo's own building blocks:

* :mod:`~sheeprl_tpu.serving.loader` — checkpoint discovery + per-algo policy
  adapters: stateless (``ppo`` / ``a2c`` / ``sac``) and stateful
  (``ppo_recurrent`` LSTM carries, ``dreamer_v3`` RSSM state, served through
  the session layer) built on ``utils/checkpoint.py`` and the existing agent
  builders, plus the health gate that reads the *training* run's journal
  (``active_anomalies``) before a checkpoint may be promoted;
* :mod:`~sheeprl_tpu.serving.batcher` — the dynamic request batcher: requests
  queue for up to ``serving.max_delay_ms``, are padded to the nearest
  MXU-friendly bucket width (``serving.batch_buckets``, defaults derived from
  the PERF.md §4 batch-width table) and dispatched as ONE device step; padded
  rows never leak into responses; beyond ``serving.max_queue`` load is shed
  with 503 + ``Retry-After``;
* :mod:`~sheeprl_tpu.serving.sessions` — device-resident recurrent state for
  stateful policies: a fixed-capacity state slab gathered/scattered inside
  the compiled step, keyed by client session id, LRU-evicted (journaled
  ``session_evict``) when full;
* :mod:`~sheeprl_tpu.serving.registry` — N resident models on one server:
  per-model services/watchers/request logs, ``/act`` routing on the request's
  ``model`` field, per-model ``{model="..."}`` metric series;
* :mod:`~sheeprl_tpu.serving.request_log` — dispatched ``/act`` traffic
  appended to per-model offline dataset shards (``data/datasets.py`` format,
  journaled ``request_log_rotate``) that ``OfflineDataset`` opens directly;
* :mod:`~sheeprl_tpu.serving.server` — :class:`PolicyService` (AOT-compiled
  per-bucket policy steps, atomic params hot-swap under the dispatch lock,
  journaled ``ckpt_promote``/``ckpt_reject``), the stdlib HTTP tier
  (``POST /act`` + ``/metrics`` + ``/healthz``, same pattern as
  ``diagnostics/metrics_server.py``) and the checkpoint-directory watcher.

Entrypoints: ``python -m sheeprl_tpu serve checkpoint_path=...`` /
``tools/serve.py`` / the ``sheeprl-serve`` console script.  See
``howto/serving.md``.
"""

from __future__ import annotations

from sheeprl_tpu.serving.batcher import DynamicBatcher, ServeError, pick_bucket
from sheeprl_tpu.serving.loader import (
    PolicyHandle,
    agent_state_from_checkpoint,
    build_policy,
    checkpoint_health,
    checkpoint_step,
    latest_checkpoint,
    load_policy,
)
from sheeprl_tpu.serving.registry import ModelEntry, ModelRegistry, render_registry_metrics
from sheeprl_tpu.serving.request_log import RequestLog
from sheeprl_tpu.serving.server import PolicyService, ServeApp, serve_checkpoint
from sheeprl_tpu.serving.sessions import SessionStore, make_slab_step

__all__ = [
    "DynamicBatcher",
    "ModelEntry",
    "ModelRegistry",
    "PolicyHandle",
    "PolicyService",
    "RequestLog",
    "ServeApp",
    "ServeError",
    "SessionStore",
    "agent_state_from_checkpoint",
    "build_policy",
    "checkpoint_health",
    "checkpoint_step",
    "latest_checkpoint",
    "load_policy",
    "make_slab_step",
    "pick_bucket",
    "render_registry_metrics",
    "serve_checkpoint",
]
