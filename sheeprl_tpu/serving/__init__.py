"""Policy-as-a-service: a batched, hot-reloading inference tier for
checkpointed agents (ROADMAP item 3).

Training produces checkpoints; this package serves them.  The architecture is
SEED-RL-style centralized batched inference (Espeholt et al., 2020) adapted to
a single-process XLA server on the repo's own building blocks:

* :mod:`~sheeprl_tpu.serving.loader` — checkpoint discovery + per-algo policy
  adapters (``ppo`` / ``a2c`` / ``sac``) built on ``utils/checkpoint.py`` and
  the existing agent builders, plus the health gate that reads the *training*
  run's journal (``active_anomalies``) before a checkpoint may be promoted;
* :mod:`~sheeprl_tpu.serving.batcher` — the dynamic request batcher: requests
  queue for up to ``serving.max_delay_ms``, are padded to the nearest
  MXU-friendly bucket width (``serving.batch_buckets``, defaults derived from
  the PERF.md §4 batch-width table) and dispatched as ONE device step; padded
  rows never leak into responses;
* :mod:`~sheeprl_tpu.serving.server` — :class:`PolicyService` (AOT-compiled
  per-bucket policy steps, atomic params hot-swap under the dispatch lock,
  journaled ``ckpt_promote``/``ckpt_reject``), the stdlib HTTP tier
  (``POST /act`` + ``/metrics`` + ``/healthz``, same pattern as
  ``diagnostics/metrics_server.py``) and the checkpoint-directory watcher.

Entrypoints: ``python -m sheeprl_tpu serve checkpoint_path=...`` /
``tools/serve.py`` / the ``sheeprl-serve`` console script.  See
``howto/serving.md``.
"""

from __future__ import annotations

from sheeprl_tpu.serving.batcher import DynamicBatcher, ServeError, pick_bucket
from sheeprl_tpu.serving.loader import (
    PolicyHandle,
    build_policy,
    checkpoint_health,
    checkpoint_step,
    latest_checkpoint,
    load_policy,
)
from sheeprl_tpu.serving.server import PolicyService, ServeApp, serve_checkpoint

__all__ = [
    "DynamicBatcher",
    "PolicyHandle",
    "PolicyService",
    "ServeApp",
    "ServeError",
    "build_policy",
    "checkpoint_health",
    "checkpoint_step",
    "latest_checkpoint",
    "load_policy",
    "pick_bucket",
    "serve_checkpoint",
]
