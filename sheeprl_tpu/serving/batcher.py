"""Dynamic request batching: many concurrent ``/act`` requests, one device
dispatch.

The capacity model is PERF.md §4: a single-row policy apply leaves almost the
whole MXU idle, and throughput rises with batch rows essentially for free
until the systolic array saturates.  So requests queue for up to
``max_delay_ms`` (or until a full bucket is waiting), the group is padded to
the nearest bucket width — every width the service ever dispatches is one of
``batch_buckets``, so the AOT executable cache never grows past
``len(buckets) x modes`` entries and steady-state serving never compiles —
and ONE dispatch fans its rows back out to the waiting requests.

The batcher owns queueing, grouping, timing and stats; what a dispatch *is*
(slab assembly, params snapshot, the compiled step) is the ``dispatch_fn``
the service injects — which is also the seam the hot-reload race test uses to
make dispatches deterministically slow.

Threading model: HTTP handler threads block in :meth:`DynamicBatcher.submit`;
one daemon dispatcher thread drains the queue.  A params hot-swap never talks
to the batcher at all — the service snapshots params once per dispatch, so a
promotion lands between dispatches, never inside one.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: MXU-friendly default widths (PERF.md §4: MFU rises monotonically with
#: batch rows; 8 is the smallest width worth a dispatch, 128 the systolic
#: array's row count).  ``configs/serving/default.yaml`` mirrors this.
DEFAULT_BUCKETS: Tuple[int, ...] = (8, 16, 32, 64, 128)


class ServeError(RuntimeError):
    """Request-level failure with an HTTP status (the server maps it).
    ``retry_after`` (seconds, load-shed 503s) becomes the ``Retry-After``
    header so well-behaved clients back off instead of hammering."""

    def __init__(self, status: int, message: str, retry_after: Optional[float] = None):
        super().__init__(message)
        self.status = int(status)
        self.retry_after = None if retry_after is None else max(1, int(round(retry_after)))


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``n`` (callers cap group size at ``max(buckets)``,
    so there is always one)."""
    for b in sorted(int(x) for x in buckets):
        if b >= int(n):
            return b
    raise ValueError(f"group of {n} exceeds the largest bucket {max(buckets)}")


class _Request:
    __slots__ = ("row", "greedy", "t_enqueue", "event", "result", "error", "abandoned", "group_key")

    def __init__(
        self,
        row: Dict[str, np.ndarray],
        greedy: bool,
        t_enqueue: float,
        group_key: Optional[Any] = None,
    ):
        self.row = row
        self.greedy = bool(greedy)
        self.t_enqueue = t_enqueue
        # rows sharing a non-None group_key never share a dispatch: the
        # session layer keys this by session id so one batch gathers each
        # session's state at most once (per-session FIFO stays exact)
        self.group_key = group_key
        self.event = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[ServeError] = None
        # set when the client's submit() gave up: if still queued the request
        # is removed outright; if already in flight its stats are skipped so
        # one stalled dispatch cannot poison the latency percentiles
        self.abandoned = False


class DynamicBatcher:
    """FIFO queue + one dispatcher thread + request/latency accounting.

    ``dispatch_fn(rows, greedy)`` must return ``(actions, meta)`` where
    ``actions`` is array-like with one leading row per *valid* request (padded
    rows already sliced off) and ``meta`` is a dict merged into every
    response (``ckpt_step``, ``batch_width``, ``params_version``, ...).
    """

    def __init__(
        self,
        dispatch_fn: Callable[[List[Dict[str, np.ndarray]], bool], Tuple[Any, Dict[str, Any]]],
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_delay_ms: float = 5.0,
        max_queue: int = 4096,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not buckets:
            raise ValueError("batch_buckets must not be empty")
        self._dispatch_fn = dispatch_fn
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if self.buckets[0] < 1:
            raise ValueError(f"batch buckets must be >= 1, got {self.buckets}")
        self.max_batch = self.buckets[-1]
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1000.0
        self.max_queue = int(max_queue)
        self._clock = clock
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # stats (all under _cond to keep one lock discipline)
        self.requests_total = 0
        self.responses_total = 0
        self.errors_total = 0
        self.shed_total = 0
        self.dispatches_total = 0
        self.rows_total = 0
        self.width_hist: Dict[int, int] = {}
        self._latency_ms: deque = deque(maxlen=4096)
        self._done_t: deque = deque(maxlen=4096)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "DynamicBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="sheeprl-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        with self._cond:
            self._stop = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for req in pending:
            req.error = ServeError(503, "server shutting down")
            req.event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- client side -------------------------------------------------------
    def submit(
        self,
        row: Dict[str, np.ndarray],
        greedy: bool,
        timeout_s: float = 30.0,
        group_key: Optional[Any] = None,
    ) -> Dict[str, Any]:
        """Enqueue one observation row; block until its batch dispatched.

        Returns ``{"action": np.ndarray, **dispatch_meta, "queued_ms": float}``.
        Raises :class:`ServeError` on overload (503 + Retry-After: load is
        shed at the door, never buffered unboundedly), shutdown (503) or
        timeout (504).
        """
        req = _Request(row, greedy, self._clock(), group_key=group_key)
        with self._cond:
            if self._stop:
                raise ServeError(503, "server shutting down")
            if len(self._queue) >= self.max_queue:
                self.errors_total += 1
                self.shed_total += 1
                raise ServeError(
                    503,
                    f"request queue full ({self.max_queue})",
                    retry_after=self._shed_retry_after_locked(),
                )
            self.requests_total += 1
            self._queue.append(req)
            self._cond.notify_all()
        if not req.event.wait(timeout_s):
            with self._cond:
                self.errors_total += 1
                req.abandoned = True
                try:
                    # still queued: drop it so it never wastes a batch slot
                    self._queue.remove(req)
                except ValueError:
                    pass  # already popped for dispatch; stats are skipped
            raise ServeError(504, f"no dispatch within {timeout_s:g}s")
        if req.error is not None:
            raise req.error
        assert req.result is not None
        return req.result

    # -- dispatcher thread -------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait()
                if self._stop:
                    return
                head = self._queue[0]
                deadline = head.t_enqueue + self.max_delay_s
                while not self._stop:
                    ready = self._group_len()
                    now = self._clock()
                    if ready >= self.max_batch or now >= deadline:
                        break
                    self._cond.wait(max(0.001, deadline - now))
                if self._stop:
                    return
                group: List[_Request] = []
                taken: set = set()
                while (
                    self._queue
                    and len(group) < self.max_batch
                    and self._queue[0].greedy == head.greedy
                    and (self._queue[0].group_key is None or self._queue[0].group_key not in taken)
                ):
                    req = self._queue.popleft()
                    if req.group_key is not None:
                        taken.add(req.group_key)
                    group.append(req)
            self._dispatch_group(group)

    def _group_len(self) -> int:
        """Contiguous head run with one greedy flag and unique non-None group
        keys (a mixed queue dispatches the head mode first; a repeated
        session stays queued — per-session order is exact FIFO)."""
        if not self._queue:
            return 0
        flag = self._queue[0].greedy
        taken: set = set()
        n = 0
        for req in self._queue:
            if req.greedy != flag or n >= self.max_batch:
                break
            if req.group_key is not None:
                if req.group_key in taken:
                    break
                taken.add(req.group_key)
            n += 1
        return n

    def _shed_retry_after_locked(self) -> float:
        """Advisory Retry-After for a shed request: the time the current
        backlog needs to drain at the observed service rate, floored at 1s.
        Caller holds ``_cond``."""
        done = list(self._done_t)
        if len(done) >= 2 and done[-1] > done[0]:
            rate = (len(done) - 1) / (done[-1] - done[0])
            if rate > 0:
                return min(60.0, max(1.0, len(self._queue) / rate))
        return 1.0

    def _dispatch_group(self, group: List[_Request]) -> None:
        try:
            actions, meta = self._dispatch_fn([r.row for r in group], group[0].greedy)
        except Exception as err:  # noqa: BLE001 - every waiter must wake
            error = err if isinstance(err, ServeError) else ServeError(500, f"dispatch failed: {err!r}")
            with self._cond:
                self.errors_total += len(group)
            for req in group:
                req.error = error
                req.event.set()
            return
        now = self._clock()
        width = int(meta.get("batch_width", len(group)))
        with self._cond:
            self.dispatches_total += 1
            self.rows_total += len(group)  # device work actually dispatched
            self.width_hist[width] = self.width_hist.get(width, 0) + 1
            for req in group:
                if req.abandoned:
                    continue  # its client already took the 504
                self._latency_ms.append((now - req.t_enqueue) * 1000.0)
                self._done_t.append(now)
                self.responses_total += 1
        for i, req in enumerate(group):
            req.result = {"action": np.asarray(actions[i]), "queued_ms": round((now - req.t_enqueue) * 1000.0, 3), **meta}
            req.event.set()

    # -- stats -------------------------------------------------------------
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def stats(self) -> Dict[str, Any]:
        """One consistent stats snapshot (the service folds it into its
        ``/metrics`` snapshot and the journal's interval events)."""
        with self._cond:
            latencies = sorted(self._latency_ms)
            done = list(self._done_t)
            out: Dict[str, Any] = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "errors_total": self.errors_total,
                "shed_total": self.shed_total,
                "dispatches_total": self.dispatches_total,
                "rows_total": self.rows_total,
                "queue_depth": len(self._queue),
                "width_hist": dict(self.width_hist),
            }
        if latencies:
            out["latency_p50_ms"] = round(_percentile(latencies, 50.0), 3)
            out["latency_p99_ms"] = round(_percentile(latencies, 99.0), 3)
        # from the snapshot, not the live counters: a dispatch completing
        # between the lock release and here must not skew the mean
        if out["dispatches_total"]:
            out["batch_width_mean"] = round(out["rows_total"] / out["dispatches_total"], 3)
        if len(done) >= 2:
            window = done[-1] - done[0]
            if window > 0:
                out["requests_per_sec"] = round((len(done) - 1) / window, 3)
        return out


def _percentile(sorted_values: List[float], pct: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_values:
        return 0.0
    rank = max(0, min(len(sorted_values) - 1, int(round(pct / 100.0 * (len(sorted_values) - 1)))))
    return float(sorted_values[rank])
