"""Checkpoint discovery, per-algo policy adapters and the promotion health
gate for the serving tier.

A :class:`PolicyHandle` is everything the server needs to turn a checkpoint
into a servable policy, with the algo-specific parts closed over once at build
time: how a request's observation row is validated, how a group of rows is
assembled into one padded device slab, the pure ``(params, obs, key) ->
actions`` step (greedy or stochastic) the service AOT-compiles per batch
bucket, and how a *new* checkpoint's params are converted for a hot swap.

Adapters exist for the feed-forward actor families — ``ppo`` / ``a2c`` (the
shared PPO-style agent) and ``sac`` (the tanh-Gaussian actor) — and, since
the session layer (:mod:`sheeprl_tpu.serving.sessions`), for the stateful
families too: ``ppo_recurrent`` (LSTM carry + previous actions) and
``dreamer_v3`` (RSSM recurrent/stochastic state).  A stateful handle sets
``stateful=True`` and exposes ``make_state_step`` — a pure
``(params, state, obs, is_first, key) -> (actions, new_state)`` step whose
``is_first`` reset handling is bit-identical to the training player; the
service keeps the per-session state resident in a fixed-capacity device slab
and gathers/scatters it around every dispatch (howto/serving.md "Sessions").

The health gate mirrors ``tools/health_diff.py``'s machine check: a candidate
checkpoint is promotable when the training run's journal (the ``version_N``
dir the checkpoint lives under) has no open learning-health anomalies
(:func:`~sheeprl_tpu.diagnostics.health.active_anomalies`).  Standalone
checkpoints without a journal are governed by
``serving.reload.allow_unjournaled``.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from math import prod
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

#: algo name -> handle builder; the public surface for registering new
#: servable families (signature: (cfg, obs_space, action_space, agent_state))
SERVABLE_BUILDERS: Dict[str, Callable] = {}

_CKPT_RE = re.compile(r"ckpt_(\d+)_\d+\.ckpt$")


def checkpoint_step(path: str) -> Optional[int]:
    """Policy step encoded in a checkpoint filename (``ckpt_{step}_{rank}``),
    or None for foreign spellings (those sort by mtime instead)."""
    match = _CKPT_RE.search(os.path.basename(str(path)))
    return int(match.group(1)) if match else None


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest checkpoint in a directory: highest encoded step, falling back
    to mtime for filenames the step pattern does not match."""
    try:
        names = [n for n in os.listdir(str(ckpt_dir)) if n.endswith(".ckpt")]
    except OSError:
        return None
    if not names:
        return None

    def sort_key(name: str) -> Tuple[int, float]:
        step = checkpoint_step(name)
        path = os.path.join(str(ckpt_dir), name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        return (step if step is not None else -1, mtime)

    return os.path.join(str(ckpt_dir), max(names, key=sort_key))


def journal_for_checkpoint(ckpt_path: str) -> Optional[str]:
    """The training run's journal that governs this checkpoint: checkpoints
    land in ``<version_N>/checkpoint/``, the journal in ``<version_N>/``."""
    version_dir = os.path.dirname(os.path.dirname(os.path.abspath(str(ckpt_path))))
    path = os.path.join(version_dir, "journal.jsonl")
    return path if os.path.isfile(path) else None


def checkpoint_health(
    ckpt_path: str,
    health_gate: bool = True,
    allow_unjournaled: bool = True,
) -> Tuple[bool, str, List[Dict[str, Any]]]:
    """Is this checkpoint promotable?  Returns ``(ok, reason, open_anomalies)``.

    The machine check from ISSUE 9's down-payment: read the training run's
    journal next to the checkpoint and refuse promotion while any
    learning-health ``anomaly`` event has no matching ``anomaly_end``.
    """
    if not health_gate:
        return True, "health gate disabled", []
    journal_path = journal_for_checkpoint(ckpt_path)
    if journal_path is None:
        if allow_unjournaled:
            return True, "no training journal (allow_unjournaled)", []
        return False, "no training journal next to the checkpoint", []
    from sheeprl_tpu.diagnostics.health import active_anomalies
    from sheeprl_tpu.diagnostics.journal import read_journal

    open_anomalies = active_anomalies(read_journal(journal_path))
    if open_anomalies:
        kinds = sorted({f"{e.get('kind')}:{e.get('subject')}" for e in open_anomalies})
        return False, f"open learning-health anomalies: {', '.join(kinds)}", open_anomalies
    return True, "journal clean", []


# ---------------------------------------------------------------------------
# the handle
# ---------------------------------------------------------------------------


@dataclass
class PolicyHandle:
    """One servable policy: the algo-specific closures the service drives.

    ``make_step(greedy)`` returns a PURE function of ``(params, obs, key)``
    (jit/AOT-compilable; the key is traced-but-unused on the greedy path so
    both modes share one signature).  ``assemble(rows, width)`` pads a request
    group to the bucket width — the padded rows are zeros and are sliced off
    before any response sees them.  ``load_params`` converts a *new*
    checkpoint's agent state (:func:`agent_state_from_checkpoint`) for an
    atomic hot swap.

    Stateful families (``stateful=True``) additionally carry ``state_spec``
    (per-row recurrent-state arrays, same ``{key: (shape, dtype)}`` layout as
    ``obs_spec``) and ``make_state_step(greedy)`` — a pure
    ``(params, state, obs, is_first, key) -> (actions, new_state)`` where
    ``state`` is a dict of ``[B, ...]`` arrays and ``is_first`` is ``[B, 1]``
    float (1 resets that row to its initial state IN-GRAPH, so reset handling
    compiles into the AOT executable and matches the training player exactly).
    ``make_step`` is None for stateful handles — the service drives the
    session slab path instead.

    ``log_row`` (optional) maps a validated obs row to the per-key arrays the
    request log stores — the seam that lets ``sac`` log the FLAT concatenated
    ``observations`` key offline training expects.
    """

    algo: str
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]]
    action_shape: Tuple[int, ...]
    params: Any
    make_step: Optional[Callable[[bool], Callable]]
    assemble: Callable[[List[Dict[str, np.ndarray]], int], Any]
    validate: Callable[[Any], Dict[str, np.ndarray]]
    load_params: Callable[[Dict[str, Any]], Any]
    ckpt_path: str = ""
    ckpt_step: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)
    stateful: bool = False
    state_spec: Dict[str, Tuple[Tuple[int, ...], str]] = field(default_factory=dict)
    make_state_step: Optional[Callable[[bool], Callable]] = None
    log_row: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None

    def zero_obs(self, width: int) -> Any:
        """A zeros slab at ``width`` (warmup compiles trace against this)."""
        return self.assemble([], width)


def _row_validator(
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]],
) -> Callable[[Any], Dict[str, np.ndarray]]:
    def validate(obs: Any) -> Dict[str, np.ndarray]:
        if not isinstance(obs, dict):
            raise ValueError(f"obs must be a dict of observation keys, got {type(obs).__name__}")
        row: Dict[str, np.ndarray] = {}
        for key, (shape, dtype) in obs_spec.items():
            if key not in obs:
                raise ValueError(f"obs is missing key {key!r} (expected {sorted(obs_spec)})")
            arr = np.asarray(obs[key], dtype=dtype)
            if int(arr.size) != int(prod(shape) if shape else 1):
                raise ValueError(
                    f"obs[{key!r}] has {arr.size} elements, expected shape {tuple(shape)}"
                )
            row[key] = arr.reshape(shape)
        return row

    return validate


def _dict_assembler(
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]],
) -> Callable[[List[Dict[str, np.ndarray]], int], Dict[str, np.ndarray]]:
    def assemble(rows: List[Dict[str, np.ndarray]], width: int) -> Dict[str, np.ndarray]:
        slab: Dict[str, np.ndarray] = {}
        for key, (shape, dtype) in obs_spec.items():
            buf = np.zeros((int(width),) + tuple(shape), dtype=dtype)
            for i, row in enumerate(rows):
                buf[i] = row[key]
            slab[key] = buf
        return slab

    return assemble


def _actions_dim(action_space) -> Tuple[Tuple[int, ...], bool, bool]:
    import gymnasium as gym

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    return tuple(int(a) for a in actions_dim), is_continuous, is_multidiscrete


def _jnp_tree(state: Any) -> Any:
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, state)


def _ppo_like_handle(cfg, obs_space, action_space, agent_state) -> PolicyHandle:
    """ppo / a2c: the shared feed-forward PPO-style agent — one apply returns
    ``(actions, log_prob, entropy, value)``; serving keeps the actions."""
    import importlib

    agent_module = importlib.import_module(f"sheeprl_tpu.algos.{cfg.algo.name}.agent")
    actions_dim, is_continuous, _ = _actions_dim(action_space)
    agent, params, _ = agent_module.build_agent(
        None, actions_dim, is_continuous, cfg, obs_space, agent_state
    )
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for k in cnn_keys:
        obs_spec[k] = (tuple(obs_space[k].shape), "float32")
    for k in mlp_keys:
        obs_spec[k] = ((int(prod(obs_space[k].shape)),), "float32")

    def make_step(greedy: bool) -> Callable:
        def step(p, obs, key):
            actions, _, _, _ = agent.apply(p, obs, key=key, greedy=greedy)
            return actions

        return step

    action_shape = (sum(actions_dim),) if is_continuous else (len(actions_dim),)
    return PolicyHandle(
        algo=str(cfg.algo.name),
        obs_spec=obs_spec,
        action_shape=action_shape,
        params=params,
        make_step=make_step,
        assemble=_dict_assembler(obs_spec),
        validate=_row_validator(obs_spec),
        load_params=_jnp_tree,
        meta={"is_continuous": is_continuous, "actions_dim": list(actions_dim)},
    )


def _sac_handle(cfg, obs_space, action_space, agent_state) -> PolicyHandle:
    """sac: the tanh-Gaussian actor — greedy is the squashed mean, stochastic
    is ``sample_and_log_prob``.  Vector keys concatenate into the flat obs the
    nets consume (same layout as ``algos/sac/utils.py::prepare_obs``)."""
    from sheeprl_tpu.algos.sac.agent import build_agent

    actor_def, _, params, *_rest = build_agent(None, cfg, obs_space, action_space, agent_state)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_spec = {k: ((int(prod(obs_space[k].shape)),), "float32") for k in mlp_keys}

    def assemble(rows: List[Dict[str, np.ndarray]], width: int) -> np.ndarray:
        dim = sum(shape[0] for shape, _ in obs_spec.values())
        buf = np.zeros((int(width), dim), dtype=np.float32)
        for i, row in enumerate(rows):
            buf[i] = np.concatenate([row[k] for k in mlp_keys], axis=-1)
        return buf

    def make_step(greedy: bool) -> Callable:
        if greedy:

            def step(p, obs, key):
                return actor_def.apply(p["actor"], obs, method="greedy_action")

        else:

            def step(p, obs, key):
                action, _ = actor_def.apply(p["actor"], obs, key, method="sample_and_log_prob")
                return action

        return step

    def log_row(row: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        # the request log stores the FLAT concat the nets consumed — the
        # 'observations' key offline sac/droq training requires
        return {"observations": np.concatenate([row[k] for k in mlp_keys], axis=-1)}

    return PolicyHandle(
        algo="sac",
        obs_spec=obs_spec,
        action_shape=tuple(action_space.shape),
        params=params,
        make_step=make_step,
        assemble=assemble,
        validate=_row_validator(obs_spec),
        load_params=_jnp_tree,
        meta={"is_continuous": True},
        log_row=log_row,
    )


def _ppo_recurrent_handle(cfg, obs_space, action_space, agent_state) -> PolicyHandle:
    """ppo_recurrent: the LSTM agent served statefully.  Per-session state is
    ``{hx, cx, prev_actions}``; the step masks all three by ``1 - is_first``
    BEFORE the apply — exactly the host-side reset the training player does
    (``ppo_recurrent.py``: ``hx *= (1 - dones)`` etc.) — then advances one
    sequence step and rebuilds ``prev_actions`` (one-hot per discrete head,
    raw actions when continuous) for the next request."""
    from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent

    actions_dim, is_continuous, _ = _actions_dim(action_space)
    agent, params, _ = build_agent(None, actions_dim, is_continuous, cfg, obs_space, agent_state)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for k in cnn_keys:
        obs_spec[k] = (tuple(obs_space[k].shape), "float32")
    for k in mlp_keys:
        obs_spec[k] = ((int(prod(obs_space[k].shape)),), "float32")
    hidden = int(cfg.algo.rnn.lstm.hidden_size)
    act_sum = int(sum(actions_dim))
    state_spec = {
        "hx": ((hidden,), "float32"),
        "cx": ((hidden,), "float32"),
        "prev_actions": ((act_sum,), "float32"),
    }

    def make_state_step(greedy: bool) -> Callable:
        import jax
        import jax.numpy as jnp

        def step(p, state, obs, is_first, key):
            keep = 1.0 - is_first  # [B, 1]; 1 -> fresh episode, zero the carry
            hx = state["hx"] * keep
            cx = state["cx"] * keep
            prev_actions = state["prev_actions"] * keep
            seq_obs = {k: v[None] for k, v in obs.items()}  # [1, B, ...]
            actions, _, _, _, (new_hx, new_cx) = agent.apply(
                p, seq_obs, prev_actions[None], hx, cx, key=key, greedy=greedy
            )
            actions_row = actions[0]  # [B, out]
            if is_continuous:
                next_prev = actions_row
            else:
                next_prev = jnp.concatenate(
                    [
                        jax.nn.one_hot(actions_row[:, j].astype(jnp.int32), d)
                        for j, d in enumerate(actions_dim)
                    ],
                    axis=-1,
                )
            return actions_row, {"hx": new_hx, "cx": new_cx, "prev_actions": next_prev}

        return step

    action_shape = (sum(actions_dim),) if is_continuous else (len(actions_dim),)
    return PolicyHandle(
        algo="ppo_recurrent",
        obs_spec=obs_spec,
        action_shape=action_shape,
        params=params,
        make_step=None,
        assemble=_dict_assembler(obs_spec),
        validate=_row_validator(obs_spec),
        load_params=_jnp_tree,
        meta={"is_continuous": is_continuous, "actions_dim": list(actions_dim)},
        stateful=True,
        state_spec=state_spec,
        make_state_step=make_state_step,
    )


def _dreamer_v3_handle(cfg, obs_space, action_space, agent_state) -> PolicyHandle:
    """dreamer_v3: the world-model policy served statefully.  Per-session
    state is the RSSM triplet ``{recurrent, stochastic, actions}``; resets
    blend the (learnable, params-dependent) initial state in by the
    ``is_first`` mask — the same masked blend as ``PlayerDV3._reset_masked``
    — and the step mirrors ``PlayerDV3._step`` op for op (encode ->
    recurrent_step -> representation -> actor.act).  Image keys travel as
    raw uint8 and are scaled in-graph exactly like ``prepare_obs``."""
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent

    actions_dim, is_continuous, _ = _actions_dim(action_space)
    state_dict = dict(agent_state or {})
    wm_def, actor_def, _, params = build_agent(
        None,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        state_dict.get("world_model"),
        state_dict.get("actor"),
        state_dict.get("critic"),
        state_dict.get("target_critic"),
    )
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for k in cnn_keys:
        obs_spec[k] = (tuple(obs_space[k].shape), "uint8")
    for k in mlp_keys:
        obs_spec[k] = ((int(prod(obs_space[k].shape)),), "float32")
    wm_cfg = cfg.algo.world_model
    recurrent_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    stochastic_size = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    act_sum = int(sum(actions_dim))
    state_spec = {
        "recurrent": ((recurrent_size,), "float32"),
        "stochastic": ((stochastic_size,), "float32"),
        "actions": ((act_sum,), "float32"),
    }

    def make_state_step(greedy: bool) -> Callable:
        import jax
        import jax.numpy as jnp

        def step(p, state, obs, is_first, key):
            wm_params, actor_params = p["world_model"], p["actor"]
            n = is_first.shape[0]
            h0, z0 = wm_def.apply(wm_params, (n,), method="initial_states")
            init = {
                "recurrent": h0,
                "stochastic": z0,
                "actions": jnp.zeros((n, act_sum), jnp.float32),
            }
            st = jax.tree_util.tree_map(
                lambda i, s: is_first * i + (1.0 - is_first) * s, init, state
            )
            prepared = {}
            for k in cnn_keys:
                prepared[k] = obs[k].astype(jnp.float32) / 255.0 - 0.5
            for k in mlp_keys:
                prepared[k] = obs[k]
            k1, k2 = jax.random.split(key)
            embedded = wm_def.apply(wm_params, prepared, method="encode")
            recurrent = wm_def.apply(
                wm_params, st["stochastic"], st["actions"], st["recurrent"], method="recurrent_step"
            )
            if wm_def.decoupled_rssm:
                _, stochastic = wm_def.apply(wm_params, None, embedded, k1, method="representation")
            else:
                _, stochastic = wm_def.apply(wm_params, recurrent, embedded, k1, method="representation")
            latent = jnp.concatenate([stochastic, recurrent], axis=-1)
            actions = actor_def.apply(actor_params, latent, k2, greedy, None, method="act")
            return actions, {"recurrent": recurrent, "stochastic": stochastic, "actions": actions}

        return step

    # dreamer actions are the actor's raw output: the one-hot concat for
    # discrete heads (clients argmax per head, like algos/dreamer_v3/utils.py
    # ``test()``), the squashed continuous vector otherwise
    return PolicyHandle(
        algo="dreamer_v3",
        obs_spec=obs_spec,
        action_shape=(act_sum,),
        params=params,
        make_step=None,
        assemble=_dict_assembler(obs_spec),
        validate=_row_validator(obs_spec),
        load_params=_jnp_tree,
        meta={"is_continuous": is_continuous, "actions_dim": list(actions_dim)},
        stateful=True,
        state_spec=state_spec,
        make_state_step=make_state_step,
    )


SERVABLE_BUILDERS.update(
    {
        "ppo": _ppo_like_handle,
        "a2c": _ppo_like_handle,
        "sac": _sac_handle,
        "ppo_recurrent": _ppo_recurrent_handle,
        "dreamer_v3": _dreamer_v3_handle,
    }
)

#: checkpoint keys that make up a Dreamer-family agent state (those runs
#: checkpoint each module separately instead of one "agent" tree)
DREAMER_STATE_KEYS = ("world_model", "actor", "critic", "target_critic")


def agent_state_from_checkpoint(state: Mapping[str, Any]) -> Dict[str, Any]:
    """The servable agent state inside a loaded checkpoint: ``state["agent"]``
    for the single-tree families, the per-module dict for the Dreamer family
    (``world_model``/``actor``/...)."""
    if "agent" in state:
        return state["agent"]
    if "world_model" in state:
        return {k: state[k] for k in DREAMER_STATE_KEYS if k in state}
    raise ValueError(
        f"checkpoint has no servable agent state (keys: {sorted(state)}); expected "
        f"'agent' or the Dreamer module keys {list(DREAMER_STATE_KEYS)}"
    )


def build_policy(cfg, obs_space, action_space, agent_state: Optional[Dict[str, Any]] = None) -> PolicyHandle:
    """Adapter dispatch: ``cfg.algo.name`` -> :class:`PolicyHandle` (random
    init params when ``agent_state`` is None — bench.py serves a throughput
    probe without any checkpoint)."""
    algo = str(cfg.algo.name)
    builder = SERVABLE_BUILDERS.get(algo)
    if builder is None:
        raise ValueError(
            f"Algorithm {algo!r} has no servable adapter; registered builders: "
            f"{sorted(SERVABLE_BUILDERS)}.  Stateless actors register a plain "
            "make_step handle; recurrent/model-based families register a stateful "
            "handle served through the session layer (howto/serving.md 'Sessions')"
        )
    return builder(cfg, obs_space, action_space, agent_state)


def load_policy(cfg, ckpt_path: str) -> PolicyHandle:
    """Checkpoint -> :class:`PolicyHandle`: read the state, rebuild the obs /
    action spaces the way the evaluation entrypoints do (one throwaway env —
    the spaces are not archived anywhere else), then adapter-dispatch."""
    import gymnasium as gym

    from sheeprl_tpu.envs.env import make_env
    from sheeprl_tpu.utils.checkpoint import load_state

    state = load_state(str(ckpt_path))
    try:
        agent_state = agent_state_from_checkpoint(state)
    except ValueError as err:
        raise ValueError(f"Checkpoint '{ckpt_path}': {err}") from None
    cfg.env.capture_video = False
    env = make_env(cfg, cfg.seed, 0, None, "serve")()
    try:
        obs_space = env.observation_space
        action_space = env.action_space
    finally:
        env.close()
    if not isinstance(obs_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation space (need a Dict): {obs_space}")
    handle = build_policy(cfg, obs_space, action_space, agent_state)
    handle.ckpt_path = str(ckpt_path)
    handle.ckpt_step = checkpoint_step(ckpt_path) or 0
    return handle
