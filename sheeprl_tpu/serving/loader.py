"""Checkpoint discovery, per-algo policy adapters and the promotion health
gate for the serving tier.

A :class:`PolicyHandle` is everything the server needs to turn a checkpoint
into a servable policy, with the algo-specific parts closed over once at build
time: how a request's observation row is validated, how a group of rows is
assembled into one padded device slab, the pure ``(params, obs, key) ->
actions`` step (greedy or stochastic) the service AOT-compiles per batch
bucket, and how a *new* checkpoint's params are converted for a hot swap.

Adapters exist for the feed-forward actor families — ``ppo`` / ``a2c`` (the
shared PPO-style agent) and ``sac`` (the tanh-Gaussian actor).  Recurrent and
model-based policies (``ppo_recurrent``, the Dreamer family) carry per-client
state across steps, which a stateless request/response tier cannot batch
without a session layer — :func:`build_policy` rejects them with a clear
error instead of serving wrong actions.

The health gate mirrors ``tools/health_diff.py``'s machine check: a candidate
checkpoint is promotable when the training run's journal (the ``version_N``
dir the checkpoint lives under) has no open learning-health anomalies
(:func:`~sheeprl_tpu.diagnostics.health.active_anomalies`).  Standalone
checkpoints without a journal are governed by
``serving.reload.allow_unjournaled``.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from math import prod
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: algo name -> handle builder; the public surface for registering new
#: servable families (signature: (cfg, obs_space, action_space, agent_state))
SERVABLE_BUILDERS: Dict[str, Callable] = {}

_CKPT_RE = re.compile(r"ckpt_(\d+)_\d+\.ckpt$")


def checkpoint_step(path: str) -> Optional[int]:
    """Policy step encoded in a checkpoint filename (``ckpt_{step}_{rank}``),
    or None for foreign spellings (those sort by mtime instead)."""
    match = _CKPT_RE.search(os.path.basename(str(path)))
    return int(match.group(1)) if match else None


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest checkpoint in a directory: highest encoded step, falling back
    to mtime for filenames the step pattern does not match."""
    try:
        names = [n for n in os.listdir(str(ckpt_dir)) if n.endswith(".ckpt")]
    except OSError:
        return None
    if not names:
        return None

    def sort_key(name: str) -> Tuple[int, float]:
        step = checkpoint_step(name)
        path = os.path.join(str(ckpt_dir), name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        return (step if step is not None else -1, mtime)

    return os.path.join(str(ckpt_dir), max(names, key=sort_key))


def journal_for_checkpoint(ckpt_path: str) -> Optional[str]:
    """The training run's journal that governs this checkpoint: checkpoints
    land in ``<version_N>/checkpoint/``, the journal in ``<version_N>/``."""
    version_dir = os.path.dirname(os.path.dirname(os.path.abspath(str(ckpt_path))))
    path = os.path.join(version_dir, "journal.jsonl")
    return path if os.path.isfile(path) else None


def checkpoint_health(
    ckpt_path: str,
    health_gate: bool = True,
    allow_unjournaled: bool = True,
) -> Tuple[bool, str, List[Dict[str, Any]]]:
    """Is this checkpoint promotable?  Returns ``(ok, reason, open_anomalies)``.

    The machine check from ISSUE 9's down-payment: read the training run's
    journal next to the checkpoint and refuse promotion while any
    learning-health ``anomaly`` event has no matching ``anomaly_end``.
    """
    if not health_gate:
        return True, "health gate disabled", []
    journal_path = journal_for_checkpoint(ckpt_path)
    if journal_path is None:
        if allow_unjournaled:
            return True, "no training journal (allow_unjournaled)", []
        return False, "no training journal next to the checkpoint", []
    from sheeprl_tpu.diagnostics.health import active_anomalies
    from sheeprl_tpu.diagnostics.journal import read_journal

    open_anomalies = active_anomalies(read_journal(journal_path))
    if open_anomalies:
        kinds = sorted({f"{e.get('kind')}:{e.get('subject')}" for e in open_anomalies})
        return False, f"open learning-health anomalies: {', '.join(kinds)}", open_anomalies
    return True, "journal clean", []


# ---------------------------------------------------------------------------
# the handle
# ---------------------------------------------------------------------------


@dataclass
class PolicyHandle:
    """One servable policy: the algo-specific closures the service drives.

    ``make_step(greedy)`` returns a PURE function of ``(params, obs, key)``
    (jit/AOT-compilable; the key is traced-but-unused on the greedy path so
    both modes share one signature).  ``assemble(rows, width)`` pads a request
    group to the bucket width — the padded rows are zeros and are sliced off
    before any response sees them.  ``load_params`` converts a *new*
    checkpoint's ``state["agent"]`` for an atomic hot swap.
    """

    algo: str
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]]
    action_shape: Tuple[int, ...]
    params: Any
    make_step: Callable[[bool], Callable]
    assemble: Callable[[List[Dict[str, np.ndarray]], int], Any]
    validate: Callable[[Any], Dict[str, np.ndarray]]
    load_params: Callable[[Dict[str, Any]], Any]
    ckpt_path: str = ""
    ckpt_step: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)

    def zero_obs(self, width: int) -> Any:
        """A zeros slab at ``width`` (warmup compiles trace against this)."""
        return self.assemble([], width)


def _row_validator(
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]],
) -> Callable[[Any], Dict[str, np.ndarray]]:
    def validate(obs: Any) -> Dict[str, np.ndarray]:
        if not isinstance(obs, dict):
            raise ValueError(f"obs must be a dict of observation keys, got {type(obs).__name__}")
        row: Dict[str, np.ndarray] = {}
        for key, (shape, dtype) in obs_spec.items():
            if key not in obs:
                raise ValueError(f"obs is missing key {key!r} (expected {sorted(obs_spec)})")
            arr = np.asarray(obs[key], dtype=dtype)
            if int(arr.size) != int(prod(shape) if shape else 1):
                raise ValueError(
                    f"obs[{key!r}] has {arr.size} elements, expected shape {tuple(shape)}"
                )
            row[key] = arr.reshape(shape)
        return row

    return validate


def _dict_assembler(
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]],
) -> Callable[[List[Dict[str, np.ndarray]], int], Dict[str, np.ndarray]]:
    def assemble(rows: List[Dict[str, np.ndarray]], width: int) -> Dict[str, np.ndarray]:
        slab: Dict[str, np.ndarray] = {}
        for key, (shape, dtype) in obs_spec.items():
            buf = np.zeros((int(width),) + tuple(shape), dtype=dtype)
            for i, row in enumerate(rows):
                buf[i] = row[key]
            slab[key] = buf
        return slab

    return assemble


def _actions_dim(action_space) -> Tuple[Tuple[int, ...], bool, bool]:
    import gymnasium as gym

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    return tuple(int(a) for a in actions_dim), is_continuous, is_multidiscrete


def _jnp_tree(state: Any) -> Any:
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, state)


def _ppo_like_handle(cfg, obs_space, action_space, agent_state) -> PolicyHandle:
    """ppo / a2c: the shared feed-forward PPO-style agent — one apply returns
    ``(actions, log_prob, entropy, value)``; serving keeps the actions."""
    import importlib

    agent_module = importlib.import_module(f"sheeprl_tpu.algos.{cfg.algo.name}.agent")
    actions_dim, is_continuous, _ = _actions_dim(action_space)
    agent, params, _ = agent_module.build_agent(
        None, actions_dim, is_continuous, cfg, obs_space, agent_state
    )
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    obs_spec: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for k in cnn_keys:
        obs_spec[k] = (tuple(obs_space[k].shape), "float32")
    for k in mlp_keys:
        obs_spec[k] = ((int(prod(obs_space[k].shape)),), "float32")

    def make_step(greedy: bool) -> Callable:
        def step(p, obs, key):
            actions, _, _, _ = agent.apply(p, obs, key=key, greedy=greedy)
            return actions

        return step

    action_shape = (sum(actions_dim),) if is_continuous else (len(actions_dim),)
    return PolicyHandle(
        algo=str(cfg.algo.name),
        obs_spec=obs_spec,
        action_shape=action_shape,
        params=params,
        make_step=make_step,
        assemble=_dict_assembler(obs_spec),
        validate=_row_validator(obs_spec),
        load_params=_jnp_tree,
        meta={"is_continuous": is_continuous, "actions_dim": list(actions_dim)},
    )


def _sac_handle(cfg, obs_space, action_space, agent_state) -> PolicyHandle:
    """sac: the tanh-Gaussian actor — greedy is the squashed mean, stochastic
    is ``sample_and_log_prob``.  Vector keys concatenate into the flat obs the
    nets consume (same layout as ``algos/sac/utils.py::prepare_obs``)."""
    from sheeprl_tpu.algos.sac.agent import build_agent

    actor_def, _, params, *_rest = build_agent(None, cfg, obs_space, action_space, agent_state)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_spec = {k: ((int(prod(obs_space[k].shape)),), "float32") for k in mlp_keys}

    def assemble(rows: List[Dict[str, np.ndarray]], width: int) -> np.ndarray:
        dim = sum(shape[0] for shape, _ in obs_spec.values())
        buf = np.zeros((int(width), dim), dtype=np.float32)
        for i, row in enumerate(rows):
            buf[i] = np.concatenate([row[k] for k in mlp_keys], axis=-1)
        return buf

    def make_step(greedy: bool) -> Callable:
        if greedy:

            def step(p, obs, key):
                return actor_def.apply(p["actor"], obs, method="greedy_action")

        else:

            def step(p, obs, key):
                action, _ = actor_def.apply(p["actor"], obs, key, method="sample_and_log_prob")
                return action

        return step

    return PolicyHandle(
        algo="sac",
        obs_spec=obs_spec,
        action_shape=tuple(action_space.shape),
        params=params,
        make_step=make_step,
        assemble=assemble,
        validate=_row_validator(obs_spec),
        load_params=_jnp_tree,
        meta={"is_continuous": True},
    )


SERVABLE_BUILDERS.update({"ppo": _ppo_like_handle, "a2c": _ppo_like_handle, "sac": _sac_handle})


def build_policy(cfg, obs_space, action_space, agent_state: Optional[Dict[str, Any]] = None) -> PolicyHandle:
    """Adapter dispatch: ``cfg.algo.name`` -> :class:`PolicyHandle` (random
    init params when ``agent_state`` is None — bench.py serves a throughput
    probe without any checkpoint)."""
    algo = str(cfg.algo.name)
    builder = SERVABLE_BUILDERS.get(algo)
    if builder is None:
        raise ValueError(
            f"Algorithm {algo!r} is not servable: the stateless batching tier supports "
            f"{sorted(SERVABLE_BUILDERS)} (recurrent/model-based policies carry per-client "
            "state a request/response API cannot batch)"
        )
    return builder(cfg, obs_space, action_space, agent_state)


def load_policy(cfg, ckpt_path: str) -> PolicyHandle:
    """Checkpoint -> :class:`PolicyHandle`: read the state, rebuild the obs /
    action spaces the way the evaluation entrypoints do (one throwaway env —
    the spaces are not archived anywhere else), then adapter-dispatch."""
    import gymnasium as gym

    from sheeprl_tpu.envs.env import make_env
    from sheeprl_tpu.utils.checkpoint import load_state

    state = load_state(str(ckpt_path))
    if "agent" not in state:
        raise ValueError(f"Checkpoint '{ckpt_path}' has no 'agent' state to serve")
    cfg.env.capture_video = False
    env = make_env(cfg, cfg.seed, 0, None, "serve")()
    try:
        obs_space = env.observation_space
        action_space = env.action_space
    finally:
        env.close()
    if not isinstance(obs_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation space (need a Dict): {obs_space}")
    handle = build_policy(cfg, obs_space, action_space, state["agent"])
    handle.ckpt_path = str(ckpt_path)
    handle.ckpt_step = checkpoint_step(ckpt_path) or 0
    return handle
