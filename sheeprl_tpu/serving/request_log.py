"""Request logging: every dispatched ``/act`` batch becomes offline training
data (ISSUE 16, ROADMAP item 2c).

Production traffic is the one dataset a deployed policy is guaranteed to be
on-distribution for — and the serving tier used to throw it away.
:class:`RequestLog` appends every dispatched row (observations as the policy
consumed them, the action it returned, an ``is_first`` episode marker from
the session layer) to a per-model sharded dataset stream in the exact
``data/datasets.py`` format: ``shard-*.npz`` + manifest sidecars, rotated at
``serving.request_log.rotate_rows`` rows (journaled ``request_log_rotate``),
with the action-space metadata (``actions_dim`` / ``is_continuous`` / algo /
checkpoint) recorded in ``dataset.json`` at collect time — so
``OfflineDataset`` opens the log directly and ``algo.offline`` training
consumes it with zero conversion (the production flywheel:
howto/offline_rl.md).

Rewards and ``terminated`` are zeros at collect time: the serving tier does
not see returns.  Label them downstream (relabeling, human feedback, env
re-simulation) or train reward-free components; the keys exist so the flat
offline loaders accept the dataset as-is.

Shard writes (npz + sha256) run on a background writer thread — the
dispatcher only appends to a host-side buffer, so logging never stalls a
batch.  ``close`` drains the writer and flushes the tail rows.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from sheeprl_tpu.data.datasets import shard_name, write_dataset_meta, write_shard

__all__ = ["RequestLog"]


class RequestLog:
    """One model's ``/act`` traffic as a growing offline dataset stream."""

    def __init__(
        self,
        root: str,
        handle: Any,
        model: Optional[str] = None,
        rotate_rows: int = 4096,
        journal: Any = None,
        stream: int = 0,
        extra_meta: Optional[Mapping[str, Any]] = None,
        tracer: Any = None,
    ):
        self.root = str(root)
        self.model = model
        self.rotate_rows = max(1, int(rotate_rows))
        self.stream = int(stream)
        self._journal = journal
        self._tracer = tracer
        self._handle = handle
        self._lock = threading.Lock()
        self._buffer: List[Dict[str, np.ndarray]] = []
        self._start = 0  # logical step cursor of the stream
        self.rows_total = 0
        self.shards_total = 0
        self.dropped_total = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=16)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._writer, name="sheeprl-request-log", daemon=True
        )
        os.makedirs(self.root, exist_ok=True)
        meta: Dict[str, Any] = {
            "source": "serving",
            "model": model,
            "algo": getattr(handle, "algo", None),
            "ckpt": getattr(handle, "ckpt_path", None) or None,
            "actions_dim": (getattr(handle, "meta", {}) or {}).get("actions_dim"),
            "is_continuous": (getattr(handle, "meta", {}) or {}).get("is_continuous"),
            "obs_keys": sorted(getattr(handle, "obs_spec", {}) or {}),
        }
        meta.update(dict(extra_meta or {}))
        write_dataset_meta(self.root, meta)
        self._thread.start()

    # -- dispatcher side -----------------------------------------------------
    def append(
        self,
        obs_rows: List[Dict[str, np.ndarray]],
        actions: np.ndarray,
        is_first: Optional[np.ndarray] = None,
    ) -> None:
        """Buffer one dispatched batch (valid rows only — padding already
        sliced off).  ``is_first`` is ``[rows, 1]`` float from the session
        layer (stateless dispatches log all-ones: each request is its own
        one-step episode)."""
        actions = np.asarray(actions)
        log_row = getattr(self._handle, "log_row", None)
        blocks: List[Dict[str, np.ndarray]] = []
        for i, row in enumerate(obs_rows):
            stored = dict(log_row(row)) if log_row is not None else dict(row)
            stored["actions"] = np.asarray(actions[i], dtype=np.float32)
            stored["rewards"] = np.zeros((1,), np.float32)
            stored["terminated"] = np.zeros((1,), np.float32)
            stored["is_first"] = (
                np.ones((1,), np.float32)
                if is_first is None
                else np.asarray(is_first[i], np.float32).reshape(1)
            )
            blocks.append(stored)
        full: Optional[List[Dict[str, np.ndarray]]] = None
        with self._lock:
            self._buffer.extend(blocks)
            self.rows_total += len(blocks)
            if len(self._buffer) >= self.rotate_rows:
                full, self._buffer = self._buffer, []
        if full:
            self._enqueue(full)

    def _enqueue(self, rows: List[Dict[str, np.ndarray]]) -> None:
        try:
            self._queue.put_nowait(rows)
        except queue.Full:
            # the disk cannot keep up: shed the oldest pending block rather
            # than stall dispatches or grow without bound
            with self._lock:
                self.dropped_total += len(rows)
            if self._journal is not None:
                self._journal.write(
                    "request_log_rotate",
                    model=self.model,
                    stream=self.stream,
                    rows=len(rows),
                    dropped=True,
                )

    # -- writer thread -------------------------------------------------------
    def _writer(self) -> None:
        while True:
            try:
                rows = self._queue.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if rows is None:
                return
            try:
                if self._tracer is not None:
                    # shard I/O shows up on the writer thread's track of the
                    # serving trace, next to the request spans it rode behind
                    with self._tracer.span("serve-request-log", rows=len(rows), model=self.model):
                        self._write_shard(rows)
                else:
                    self._write_shard(rows)
            except Exception:  # noqa: BLE001 - logging must outlive bad disks
                with self._lock:
                    self.dropped_total += len(rows)

    def _write_shard(self, rows: List[Dict[str, np.ndarray]]) -> None:
        arrays = {
            k: np.stack([r[k] for r in rows], axis=0) for k in rows[0]
        }
        start = self._start
        self._start += len(rows)
        entry = write_shard(self.root, self.stream, start, arrays)
        with self._lock:
            self.shards_total += 1
        if self._journal is not None:
            self._journal.write(
                "request_log_rotate",
                model=self.model,
                stream=self.stream,
                rows=int(entry["rows"]),
                bytes=int(entry["bytes"]),
                start=int(entry["start"]),
                path=shard_name(self.stream, start),
                shards=self.shards_total,
            )

    # -- lifecycle -----------------------------------------------------------
    def flush(self) -> None:
        """Rotate whatever is buffered (tests and close use this; a partial
        shard is fine — shards only need a consistent time axis)."""
        with self._lock:
            rows, self._buffer = self._buffer, []
        if rows:
            self._enqueue(rows)

    def close(self) -> None:
        self.flush()
        self._stop.set()
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=10)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rows_total": self.rows_total,
                "shards_total": self.shards_total,
                "dropped_total": self.dropped_total,
                "buffered": len(self._buffer),
            }
