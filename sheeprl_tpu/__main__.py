"""``python -m sheeprl_tpu`` → train CLI (reference sheeprl/__main__.py);
``python -m sheeprl_tpu serve checkpoint_path=...`` → the policy server;
``python -m sheeprl_tpu export <run dir>`` → the run-dir dataset converter."""

import sys

from sheeprl_tpu.cli import run, serve

if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        serve(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "export":
        from sheeprl_tpu.offline.export import main as export_main

        sys.exit(export_main(sys.argv[2:]))
    else:
        run()
