"""``python -m sheeprl_tpu`` → train CLI (reference sheeprl/__main__.py);
``python -m sheeprl_tpu serve checkpoint_path=...`` → the policy server."""

import sys

from sheeprl_tpu.cli import run, serve

if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        serve(sys.argv[2:])
    else:
        run()
