"""``python -m sheeprl_tpu`` → train CLI (reference sheeprl/__main__.py)."""

from sheeprl_tpu.cli import run

if __name__ == "__main__":
    run()
