"""Render the algorithm registry as a table
(reference /root/reference/sheeprl/available_agents.py:7-34)."""

from __future__ import annotations

import sheeprl_tpu  # noqa: F401  (fires registration)
from sheeprl_tpu.utils.registry import algorithm_registry


def available_agents() -> None:
    try:
        from rich.console import Console
        from rich.table import Table

        table = Table(title="SheepRL-TPU Agents")
        table.add_column("Module")
        table.add_column("Algorithm")
        table.add_column("Entrypoint")
        table.add_column("Decoupled")
        for module, metadata in algorithm_registry.items():
            for m in metadata:
                table.add_row(module, m["name"], m["entrypoint"], str(m["decoupled"]))
        Console().print(table)
    except ImportError:  # pragma: no cover
        for module, metadata in algorithm_registry.items():
            for m in metadata:
                print(f"{module}: {m['name']} ({m['entrypoint']}, decoupled={m['decoupled']})")


if __name__ == "__main__":
    available_agents()
