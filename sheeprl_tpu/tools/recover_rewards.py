"""Reward-log recovery.

Parity with the fork's ``recover_reward_logs.py``
(/root/reference/recover_reward_logs.py:1-371): pull reward traces out of past
runs from whatever survived — TensorBoard event files or the memory-mapped
replay buffers — and write them to CSV for analysis.  Differences from the
reference are deliberate: no pandas/TensorFlow dependency (the ``tensorboard``
package's event_accumulator + the csv module suffice), and the memmap reader
uses this repo's buffer layout (``memmap_buffer[/rank_0]/env_*/rewards.memmap``
written by MemmapArray as raw float32).

Usage:
    python -m sheeprl_tpu.tools.recover_rewards --list-runs
    python -m sheeprl_tpu.tools.recover_rewards --run-path logs/runs/<algo>/<env>/<run> \
        [--format all|tensorboard|memmap] [--output-dir recovered]
"""

from __future__ import annotations

import argparse
import csv
import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

REWARD_TAGS = ("reward", "rew_avg", "episode")


def list_runs(logs_dir: str = "logs/runs") -> List[Dict[str, Any]]:
    """Enumerate run directories and which recovery formats each offers."""
    root = Path(logs_dir)
    if not root.exists():
        raise FileNotFoundError(f"Logs directory not found: {logs_dir}")
    runs = []
    # layout: logs/runs/<algo>/<env_id>/<run_name>/version_*
    for run_dir in sorted(p for p in root.glob("*/*/*") if p.is_dir()):
        formats = []
        # events live at the run root (this repo's TensorBoardLogger) or
        # under version_* (reference Lightning layout) — accept both
        if any(run_dir.glob("events.out.tfevents.*")) or any(
            run_dir.glob("version_*/events.out.tfevents.*")
        ):
            formats.append("tensorboard")
        if any(run_dir.glob("version_*/memmap_buffer")):
            formats.append("memmap")
        if formats:
            algo, env, name = run_dir.parts[-3:]
            runs.append(
                {"algorithm": algo, "environment": env, "run_name": name, "path": str(run_dir), "formats": formats}
            )
    return runs


def read_tensorboard_rewards(run_path: str) -> List[Dict[str, Any]]:
    """Reward-tagged scalars from every event file under ``version_*``."""
    try:
        from tensorboard.backend.event_processing.event_accumulator import EventAccumulator
    except ImportError:  # pragma: no cover - tensorboard ships with the image
        print("tensorboard package unavailable; skipping event-file recovery")
        return []
    rows: List[Dict[str, Any]] = []
    event_dirs = [Path(run_path)] + sorted(Path(run_path).glob("version_*"))
    for version_dir in event_dirs:
        if not any(version_dir.glob("events.out.tfevents.*")):
            continue
        acc = EventAccumulator(str(version_dir), size_guidance={"scalars": 0})
        try:
            acc.Reload()
        except Exception as err:  # noqa: BLE001 - recovery keeps going on bad files
            print(f"Could not read events under {version_dir}: {err}")
            continue
        for tag in acc.Tags().get("scalars", []):
            if not any(t in tag.lower() for t in REWARD_TAGS):
                continue
            for ev in acc.Scalars(tag):
                rows.append(
                    {
                        "step": ev.step,
                        "wall_time": ev.wall_time,
                        "metric": tag,
                        "value": ev.value,
                        "version": version_dir.name,
                    }
                )
    return rows


def read_memmap_rewards(run_path: str) -> List[Dict[str, Any]]:
    """Raw per-step rewards straight out of the replay buffers on disk."""
    rows: List[Dict[str, Any]] = []
    for reward_file in sorted(Path(run_path).glob("version_*/memmap_buffer/**/rewards.memmap")):
        try:
            values = np.memmap(reward_file, dtype=np.float32, mode="r")
        except (OSError, ValueError) as err:
            print(f"Could not read {reward_file}: {err}")
            continue
        origin = str(reward_file.parent.relative_to(run_path))
        for i, v in enumerate(np.asarray(values).reshape(-1)):
            rows.append({"step": i, "origin": origin, "reward": float(v)})
    return rows


def recover(run_path: str, format_type: str = "all") -> Dict[str, List[Dict[str, Any]]]:
    out: Dict[str, List[Dict[str, Any]]] = {}
    if format_type in ("all", "tensorboard"):
        rows = read_tensorboard_rewards(run_path)
        if rows:
            out["tensorboard"] = rows
    if format_type in ("all", "memmap"):
        rows = read_memmap_rewards(run_path)
        if rows:
            out["memmap"] = rows
    return out


def save_csv(recovered: Dict[str, List[Dict[str, Any]]], output_dir: str) -> List[str]:
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    written = []
    for fmt, rows in recovered.items():
        path = out / f"rewards_{fmt}.csv"
        with open(path, "w", newline="") as fp:
            writer = csv.DictWriter(fp, fieldnames=list(rows[0].keys()))
            writer.writeheader()
            writer.writerows(rows)
        written.append(str(path))
        print(f"Saved {len(rows)} {fmt} rows to {path}")
    return written


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(description="Recover reward logs from past runs")
    parser.add_argument("--logs-dir", default="logs/runs")
    parser.add_argument("--list-runs", action="store_true")
    parser.add_argument("--run-path", default=None)
    parser.add_argument("--format", default="all", choices=["all", "tensorboard", "memmap"])
    parser.add_argument("--output-dir", default="recovered_rewards")
    args = parser.parse_args(argv)

    if args.list_runs:
        runs = list_runs(args.logs_dir)
        if not runs:
            print("No recoverable runs found.")
            return
        for r in runs:
            print(f"{r['algorithm']}/{r['environment']}/{r['run_name']}  [{', '.join(r['formats'])}]")
            print(f"    {r['path']}")
        return

    if not args.run_path:
        parser.error("--run-path is required unless --list-runs is given")
    if not os.path.isdir(args.run_path):
        raise FileNotFoundError(f"Run directory not found: {args.run_path}")
    recovered = recover(args.run_path, args.format)
    if not recovered:
        print("No reward data recovered.")
        return
    save_csv(recovered, args.output_dir)


if __name__ == "__main__":
    main()
