"""Quick statistics over a recovered-rewards CSV (parity with the fork's
``analyze_rewards.py``, /root/reference/analyze_rewards.py:1-82; csv+numpy
only — no pandas in this image)."""

from __future__ import annotations

import argparse
import csv
from pathlib import Path
from typing import Optional, Sequence

import numpy as np


def analyze(csv_path: str) -> dict:
    """Summarize a rewards CSV; returns the stats it prints (for tests)."""
    with open(csv_path, newline="") as fp:
        rows = list(csv.DictReader(fp))
    print(f"Reward Data Analysis: {csv_path}")
    print("=" * 60)
    print(f"Total data points: {len(rows)}")
    if not rows:
        return {"count": 0}
    columns = list(rows[0].keys())
    print(f"Columns: {columns}")
    stats: dict = {"count": len(rows), "columns": columns}

    value_col = "reward" if "reward" in columns else ("value" if "value" in columns else None)
    if value_col is None:
        return stats
    values = np.array([float(r[value_col]) for r in rows], np.float64)
    stats.update(
        mean=float(values.mean()),
        std=float(values.std()),
        min=float(values.min()),
        max=float(values.max()),
        total=float(values.sum()),
    )
    print(f"\n{value_col.capitalize()} statistics:")
    print(f"  mean={stats['mean']:.4f}  std={stats['std']:.4f}")
    print(f"  min={stats['min']:.4f}  max={stats['max']:.4f}  sum={stats['total']:.4f}")

    nonzero = values[values != 0]
    stats["nonzero_count"] = int(nonzero.size)
    if nonzero.size:
        print(f"\nNon-zero ({nonzero.size} points): mean={nonzero.mean():.4f} sum={nonzero.sum():.4f}")

    group_col = "origin" if "origin" in columns else ("metric" if "metric" in columns else None)
    if group_col:
        print(f"\nPer-{group_col} breakdown:")
        groups: dict = {}
        for r, v in zip(rows, values):
            groups.setdefault(r[group_col], []).append(v)
        stats["groups"] = {}
        for name, vs in sorted(groups.items()):
            arr = np.array(vs)
            nz = arr[arr != 0]
            stats["groups"][name] = {"count": int(arr.size), "nonzero": int(nz.size)}
            line = f"  {name}: {arr.size} points, {nz.size} non-zero"
            if nz.size:
                line += f" (mean {nz.mean():.4f}, sum {nz.sum():.4f})"
            print(line)
    return stats


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(description="Analyze recovered reward CSV files")
    parser.add_argument("csv_file")
    args = parser.parse_args(argv)
    if not Path(args.csv_file).exists():
        raise FileNotFoundError(args.csv_file)
    analyze(args.csv_file)


if __name__ == "__main__":
    main()
