"""Hyperparameter search harness.

TPU-native re-design of the fork's Phase-1 JEPA search
(/root/reference/search_phase1.py:1-568 + dreamer_v3_jepa_search.py:683-722).
The reference drives Optuna with a Hyperband pruner around subprocess-style
trials; this image has no Optuna, so the harness implements the same search
shape self-contained:

- a categorical search space (default: the reference's Phase-1 JEPA grid —
  ``jepa_coef`` x ``jepa_ema`` x ``jepa_mask.erase_frac``);
- random or grid sampling;
- synchronous successive halving (the core of ASHA/Hyperband): every rung
  multiplies the per-trial step budget by ``reduction_factor`` and keeps the
  top ``1/reduction_factor`` of trials;
- each trial runs IN PROCESS through the real CLI composer
  (``sheeprl_tpu.cli.run``) with ``algo.run_test=True``; the objective is the
  returned final-test cumulative reward.

Artifacts mirror the reference: ``results.csv``, ``topk.json``,
``best_config.yaml``, ``SUMMARY.md`` under ``--output-dir``.
"""

from __future__ import annotations

import argparse
import csv
import itertools
import json
import math
import os
import random
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import yaml

#: the reference Phase-1 space (search_phase1.py:155-158)
DEFAULT_SPACE: Dict[str, List[Any]] = {
    "algo.jepa_coef": [0.3, 1.0, 3.0],
    "algo.jepa_ema": [0.992, 0.996, 0.999],
    "algo.jepa_mask.erase_frac": [0.4, 0.6],
}


def parse_args(argv: Optional[Sequence[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="Successive-halving hyperparameter search")
    parser.add_argument("--exp", type=str, default="dreamer_v3_jepa", help="exp config to search over")
    parser.add_argument("--env", type=str, default=None, help="env config override (e.g. 'atari', 'dmc')")
    parser.add_argument("--full-steps", type=int, required=True, help="full training steps of Phase 2")
    parser.add_argument("--fidelity-frac", type=float, default=0.15, help="top-rung budget fraction")
    parser.add_argument("--n-trials", type=int, default=20)
    parser.add_argument("--reduction-factor", type=int, default=3, help="halving rate between rungs")
    parser.add_argument("--rungs", type=int, default=2, help="number of successive-halving rungs")
    parser.add_argument("--sampler", type=str, default="random", choices=["random", "grid"])
    parser.add_argument("--seed0", type=int, default=0, help="base seed; trial i runs with seed0+i")
    parser.add_argument("--output-dir", type=str, default="./runs/phase1")
    parser.add_argument(
        "--space",
        type=str,
        default=None,
        help="JSON dict of {config.key: [choices...]} replacing the default JEPA space",
    )
    parser.add_argument(
        "--override",
        action="append",
        default=[],
        help="extra config overrides applied to every trial (repeatable)",
    )
    args = parser.parse_args(argv)
    if args.full_steps <= 0:
        raise ValueError(f"full_steps must be > 0, got {args.full_steps}")
    if not 0.0 < args.fidelity_frac <= 1.0:
        raise ValueError(f"fidelity_frac must be in (0, 1], got {args.fidelity_frac}")
    if args.n_trials < 1:
        raise ValueError(f"n_trials must be >= 1, got {args.n_trials}")
    if args.reduction_factor < 2:
        raise ValueError(f"reduction_factor must be >= 2, got {args.reduction_factor}")
    return args


def sample_trials(space: Dict[str, List[Any]], n_trials: int, sampler: str, seed: int) -> List[Dict[str, Any]]:
    """Draw ``n_trials`` parameter assignments from a categorical space."""
    keys = sorted(space)
    if sampler == "grid":
        grid = list(itertools.product(*(space[k] for k in keys)))
        rng = random.Random(seed)
        rng.shuffle(grid)
        picks = (grid * math.ceil(n_trials / len(grid)))[:n_trials]
        return [dict(zip(keys, p)) for p in picks]
    rng = random.Random(seed)
    return [{k: rng.choice(space[k]) for k in keys} for _ in range(n_trials)]


def run_trial(
    exp: str,
    params: Dict[str, Any],
    steps: int,
    seed: int,
    trial_dir: Path,
    env: Optional[str] = None,
    extra_overrides: Sequence[str] = (),
) -> float:
    """One training run through the real CLI; returns the final test reward
    (``-inf`` on failure so the rung ranking drops the trial)."""
    from sheeprl_tpu.cli import run

    trial_dir.mkdir(parents=True, exist_ok=True)
    overrides = [f"exp={exp}"]
    if env:
        overrides.append(f"env={env}")
    overrides += [
        f"algo.total_steps={steps}",
        "algo.run_test=True",
        f"seed={seed}",
        f"root_dir={trial_dir}",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
    ]
    overrides += [f"{k}={v}" for k, v in params.items()]
    overrides += list(extra_overrides)
    try:
        reward = run(overrides)
    except Exception as err:  # noqa: BLE001 - a failed trial must not kill the study
        (trial_dir / "error.txt").write_text(f"{type(err).__name__}: {err}\n")
        return float("-inf")
    return float(reward) if reward is not None else float("-inf")


def successive_halving(args: argparse.Namespace) -> List[Dict[str, Any]]:
    """Run the study; returns per-trial result records (all rungs)."""
    space = json.loads(args.space) if args.space else dict(DEFAULT_SPACE)
    output_dir = Path(args.output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)

    top_budget = max(1, int(math.ceil(args.full_steps * args.fidelity_frac)))
    # rung budgets grow toward the top fidelity: b_r = top * rf^(r - last)
    budgets = [
        max(1, top_budget // (args.reduction_factor ** (args.rungs - 1 - r))) for r in range(args.rungs)
    ]

    trials = [
        {"trial_id": i, "seed": args.seed0 + i, "params": p}
        for i, p in enumerate(sample_trials(space, args.n_trials, args.sampler, args.seed0))
    ]
    records: List[Dict[str, Any]] = []
    survivors = trials
    for rung, budget in enumerate(budgets):
        print(f"[search] rung {rung}: {len(survivors)} trials x {budget} steps")
        scored = []
        for t in survivors:
            tic = time.time()
            trial_dir = output_dir / f"trial_{t['trial_id']}" / f"rung_{rung}"
            value = run_trial(
                args.exp, t["params"], budget, t["seed"], trial_dir, args.env, args.override
            )
            record = {
                "trial_id": t["trial_id"],
                "rung": rung,
                "steps": budget,
                "seed": t["seed"],
                **t["params"],
                "eval_return": value,
                "wall_time_s": round(time.time() - tic, 2),
                "state": "COMPLETE" if math.isfinite(value) else "FAILED",
            }
            records.append(record)
            with open(output_dir / f"trial_{t['trial_id']}" / "results.json", "w") as fp:
                json.dump(record, fp, indent=2)
            scored.append((value, t))
            print(f"[search]   trial {t['trial_id']}: return={value:.4f}")
        scored.sort(key=lambda x: x[0], reverse=True)
        keep = max(1, len(scored) // args.reduction_factor)
        survivors = [t for _, t in scored[:keep]]
        if rung == len(budgets) - 1 or len(survivors) == 1:
            break
    return records


def save_study(records: List[Dict[str, Any]], args: argparse.Namespace) -> None:
    output_dir = Path(args.output_dir)
    fieldnames = sorted({k for r in records for k in r})
    with open(output_dir / "results.csv", "w", newline="") as fp:
        writer = csv.DictWriter(fp, fieldnames=fieldnames)
        writer.writeheader()
        writer.writerows(records)

    # rank by the best return any rung achieved
    best_by_trial: Dict[int, Dict[str, Any]] = {}
    for r in records:
        cur = best_by_trial.get(r["trial_id"])
        if cur is None or r["eval_return"] > cur["eval_return"]:
            best_by_trial[r["trial_id"]] = r
    ranked = sorted(best_by_trial.values(), key=lambda r: r["eval_return"], reverse=True)
    param_keys = [k for k in ranked[0] if k.startswith("algo.") or k.startswith("env.")] if ranked else []

    top_k = ranked[: min(6, len(ranked))]
    with open(output_dir / "topk.json", "w") as fp:
        json.dump(
            [
                {
                    "rank": i + 1,
                    "trial_id": r["trial_id"],
                    "best_eval_return": r["eval_return"],
                    "params": {k: r[k] for k in param_keys},
                }
                for i, r in enumerate(top_k)
            ],
            fp,
            indent=2,
        )

    if ranked:
        best = ranked[0]
        best_cfg: Dict[str, Any] = {"exp": args.exp, "seed": best["seed"], "best_eval_return": best["eval_return"]}
        for k in param_keys:
            node = best_cfg
            parts = k.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = best[k]
        with open(output_dir / "best_config.yaml", "w") as fp:
            yaml.safe_dump(best_cfg, fp, sort_keys=False)

    with open(output_dir / "SUMMARY.md", "w") as fp:
        fp.write("# Hyperparameter Search Summary\n\n")
        fp.write(f"**Experiment**: {args.exp}\n")
        fp.write(f"**Trials**: {args.n_trials} ({args.sampler} sampling, ")
        fp.write(f"{args.rungs} rungs, reduction factor {args.reduction_factor})\n")
        fp.write(f"**Top-rung budget**: {int(math.ceil(args.full_steps * args.fidelity_frac))} steps\n\n")
        fp.write("| Rank | Trial | Best return | Params |\n|---|---|---|---|\n")
        for i, r in enumerate(top_k):
            params = ", ".join(f"{k.split('.')[-1]}={r[k]}" for k in param_keys)
            fp.write(f"| {i + 1} | {r['trial_id']} | {r['eval_return']:.4f} | {params} |\n")
        if ranked:
            best = ranked[0]
            fp.write("\n## Best command for Phase 2\n\n```bash\nsheeprl exp=" + args.exp)
            for k in param_keys:
                fp.write(f" \\\n  {k}={best[k]}")
            fp.write(f" \\\n  algo.total_steps={args.full_steps}\n```\n")


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = parse_args(argv)
    records = successive_halving(args)
    save_study(records, args)
    finished = [r for r in records if r["state"] == "COMPLETE"]
    print(f"[search] done: {len(finished)}/{len(records)} rung-runs completed -> {args.output_dir}")


if __name__ == "__main__":
    main()
