"""Hyperparameter search harness.

TPU-native re-design of the fork's Phase-1 JEPA search
(/root/reference/search_phase1.py:1-568 + dreamer_v3_jepa_search.py:683-722).
The reference drives Optuna (TPE sampler + Hyperband/ASHA pruner) around
subprocess-style trials; this image has no Optuna, so the harness implements
the same search shape self-contained:

- a categorical search space (default: the reference's Phase-1 JEPA grid —
  ``jepa_coef`` x ``jepa_ema`` x ``jepa_mask.erase_frac``);
- ``random``, ``grid``, or ``tpe`` sampling — the TPE sampler is a
  Tree-structured Parzen Estimator over categorical choices: observed trials
  split into good (top ``gamma`` quantile) / bad, each candidate scored by
  ``log l(x) - log g(x)`` with Laplace-smoothed per-key densities, best of
  ``n_candidates`` drawn from ``l`` wins (Bergstra et al. 2011, the sampler
  Optuna's TPESampler implements);
- two schedulers: synchronous successive halving (every rung multiplies the
  per-trial budget by ``reduction_factor`` and keeps the top
  ``1/reduction_factor``) and ``asha`` — asynchronous successive halving
  (Li et al. 2018): each new trial starts at rung 0 and is promoted rung by
  rung whenever it ranks in the top ``1/reduction_factor`` of its rung's
  results so far, so good configs reach high fidelity without waiting for a
  full rung cohort, and the TPE sampler conditions each proposal on every
  prior trial's highest-fidelity result;
- each trial runs IN PROCESS through the real CLI composer
  (``sheeprl_tpu.cli.run``) with ``algo.run_test=True``; the objective is the
  returned final-test cumulative reward.

Artifacts mirror the reference: ``results.csv``, ``topk.json``,
``best_config.yaml``, ``SUMMARY.md`` under ``--output-dir``.
"""

from __future__ import annotations

import argparse
import csv
import itertools
import json
import math
import os
import random
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import yaml

#: the reference Phase-1 space (search_phase1.py:155-158)
DEFAULT_SPACE: Dict[str, List[Any]] = {
    "algo.jepa_coef": [0.3, 1.0, 3.0],
    "algo.jepa_ema": [0.992, 0.996, 0.999],
    "algo.jepa_mask.erase_frac": [0.4, 0.6],
}


def parse_args(argv: Optional[Sequence[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="Successive-halving hyperparameter search")
    parser.add_argument("--exp", type=str, default="dreamer_v3_jepa", help="exp config to search over")
    parser.add_argument("--env", type=str, default=None, help="env config override (e.g. 'atari', 'dmc')")
    parser.add_argument("--full-steps", type=int, required=True, help="full training steps of Phase 2")
    parser.add_argument("--fidelity-frac", type=float, default=0.15, help="top-rung budget fraction")
    parser.add_argument("--n-trials", type=int, default=20)
    parser.add_argument("--reduction-factor", type=int, default=3, help="halving rate between rungs")
    parser.add_argument("--rungs", type=int, default=2, help="number of successive-halving rungs")
    parser.add_argument("--sampler", type=str, default="tpe", choices=["random", "grid", "tpe"])
    parser.add_argument(
        "--scheduler", type=str, default="asha", choices=["halving", "asha"],
        help="synchronous successive halving vs asynchronous (promotion-based) ASHA",
    )
    parser.add_argument("--tpe-gamma", type=float, default=0.25, help="TPE good-quantile")
    parser.add_argument("--tpe-startup", type=int, default=8, help="random trials before TPE kicks in")
    parser.add_argument("--tpe-candidates", type=int, default=24, help="TPE candidate draws per trial")
    parser.add_argument("--seed0", type=int, default=0, help="base seed; trial i runs with seed0+i")
    parser.add_argument("--output-dir", type=str, default="./runs/phase1")
    parser.add_argument(
        "--space",
        type=str,
        default=None,
        help="JSON dict of {config.key: [choices...]} replacing the default JEPA space",
    )
    parser.add_argument(
        "--override",
        action="append",
        default=[],
        help="extra config overrides applied to every trial (repeatable)",
    )
    args = parser.parse_args(argv)
    if args.full_steps <= 0:
        raise ValueError(f"full_steps must be > 0, got {args.full_steps}")
    if not 0.0 < args.fidelity_frac <= 1.0:
        raise ValueError(f"fidelity_frac must be in (0, 1], got {args.fidelity_frac}")
    if args.n_trials < 1:
        raise ValueError(f"n_trials must be >= 1, got {args.n_trials}")
    if args.reduction_factor < 2:
        raise ValueError(f"reduction_factor must be >= 2, got {args.reduction_factor}")
    return args


def sample_trials(space: Dict[str, List[Any]], n_trials: int, sampler: str, seed: int) -> List[Dict[str, Any]]:
    """Draw ``n_trials`` parameter assignments from a categorical space."""
    keys = sorted(space)
    if sampler == "grid":
        grid = list(itertools.product(*(space[k] for k in keys)))
        rng = random.Random(seed)
        rng.shuffle(grid)
        picks = (grid * math.ceil(n_trials / len(grid)))[:n_trials]
        return [dict(zip(keys, p)) for p in picks]
    rng = random.Random(seed)
    return [{k: rng.choice(space[k]) for k in keys} for _ in range(n_trials)]


class TPESampler:
    """Tree-structured Parzen Estimator over a categorical space.

    ``observations`` are ``(params, value)`` with HIGHER better.  Choices are
    scored by ``log l(x) - log g(x)`` where ``l``/``g`` are Laplace-smoothed
    empirical categoricals of the good/bad split at quantile ``gamma``; the
    candidate maximizing the acquisition among ``n_candidates`` draws from
    ``l`` is proposed.  Until ``n_startup`` observations exist, sampling is
    uniform (Optuna TPESampler's ``n_startup_trials`` semantics)."""

    def __init__(
        self,
        space: Dict[str, List[Any]],
        seed: int = 0,
        gamma: float = 0.25,
        n_startup: int = 8,
        n_candidates: int = 24,
    ):
        if not 0.0 < gamma < 1.0:
            raise ValueError(f"gamma must be in (0, 1), got {gamma}")
        self.space = {k: list(v) for k, v in space.items()}
        self.keys = sorted(space)
        self.rng = random.Random(seed)
        self.gamma = gamma
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.observations: List[tuple] = []

    def tell(self, params: Dict[str, Any], value: float) -> None:
        if math.isfinite(value):
            self.observations.append((params, value))

    def _smoothed(self, values: List[Any], choices: List[Any]) -> Dict[Any, float]:
        counts = {c: 1.0 for c in choices}  # Laplace prior
        for v in values:
            counts[v] = counts.get(v, 1.0) + 1.0
        total = sum(counts.values())
        return {c: counts[c] / total for c in choices}

    def ask(self) -> Dict[str, Any]:
        # below 2 observations the good/bad split cannot be disjoint: the sole
        # point would land in both sides and self-penalize (its l/g densities
        # cancel), so the candidate scoring degenerates — stay on random
        # sampling until a real split exists, whatever n_startup says
        if len(self.observations) < max(2, self.n_startup):
            return {k: self.rng.choice(self.space[k]) for k in self.keys}
        ranked = sorted(self.observations, key=lambda o: o[1], reverse=True)
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        if len(ranked) > 1:
            # keep good/bad disjoint: with a tiny study n_good can otherwise
            # cover every observation, making the worst one penalize itself
            n_good = min(n_good, len(ranked) - 1)
        good, bad = ranked[:n_good], ranked[n_good:] or ranked[-1:]
        l_dist = {k: self._smoothed([p[k] for p, _ in good], self.space[k]) for k in self.keys}
        g_dist = {k: self._smoothed([p[k] for p, _ in bad], self.space[k]) for k in self.keys}
        best, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            cand = {
                k: self.rng.choices(self.space[k], weights=[l_dist[k][c] for c in self.space[k]])[0]
                for k in self.keys
            }
            score = sum(math.log(l_dist[k][cand[k]]) - math.log(g_dist[k][cand[k]]) for k in self.keys)
            if score > best_score:
                best, best_score = cand, score
        return best


def run_trial(
    exp: str,
    params: Dict[str, Any],
    steps: int,
    seed: int,
    trial_dir: Path,
    env: Optional[str] = None,
    extra_overrides: Sequence[str] = (),
) -> float:
    """One training run through the real CLI; returns the final test reward
    (``-inf`` on failure so the rung ranking drops the trial)."""
    from sheeprl_tpu.cli import run

    trial_dir.mkdir(parents=True, exist_ok=True)
    overrides = [f"exp={exp}"]
    if env:
        overrides.append(f"env={env}")
    overrides += [
        f"algo.total_steps={steps}",
        "algo.run_test=True",
        f"seed={seed}",
        f"root_dir={trial_dir}",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
    ]
    overrides += [f"{k}={v}" for k, v in params.items()]
    overrides += list(extra_overrides)
    try:
        reward = run(overrides)
    except Exception as err:  # noqa: BLE001 - a failed trial must not kill the study
        (trial_dir / "error.txt").write_text(f"{type(err).__name__}: {err}\n")
        return float("-inf")
    return float(reward) if reward is not None else float("-inf")


def _rung_budgets(args: argparse.Namespace) -> List[int]:
    """Budgets grow toward the top fidelity: b_r = top * rf^(r - last)."""
    top_budget = max(1, int(math.ceil(args.full_steps * args.fidelity_frac)))
    return [
        max(1, top_budget // (args.reduction_factor ** (args.rungs - 1 - r))) for r in range(args.rungs)
    ]


def _default_objective(args: argparse.Namespace):
    output_dir = Path(args.output_dir)

    def objective(params: Dict[str, Any], steps: int, seed: int, trial_id: int, rung: int) -> float:
        trial_dir = output_dir / f"trial_{trial_id}" / f"rung_{rung}"
        return run_trial(args.exp, params, steps, seed, trial_dir, args.env, args.override)

    return objective


def _make_sampler(args: argparse.Namespace, space: Dict[str, List[Any]]):
    """An ask/tell sampler.  random/grid pre-draw the whole cohort; tpe
    proposes sequentially from what it has seen."""
    if args.sampler == "tpe":
        return TPESampler(
            space,
            seed=args.seed0,
            gamma=args.tpe_gamma,
            n_startup=args.tpe_startup,
            n_candidates=args.tpe_candidates,
        )

    class _Pre:
        def __init__(self):
            self._draws = iter(sample_trials(space, args.n_trials, args.sampler, args.seed0))

        def ask(self) -> Dict[str, Any]:
            return next(self._draws)

        def tell(self, params: Dict[str, Any], value: float) -> None:
            pass

    return _Pre()


def _record(records, output_dir, trial_id, rung, steps, seed, params, value, tic):
    record = {
        "trial_id": trial_id,
        "rung": rung,
        "steps": steps,
        "seed": seed,
        **params,
        "eval_return": value,
        "wall_time_s": round(time.time() - tic, 2),
        "state": "COMPLETE" if math.isfinite(value) else "FAILED",
    }
    records.append(record)
    trial_dir = output_dir / f"trial_{trial_id}"
    trial_dir.mkdir(parents=True, exist_ok=True)
    with open(trial_dir / "results.json", "w") as fp:
        json.dump(record, fp, indent=2)
    return record


def successive_halving(args: argparse.Namespace, objective=None) -> List[Dict[str, Any]]:
    """Synchronous successive halving; returns per-trial records (all rungs).
    Rung 0 runs sequentially through the sampler's ask/tell loop, so the TPE
    sampler conditions each proposal on every rung-0 result seen so far (the
    cohort barrier means higher rungs complete only after sampling ends)."""
    space = json.loads(args.space) if args.space else dict(DEFAULT_SPACE)
    output_dir = Path(args.output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    objective = objective or _default_objective(args)
    budgets = _rung_budgets(args)
    sampler = _make_sampler(args, space)

    records: List[Dict[str, Any]] = []
    survivors = []
    for rung, budget in enumerate(budgets):
        n = args.n_trials if rung == 0 else len(survivors)
        print(f"[search] rung {rung}: {n} trials x {budget} steps")
        scored = []
        for i in range(n):
            if rung == 0:
                t = {"trial_id": i, "seed": args.seed0 + i, "params": sampler.ask()}
            else:
                t = survivors[i]
            tic = time.time()
            value = objective(t["params"], budget, t["seed"], t["trial_id"], rung)
            if rung == 0:
                sampler.tell(t["params"], value)
            _record(records, output_dir, t["trial_id"], rung, budget, t["seed"], t["params"], value, tic)
            scored.append((value, t))
            print(f"[search]   trial {t['trial_id']}: return={value:.4f}")
        scored.sort(key=lambda x: x[0], reverse=True)
        keep = max(1, len(scored) // args.reduction_factor)
        survivors = [t for _, t in scored[:keep]]
        if rung == len(budgets) - 1 or len(survivors) == 1:
            break
    return records


def asha(args: argparse.Namespace, objective=None) -> List[Dict[str, Any]]:
    """Asynchronous successive halving (Li et al. 2018), sequential driver.

    Each trial starts at rung 0; after finishing rung r it is promoted to
    rung r+1 immediately if it ranks in the top ``1/reduction_factor`` of all
    rung-r results observed SO FAR (with at least ``reduction_factor``
    results to rank against).  No rung-cohort barrier: a strong early trial
    reaches the top fidelity while the study is still exploring, and the TPE
    sampler conditions on every completed evaluation."""
    space = json.loads(args.space) if args.space else dict(DEFAULT_SPACE)
    output_dir = Path(args.output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    objective = objective or _default_objective(args)
    budgets = _rung_budgets(args)
    eta = args.reduction_factor
    sampler = _make_sampler(args, space)

    records: List[Dict[str, Any]] = []
    rung_values: List[List[float]] = [[] for _ in budgets]
    for i in range(args.n_trials):
        params = sampler.ask()
        seed = args.seed0 + i
        rung = 0
        last_finite = -math.inf
        while True:
            tic = time.time()
            value = objective(params, budgets[rung], seed, i, rung)
            if math.isfinite(value):
                last_finite = value
            _record(records, output_dir, i, rung, budgets[rung], seed, params, value, tic)
            print(f"[search]   trial {i} rung {rung}: return={value:.4f}")
            rung_values[rung].append(value)
            if rung + 1 >= len(budgets) or not math.isfinite(value):
                break
            seen = sorted(rung_values[rung], reverse=True)
            top_k = max(1, len(seen) // eta)
            if len(seen) >= eta and value >= seen[top_k - 1]:
                rung += 1  # promoted: re-run at the next fidelity
            else:
                break
        # the sampler conditions on the trial's HIGHEST-fidelity result (the
        # least blurred view of the config, like Optuna studies that report
        # the final intermediate value of pruned trials)
        sampler.tell(params, last_finite)
    return records


def save_study(records: List[Dict[str, Any]], args: argparse.Namespace) -> None:
    output_dir = Path(args.output_dir)
    fieldnames = sorted({k for r in records for k in r})
    with open(output_dir / "results.csv", "w", newline="") as fp:
        writer = csv.DictWriter(fp, fieldnames=fieldnames)
        writer.writeheader()
        writer.writerows(records)

    # rank by the best return any rung achieved
    best_by_trial: Dict[int, Dict[str, Any]] = {}
    for r in records:
        cur = best_by_trial.get(r["trial_id"])
        if cur is None or r["eval_return"] > cur["eval_return"]:
            best_by_trial[r["trial_id"]] = r
    ranked = sorted(best_by_trial.values(), key=lambda r: r["eval_return"], reverse=True)
    param_keys = [k for k in ranked[0] if k.startswith("algo.") or k.startswith("env.")] if ranked else []

    top_k = ranked[: min(6, len(ranked))]
    with open(output_dir / "topk.json", "w") as fp:
        json.dump(
            [
                {
                    "rank": i + 1,
                    "trial_id": r["trial_id"],
                    "best_eval_return": r["eval_return"],
                    "params": {k: r[k] for k in param_keys},
                }
                for i, r in enumerate(top_k)
            ],
            fp,
            indent=2,
        )

    if ranked:
        best = ranked[0]
        best_cfg: Dict[str, Any] = {"exp": args.exp, "seed": best["seed"], "best_eval_return": best["eval_return"]}
        for k in param_keys:
            node = best_cfg
            parts = k.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = best[k]
        with open(output_dir / "best_config.yaml", "w") as fp:
            yaml.safe_dump(best_cfg, fp, sort_keys=False)

    with open(output_dir / "SUMMARY.md", "w") as fp:
        fp.write("# Hyperparameter Search Summary\n\n")
        fp.write(f"**Experiment**: {args.exp}\n")
        fp.write(f"**Trials**: {args.n_trials} ({args.sampler} sampling, ")
        fp.write(f"{args.rungs} rungs, reduction factor {args.reduction_factor})\n")
        fp.write(f"**Top-rung budget**: {int(math.ceil(args.full_steps * args.fidelity_frac))} steps\n\n")
        fp.write("| Rank | Trial | Best return | Params |\n|---|---|---|---|\n")
        for i, r in enumerate(top_k):
            params = ", ".join(f"{k.split('.')[-1]}={r[k]}" for k in param_keys)
            fp.write(f"| {i + 1} | {r['trial_id']} | {r['eval_return']:.4f} | {params} |\n")
        if ranked:
            best = ranked[0]
            fp.write("\n## Best command for Phase 2\n\n```bash\nsheeprl exp=" + args.exp)
            for k in param_keys:
                fp.write(f" \\\n  {k}={best[k]}")
            fp.write(f" \\\n  algo.total_steps={args.full_steps}\n```\n")


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = parse_args(argv)
    records = asha(args) if args.scheduler == "asha" else successive_halving(args)
    save_study(records, args)
    finished = [r for r in records if r["state"] == "COMPLETE"]
    print(f"[search] done: {len(finished)}/{len(records)} rung-runs completed -> {args.output_dir}")


if __name__ == "__main__":
    main()
