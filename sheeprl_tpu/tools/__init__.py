"""Auxiliary tooling: hyperparameter search, reward-log recovery/analysis
(the fork's repo-root scripts ``search_phase1.py``, ``recover_reward_logs.py``,
``analyze_rewards.py`` — see each module for the reference mapping)."""
