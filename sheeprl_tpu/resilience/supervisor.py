"""Auto-restart supervisor: ``sheeprl-supervise`` / ``tools/supervise.py``.

Wraps ``cli.run`` as a child process and owns the kill-to-recovered loop:

* a child that exits cleanly (0) ends supervision;
* any non-clean exit — crash, OOM-kill, SIGKILL from the scheduler, or the
  graceful-preemption code 75 — triggers a restart with capped exponential
  backoff (preempted exits skip the backoff: the emergency snapshot already
  landed and the pool wants the slot back *now*) under a total restart
  budget;
* every restart resumes from the newest checkpoint whose manifest verifies
  (``checkpoint.resume_from=<run dir>`` semantics — corrupt/partial files
  are skipped, never crashed on), or from scratch when none exists yet;
* each restart is journaled to ``<run dir>/supervisor.jsonl`` (``restart``
  events: attempt, rc, backoff, measured downtime, resume source) so
  ``tools/goodput_report.py`` reports time-to-recover measured on real
  kill/resume cycles rather than inferred from segment gaps.

The run name must be pinned for resumes to land in the same run dir; when the
caller does not pass ``run_name=...`` the supervisor pins the composed
(timestamped) one and says so.

``--kill-after-first-checkpoint`` is the chaos drill used by the e2e tests
and ``bench.py``'s recovery block: the supervisor SIGKILLs its *first* child
the moment a verified checkpoint exists, then lets the normal restart path
prove the whole cycle.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from sheeprl_tpu.resilience.monitor import RESTARTS_ENV_VAR
from sheeprl_tpu.resilience.preemption import PREEMPTED_EXIT_CODE

SUPERVISOR_JOURNAL = "supervisor.jsonl"


def backoff_delay(attempt: int, base_s: float, cap_s: float) -> float:
    """Capped exponential: ``base * 2**(attempt-1)``, clamped to ``cap``."""
    if attempt <= 0:
        return 0.0
    return float(min(cap_s, base_s * (2 ** (attempt - 1))))


def _child_env(restarts: int) -> dict:
    env = dict(os.environ)
    # the child must import sheeprl_tpu from the same checkout/venv we did
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    existing = env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        env["PYTHONPATH"] = pkg_root + (os.pathsep + existing if existing else "")
    env[RESTARTS_ENV_VAR] = str(int(restarts))
    return env


def _kill_after_checkpoint(proc: subprocess.Popen, run_dir: str, poll_s: float) -> None:
    """Drill thread: SIGKILL the child the instant a verified checkpoint
    exists under the run dir (simulates the scheduler's no-grace kill)."""
    from sheeprl_tpu.resilience.manifest import newest_verified_checkpoint

    while proc.poll() is None:
        best, _ = newest_verified_checkpoint(run_dir, deep=True)
        if best is not None:
            try:
                proc.send_signal(signal.SIGKILL)
            except OSError:  # pragma: no cover - child already gone
                pass
            return
        time.sleep(poll_s)


def supervise_command(
    argv_builder: Callable[[Optional[str]], List[str]],
    run_dir: str,
    max_restarts: int = 5,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    kill_after_first_checkpoint: bool = False,
    poll_s: float = 0.5,
    sleep_fn: Callable[[float], None] = time.sleep,
) -> int:
    """Core restart loop over an arbitrary child command.

    ``argv_builder(resume_path)`` produces the child argv for this attempt —
    the indirection keeps the loop unit-testable with stub children.
    Returns the exit code supervision ends with (0 = the run completed).
    """
    from sheeprl_tpu.diagnostics.journal import RunJournal
    from sheeprl_tpu.resilience.manifest import newest_verified_checkpoint

    os.makedirs(run_dir, exist_ok=True)
    journal = RunJournal(os.path.join(run_dir, SUPERVISOR_JOURNAL))
    restarts = 0
    last_rc: Optional[int] = None
    exit_t: Optional[float] = None
    backoff_s = 0.0
    drill_pending = bool(kill_after_first_checkpoint)
    try:
        while True:
            resume, _skipped = newest_verified_checkpoint(run_dir, deep=True)
            if restarts > 0:
                journal.write(
                    "restart",
                    attempt=restarts,
                    rc=last_rc,
                    preempted=last_rc == PREEMPTED_EXIT_CODE,
                    backoff_s=round(backoff_s, 3),
                    down_s=round(time.time() - exit_t, 3) if exit_t is not None else None,
                    resume_from=resume,
                )
                journal.sync()
            argv = argv_builder(resume)
            proc = subprocess.Popen(argv, env=_child_env(restarts))
            if drill_pending:
                drill_pending = False
                threading.Thread(
                    target=_kill_after_checkpoint,
                    args=(proc, run_dir, poll_s),
                    name="sheeprl-supervise-drill",
                    daemon=True,
                ).start()
            try:
                rc = proc.wait()
            except KeyboardInterrupt:
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    proc.kill()
                raise
            exit_t = time.time()
            if rc == 0:
                return 0
            last_rc = rc
            if restarts >= max_restarts:
                journal.write("restart", attempt=restarts, rc=rc, gave_up=True)
                journal.sync()
                print(
                    f"sheeprl-supervise: restart budget exhausted after {restarts} "
                    f"restart(s); last exit code {rc}",
                    file=sys.stderr,
                )
                return rc
            restarts += 1
            # graceful preemption already saved its snapshot and freed the
            # slot on purpose — respawn immediately; crashes back off
            backoff_s = 0.0 if rc == PREEMPTED_EXIT_CODE else backoff_delay(
                restarts, backoff_base_s, backoff_max_s
            )
            print(
                f"sheeprl-supervise: child exited rc={rc}"
                f"{' (preempted)' if rc == PREEMPTED_EXIT_CODE else ''}; "
                f"restart {restarts}/{max_restarts} in {backoff_s:.1f}s",
                file=sys.stderr,
            )
            if backoff_s > 0:
                sleep_fn(backoff_s)
    finally:
        journal.close()


def supervise(
    overrides: Sequence[str],
    max_restarts: int = 5,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    kill_after_first_checkpoint: bool = False,
) -> int:
    """Supervise a ``cli.run`` training described by Hydra-style overrides."""
    from sheeprl_tpu.config import compose

    overrides = list(overrides)
    cfg = compose(overrides)
    if not any(str(o).startswith("run_name=") for o in overrides):
        # resumes must land in the SAME run dir: pin the composed
        # (timestamped) run name for every child
        overrides.append(f"run_name={cfg.run_name}")
        print(
            f"sheeprl-supervise: run_name not pinned; using '{cfg.run_name}' "
            "for every (re)start",
            file=sys.stderr,
        )
    run_dir = os.path.join("logs", "runs", str(cfg.root_dir), str(cfg.run_name))

    def argv_builder(resume: Optional[str]) -> List[str]:
        argv = [sys.executable, "-m", "sheeprl_tpu", *overrides]
        if resume is not None:
            argv.append(f"checkpoint.resume_from={resume}")
        return argv

    return supervise_command(
        argv_builder,
        run_dir,
        max_restarts=max_restarts,
        backoff_base_s=backoff_base_s,
        backoff_max_s=backoff_max_s,
        kill_after_first_checkpoint=kill_after_first_checkpoint,
    )


def main(args: Optional[Sequence[str]] = None) -> Any:
    parser = argparse.ArgumentParser(
        description="Auto-restart supervisor for sheeprl-tpu training runs "
        "(resumes from the newest verified checkpoint after any non-clean exit)."
    )
    parser.add_argument("--max-restarts", type=int, default=5, help="restart budget (default 5)")
    parser.add_argument(
        "--backoff", type=float, default=1.0, help="base backoff seconds (doubles per restart)"
    )
    parser.add_argument("--backoff-max", type=float, default=60.0, help="backoff cap in seconds")
    parser.add_argument(
        "--kill-after-first-checkpoint",
        action="store_true",
        help="chaos drill: SIGKILL the first child once a verified checkpoint "
        "exists, then recover through the normal restart path",
    )
    parser.add_argument(
        "overrides", nargs=argparse.REMAINDER, help="Hydra-style overrides passed to cli.run"
    )
    ns = parser.parse_args(list(args) if args is not None else None)
    overrides = [o for o in ns.overrides if o != "--"]
    return sys.exit(
        supervise(
            overrides,
            max_restarts=ns.max_restarts,
            backoff_base_s=ns.backoff,
            backoff_max_s=ns.backoff_max,
            kill_after_first_checkpoint=ns.kill_after_first_checkpoint,
        )
    )


if __name__ == "__main__":
    main()
