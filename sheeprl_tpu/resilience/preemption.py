"""Graceful preemption: signal → emergency snapshot → distinct exit code.

Preemptible pools deliver SIGTERM with a grace window before the SIGKILL.
:class:`PreemptionGuard` turns the signal into a *flag* the training loops
poll at their checkpoint boundary (``diag.preempt_due``): the loop then takes
an emergency checkpoint through the normal save path, the facade journals a
fsync'd ``preempted`` event, drains the async writer so the snapshot is
durable, and raises :class:`PreemptedExit` — a ``SystemExit`` carrying
:data:`PREEMPTED_EXIT_CODE` so the supervisor (and any orchestration layer)
can tell "preempted with a fresh checkpoint, resume me" apart from a crash
(nonzero traceback exit) and from clean completion (0).

A second signal of the same kind restores the previous handler and re-raises
it: a stuck loop can always be force-killed the normal way.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Dict, Optional, Sequence

#: EX_TEMPFAIL — "temporary failure, retry": distinct from clean completion
#: (0) and from crash exits (1 / signal deaths), chosen so shell tooling and
#: the supervisor can branch on it.
PREEMPTED_EXIT_CODE = 75


class PreemptedExit(SystemExit):
    """Raised at the loop boundary after the emergency snapshot landed."""

    def __init__(self, message: str = ""):
        super().__init__(PREEMPTED_EXIT_CODE)
        self.message = message

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.message or f"preempted (exit {PREEMPTED_EXIT_CODE})"


class PreemptionGuard:
    """Installable SIGTERM/SIGINT → preemption-requested flag.

    Handlers can only be installed from the main thread; elsewhere (e.g. a
    test harness driving the loop from a worker thread) :meth:`install`
    returns False and the guard stays inert — the ``inject_preempt_iter``
    drill does not need real signals.
    """

    def __init__(self, signals: Sequence[str] = ("SIGTERM", "SIGINT")):
        self.signal_names = tuple(signals)
        self._requested = False
        self._signum: Optional[int] = None
        self._previous: Dict[int, object] = {}
        self._installed = False

    # -- handler ------------------------------------------------------------
    def _handle(self, signum, frame) -> None:  # noqa: ANN001 - signal API
        if self._requested:
            # second signal: restore the previous disposition and re-deliver —
            # a wedged loop must stay force-killable
            previous = self._previous.get(signum, signal.SIG_DFL)
            try:
                signal.signal(signum, previous)  # type: ignore[arg-type]
            except (ValueError, TypeError):  # pragma: no cover
                pass
            os.kill(os.getpid(), signum)
            return
        self._requested = True
        self._signum = signum

    # -- lifecycle ----------------------------------------------------------
    def install(self) -> bool:
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            return False
        for name in self.signal_names:
            signum = getattr(signal, name, None)
            if signum is None:  # pragma: no cover - platform-dependent
                continue
            try:
                self._previous[int(signum)] = signal.signal(signum, self._handle)
            except (ValueError, OSError):  # pragma: no cover - exotic runtimes
                continue
        self._installed = bool(self._previous)
        return self._installed

    def uninstall(self) -> None:
        if not self._installed:
            return
        if threading.current_thread() is threading.main_thread():
            for signum, previous in self._previous.items():
                try:
                    if signal.getsignal(signum) == self._handle:
                        signal.signal(signum, previous)  # type: ignore[arg-type]
                except (ValueError, TypeError):  # pragma: no cover
                    continue
        self._previous.clear()
        self._installed = False

    # -- state --------------------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._requested

    @property
    def signal_name(self) -> Optional[str]:
        if self._signum is None:
            return None
        try:
            return signal.Signals(self._signum).name
        except ValueError:  # pragma: no cover
            return str(self._signum)
