"""Coordinated multi-host snapshots (ISSUE 14 tentpole pillar 3).

Checkpointing was rank-0-only: under ``jax.distributed`` only the global-zero
host wrote a file, so per-host state (and any future per-rank sharded state)
was silently dropped and a restart could resume from a step no other rank
agreed on.  This module makes the checkpoint a *group* artifact:

1. every process barriers at the checkpoint boundary (no rank writes while
   another is still training toward a different step);
2. the group **broadcast-agrees on the step** (rank 0's parse of the
   checkpoint path wins — the one number all manifests must share);
3. each rank writes its own shard — ``ckpt_<step>_<rank>.ckpt`` — whose
   manifest sidecar records ``{"group": {"world_size", "rank",
   "group_step"}}``; rank 0 still routes through the async writer, other
   ranks write blocking (their loops are at the barrier anyway);
4. resume-time selection (``resilience/manifest.py``) treats a step as
   resumable only when EVERY participating rank's shard verifies — a torn
   snapshot (one shard missing/corrupt/step-mismatched) is skipped with a
   journaled ``ckpt_skipped reason=incomplete_group`` and the previous
   complete group is used instead.

Single-process runs never enter this path: ``Runtime.save`` keeps its exact
pre-existing behavior (no group record in the manifest, bit-identical
sidecars), so every current producer/consumer is untouched.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Mapping, Optional, Tuple

from sheeprl_tpu.resilience.manifest import (
    checkpoint_step,
    read_manifest,
    save_verified_checkpoint,
    verify_checkpoint,
)

_RANK_RE = re.compile(r"^(?P<stem>.*ckpt_\d+)_(?P<rank>\d+)\.ckpt$")
_FALLBACK_RANK_RE = re.compile(r"^(?P<stem>.*)\.rank\d+(?P<ext>\.[^.]+)$")


def rank_shard_path(ckpt_path: str, rank: int) -> str:
    """``.../ckpt_<step>_0.ckpt`` → ``.../ckpt_<step>_<rank>.ckpt`` (the
    loops' filename convention); a path without the rank suffix gains
    ``.rank<r>`` before its extension so exotic names still shard safely.
    Idempotent on BOTH spellings: mapping an existing shard to another rank
    replaces its marker (group_status derives siblings from a shard path,
    so ``last.rank0.ckpt`` must map to ``last.rank1.ckpt``, never to
    ``last.rank0.rank1.ckpt``)."""
    ckpt_path = str(ckpt_path)
    match = _RANK_RE.match(ckpt_path)
    if match:
        return f"{match.group('stem')}_{int(rank)}.ckpt"
    match = _FALLBACK_RANK_RE.match(ckpt_path)
    if match:
        return f"{match.group('stem')}.rank{int(rank)}{match.group('ext')}"
    root, ext = os.path.splitext(ckpt_path)
    return f"{root}.rank{int(rank)}{ext}"


def group_record(world_size: int, rank: int, group_step: Optional[int]) -> Dict[str, Any]:
    return {"world_size": int(world_size), "rank": int(rank), "group_step": group_step}


def group_status(
    ckpt_path: str, deep: bool = True, assume_verified: Tuple[int, ...] = ()
) -> Tuple[bool, str]:
    """``(complete, reason)`` for the snapshot group a checkpoint belongs to.

    A checkpoint without a group record (single-process, legacy) is trivially
    complete.  A grouped one is complete only when every rank's shard exists,
    verifies, and records the same ``group_step`` — anything else is
    ``incomplete_group`` (the torn-snapshot skip reason).  ``assume_verified``
    names ranks whose shard content the caller has ALREADY (deep-)verified —
    their manifests are still cross-checked, but multi-GB shards are not
    re-hashed a second time.
    """
    entry = read_manifest(ckpt_path)
    group = (entry or {}).get("group")
    if not isinstance(group, Mapping):
        return True, "ungrouped"
    world = int(group.get("world_size", 1) or 1)
    if world <= 1:
        return True, "ungrouped"
    step = group.get("group_step")
    for rank in range(world):
        shard = rank_shard_path(ckpt_path, rank)
        if rank not in assume_verified:
            ok, _reason = verify_checkpoint(shard, deep=deep)
            if not ok:
                return False, "incomplete_group"
        sibling = read_manifest(shard)
        sib_group = (sibling or {}).get("group")
        if not isinstance(sib_group, Mapping) or sib_group.get("group_step") != step:
            return False, "incomplete_group"
    return True, "group_verified"


def shard_rank(ckpt_path: str) -> Optional[int]:
    """The rank recorded in a checkpoint's manifest group, or None for
    ungrouped checkpoints — resume selection only ever returns the rank-0
    (canonical) shard of a group."""
    entry = read_manifest(ckpt_path)
    group = (entry or {}).get("group")
    if not isinstance(group, Mapping) or int(group.get("world_size", 1) or 1) <= 1:
        return None
    try:
        return int(group.get("rank", 0))
    except (TypeError, ValueError):
        return 0


def coordinated_save(runtime, path: str, state: Mapping[str, Any]) -> None:
    """The multi-process ``Runtime.save`` protocol: barrier → broadcast-agree
    on the step → every rank writes its shard (+ group manifest) → barrier.

    Rank 0 routes through the diagnostics resilience layer when present
    (async writer, ``ckpt_begin``/``ckpt_end`` journaling) exactly like the
    single-process path; other ranks write blocking — they are parked at the
    exit barrier regardless, and a blocking write is its own durability
    proof for the group-completeness check.
    """
    import jax

    world = jax.process_count()
    rank = jax.process_index()
    # entry barrier: no shard is written while another rank still trains
    runtime.barrier()
    step = runtime.broadcast(checkpoint_step(path, state), src=0)
    group = group_record(world, rank, step)
    shard = rank_shard_path(path, rank)
    diagnostics = getattr(runtime, "diagnostics", None)
    routed = (
        rank == 0
        and diagnostics is not None
        and diagnostics.save_checkpoint(shard, state, group=group)
    )
    if not routed:
        save_verified_checkpoint(shard, state, step=step, group=group)
    # exit barrier: every rank's write was at least submitted before any loop
    # resumes (durability is the manifest group's job, not the barrier's)
    runtime.barrier()
