"""Preemption-tolerant elastic training (ISSUE 13 — the elasticity half of
ROADMAP item 4).

The goodput layer (:mod:`sheeprl_tpu.diagnostics.goodput`) *measures* whether
a run survives preemptible pools; this package makes it *survive* them.  Four
pillars, wired through the :class:`~sheeprl_tpu.diagnostics.Diagnostics`
facade and ``Runtime.save``:

* :mod:`~sheeprl_tpu.resilience.manifest` — validated checkpoints: every save
  writes a ``<ckpt>.manifest.json`` sidecar (content digest, step, param-tree
  shapes/dtypes, code fingerprint reusing the AOT-cache fingerprint helpers);
  resume selection becomes "newest checkpoint whose manifest verifies"
  instead of the old second-newest-by-mtime heuristic, and corrupt/truncated
  checkpoints are skipped with a journaled ``ckpt_skipped`` reason, never
  crashed on;
* :mod:`~sheeprl_tpu.resilience.async_writer` — async off-critical-path
  checkpointing: the train loop pays one cheap device→host snapshot
  (``jax.device_get`` + a host-buffer copy, double-buffered with
  backpressure) and a background thread serializes/fsyncs through the
  existing atomic tmp+rename in ``utils/checkpoint.py::save_state``,
  journaling ``ckpt_begin``/``ckpt_end`` with write duration and bytes so
  checkpoint cost disappears from the goodput train spans;
* :mod:`~sheeprl_tpu.resilience.preemption` — graceful preemption: a
  SIGTERM/SIGINT handler requests an emergency snapshot at the next loop
  boundary; the loop saves, journals a fsync'd ``preempted`` event and exits
  with :data:`~sheeprl_tpu.resilience.preemption.PREEMPTED_EXIT_CODE` (75,
  EX_TEMPFAIL) so a supervisor can tell "preempted, resume me" from a crash;
  ``diagnostics.resilience.inject_preempt_iter`` drills the chain through
  the real CLI;
* :mod:`~sheeprl_tpu.resilience.supervisor` — auto-restart supervisor
  (``tools/supervise.py`` / ``sheeprl-supervise``): wraps ``cli.run`` as a
  child process, restarts on non-clean exit with capped exponential backoff
  and a restart budget, resumes from the newest *verified* checkpoint, and
  journals ``restart`` events into ``<run dir>/supervisor.jsonl`` so
  ``tools/goodput_report.py`` measures time-to-recover on real kill/resume
  cycles.

ISSUE 14 (the robustness half of ROADMAP item 4) adds three more:

* :mod:`~sheeprl_tpu.resilience.isolation` — last-good param fencing for the
  decoupled topology (promotion gate + ``params_reject`` + the
  ``Telemetry/param_staleness`` gauge) and train-step quarantine & rollback
  (double-buffered last-good snapshot, journaled ``rollback``,
  ``retry_budget``-bounded);
* :mod:`~sheeprl_tpu.resilience.coordination` — coordinated multi-host
  snapshots: barrier + broadcast-agreed step + one manifest-grouped shard
  per rank; resume selection skips torn groups
  (``ckpt_skipped reason=incomplete_group``);
* :mod:`~sheeprl_tpu.resilience.chaos` — scripted multi-fault schedules
  (``diagnostics.resilience.chaos.schedule``) and the ``sheeprl-chaos`` /
  ``tools/chaos_drill.py`` drill asserting recovery invariants through the
  real CLI.

The :class:`~sheeprl_tpu.resilience.monitor.ResilienceMonitor` ties the
pillars to the facade (journal hooks, ``/metrics`` counters, config knobs
under ``diagnostics.resilience``).  See ``howto/resilience.md``.
"""

from __future__ import annotations

from sheeprl_tpu.resilience.async_writer import AsyncCheckpointWriter, host_snapshot
from sheeprl_tpu.resilience.chaos import ChaosMonitor, ChaosTrainerError
from sheeprl_tpu.resilience.coordination import (
    coordinated_save,
    group_status,
    rank_shard_path,
)
from sheeprl_tpu.resilience.isolation import IsolationHalt, IsolationMonitor
from sheeprl_tpu.resilience.manifest import (
    MANIFEST_SUFFIX,
    newest_verified_checkpoint,
    read_manifest,
    reap_orphan_tmps,
    resolve_resume_from,
    save_verified_checkpoint,
    verify_checkpoint,
    write_manifest,
)
from sheeprl_tpu.resilience.monitor import ResilienceMonitor
from sheeprl_tpu.resilience.preemption import PREEMPTED_EXIT_CODE, PreemptedExit, PreemptionGuard

__all__ = [
    "AsyncCheckpointWriter",
    "ChaosMonitor",
    "ChaosTrainerError",
    "IsolationHalt",
    "IsolationMonitor",
    "MANIFEST_SUFFIX",
    "PREEMPTED_EXIT_CODE",
    "PreemptedExit",
    "PreemptionGuard",
    "ResilienceMonitor",
    "coordinated_save",
    "group_status",
    "host_snapshot",
    "rank_shard_path",
    "newest_verified_checkpoint",
    "read_manifest",
    "reap_orphan_tmps",
    "resolve_resume_from",
    "save_verified_checkpoint",
    "verify_checkpoint",
    "write_manifest",
]
