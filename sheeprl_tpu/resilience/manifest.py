"""Validated checkpoints: manifest sidecars + verified resume selection.

Every checkpoint save writes a ``<ckpt>.manifest.json`` sidecar *after* the
checkpoint itself has atomically landed:

``{"format": 1, "step": 128, "bytes": N, "sha256": "...", "tree": {path:
[shape, dtype]}, "fingerprint": "<code fingerprint>", "written_t": ...}``

The sidecar is what makes "is this checkpoint complete and uncorrupted?"
answerable without unpickling it: a SIGKILL mid-write leaves only a
``*.ckpt.tmp`` (the tmp+rename in ``utils/checkpoint.py::save_state`` is
atomic), and external corruption/truncation fails the size/digest check.
Resume selection (:func:`newest_verified_checkpoint`) walks candidates
newest-first by step and returns the first one that verifies, collecting a
``(path, reason)`` skip record for every rejected sibling — the facade
journals those as ``ckpt_skipped`` events once the run journal opens.

Checkpoints written before this module existed carry no manifest; they are
"legacy": shallow verification accepts them (a non-empty file), deep
verification falls back to actually unpickling them.  The mtime-second-newest
resume heuristic this replaces is documented in the ISSUE-8 SIGKILL e2e.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Tuple

MANIFEST_SUFFIX = ".manifest.json"
MANIFEST_FORMAT = 1

_STEP_RE = re.compile(r"ckpt_(\d+)")

#: Journal events queued before the run journal exists (resume selection runs
#: at config-compose time); ``ResilienceMonitor.open`` drains them.
_PENDING_JOURNAL: List[Tuple[str, Dict[str, Any]]] = []


def queue_journal_event(kind: str, **fields: Any) -> None:
    _PENDING_JOURNAL.append((kind, dict(fields)))


def drain_journal_events() -> List[Tuple[str, Dict[str, Any]]]:
    out = list(_PENDING_JOURNAL)
    _PENDING_JOURNAL.clear()
    return out


def manifest_path(ckpt_path: str) -> str:
    return str(ckpt_path) + MANIFEST_SUFFIX


def checkpoint_step(ckpt_path: str, state: Optional[Mapping[str, Any]] = None) -> Optional[int]:
    """Policy step of a checkpoint: the ``ckpt_<step>_<rank>.ckpt`` filename
    convention first, state counters (``policy_step``/``iter_num``) second."""
    match = _STEP_RE.search(os.path.basename(str(ckpt_path)))
    if match:
        return int(match.group(1))
    if state is not None:
        for key in ("policy_step", "update", "iter_num"):
            value = state.get(key)
            if isinstance(value, (int, float)):
                return int(value)
    return None


def _file_digest(path: str, chunk_bytes: int = 1 << 20) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fp:
        while True:
            block = fp.read(chunk_bytes)
            if not block:
                break
            digest.update(block)
    return digest.hexdigest()


def tree_spec(state: Mapping[str, Any]) -> Dict[str, List[Any]]:
    """``{dotted-path: [shape, dtype]}`` for every array leaf of the state —
    the manifest's structural record, checked by serving/resume consumers
    that care about shape drift (verification itself uses the content
    digest; a spec mismatch is a *different* checkpoint, not a corrupt one)."""
    out: Dict[str, List[Any]] = {}

    def walk(node: Any, prefix: str) -> None:
        if isinstance(node, Mapping):
            for key, value in node.items():
                walk(value, f"{prefix}.{key}" if prefix else str(key))
            return
        if isinstance(node, (list, tuple)):
            for i, value in enumerate(node):
                walk(value, f"{prefix}[{i}]")
            return
        shape = getattr(node, "shape", None)
        dtype = getattr(node, "dtype", None)
        if shape is not None and dtype is not None:
            out[prefix] = [list(shape), str(dtype)]

    walk(state, "")
    return out


def _code_fingerprint() -> str:
    """Code-revision stamp reusing the AOT-cache fingerprint helper (PR 10):
    package version + git HEAD.  Informational — resuming across revisions is
    legitimate, so a mismatch is recorded, never fatal."""
    try:
        from sheeprl_tpu.diagnostics.telemetry import _code_fingerprint as fp

        return fp()
    except Exception:  # pragma: no cover - never block a save on this
        return "?"


def write_manifest(
    ckpt_path: str,
    state: Optional[Mapping[str, Any]] = None,
    step: Optional[int] = None,
    digest: Optional[Mapping[str, Any]] = None,
    group: Optional[Mapping[str, Any]] = None,
) -> Dict[str, Any]:
    """Write the sidecar for an already-landed checkpoint (atomic tmp+rename;
    a crash can only leave a checkpoint *without* a manifest — i.e. legacy,
    still resumable — never a manifest describing a half-written file).
    ``digest`` is the ``{"sha256", "bytes"}`` record ``save_state`` computed
    while streaming the pickle out; without it the file is re-read.
    ``group`` is the coordinated multi-host record
    (``{"world_size", "rank", "group_step"}`` — see
    ``resilience/coordination.py``); single-process saves pass None and the
    sidecar stays bit-identical to the pre-coordination format."""
    ckpt_path = str(ckpt_path)
    entry: Dict[str, Any] = {
        "format": MANIFEST_FORMAT,
        "step": step if step is not None else checkpoint_step(ckpt_path, state),
        "bytes": digest["bytes"] if digest else os.path.getsize(ckpt_path),
        "sha256": digest["sha256"] if digest else _file_digest(ckpt_path),
        "fingerprint": _code_fingerprint(),
        "written_t": round(time.time(), 3),
    }
    if state is not None:
        entry["tree"] = tree_spec(state)
    if group is not None:
        entry["group"] = dict(group)
    out_path = manifest_path(ckpt_path)
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(entry, fp)
        fp.flush()
        os.fsync(fp.fileno())
    os.replace(tmp, out_path)
    return entry


def read_manifest(ckpt_path: str) -> Optional[Dict[str, Any]]:
    """The sidecar dict, or None when absent/unparseable (both mean "treat the
    checkpoint as legacy" — verification then needs the pickle fallback)."""
    path = manifest_path(ckpt_path)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fp:
            entry = json.load(fp)
        return entry if isinstance(entry, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def verify_checkpoint(ckpt_path: str, deep: bool = True) -> Tuple[bool, str]:
    """``(ok, reason)`` for one checkpoint file.

    * manifest present — shallow checks existence + byte size (O(1), used by
      pruning), deep additionally re-digests the content (used by resume);
    * no/corrupt manifest (legacy) — shallow accepts any non-empty file, deep
      attempts the actual unpickle;
    * every failure mode is a *reason string*, never an exception.
    """
    ckpt_path = str(ckpt_path)
    if not os.path.isfile(ckpt_path):
        return False, "missing"
    size = os.path.getsize(ckpt_path)
    if size == 0:
        return False, "empty"
    entry = read_manifest(ckpt_path)
    if entry is None:
        if not deep:
            return True, "legacy"
        try:
            from sheeprl_tpu.utils.checkpoint import load_state

            load_state(ckpt_path)
            return True, "legacy"
        except Exception as err:
            return False, f"unreadable:{type(err).__name__}"
    if entry.get("bytes") != size:
        return False, "size_mismatch"
    if deep and entry.get("sha256") != _file_digest(ckpt_path):
        return False, "digest_mismatch"
    return True, "verified"


def save_verified_checkpoint(
    path: str, state: Mapping[str, Any], step: Optional[int] = None, group: Optional[Mapping[str, Any]] = None
) -> Dict[str, Any]:
    """Atomic checkpoint save + manifest sidecar; returns
    ``{path, step, bytes, write_ms}`` (the payload of a ``ckpt_end`` event).
    The content digest is computed while the pickle streams out — the
    checkpoint is never read back.  ``group`` threads the coordinated
    multi-host record into the sidecar (None for single-process saves)."""
    from sheeprl_tpu.utils.checkpoint import save_state

    t0 = time.perf_counter()
    digest = save_state(path, state, digest=True)
    entry = write_manifest(path, state=state, step=step, digest=digest, group=group)
    return {
        "path": str(path),
        "step": entry["step"],
        "bytes": entry["bytes"],
        "write_ms": round((time.perf_counter() - t0) * 1e3, 3),
    }


def _sort_key(path: Path) -> Tuple[int, float]:
    step = checkpoint_step(str(path))
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = 0.0
    return (step if step is not None else -1, mtime)


def list_checkpoints(root: str) -> List[str]:
    """All ``*.ckpt`` files under ``root`` (a file passes through), newest
    first — by parsed step, mtime breaking ties (mtime alone lies when a
    restore or copy touches files)."""
    p = Path(root)
    if p.is_file():
        return [str(p)]
    if not p.is_dir():
        return []
    return [str(c) for c in sorted(p.rglob("*.ckpt"), key=_sort_key, reverse=True)]


def newest_verified_checkpoint(
    root: str, deep: bool = True
) -> Tuple[Optional[str], List[Dict[str, str]]]:
    """The newest checkpoint under ``root`` that verifies, plus a skip record
    for every newer sibling that did not — the "never crash on a corrupt
    checkpoint" resume rule in one place.

    Coordinated multi-host snapshots add a group rule: a checkpoint whose
    manifest carries a ``group`` record is resumable only when EVERY
    participating rank's shard verifies with the same ``group_step`` — a
    torn group (one shard missing / corrupt / step-mismatched) is skipped
    with reason ``incomplete_group``.  Only the rank-0 shard of a group is
    ever returned (it is the canonical resume path; non-zero shards are
    selection-invisible, not corrupt, so they get no skip record)."""
    from sheeprl_tpu.resilience.coordination import group_status, shard_rank

    skipped: List[Dict[str, str]] = []
    for candidate in list_checkpoints(root):
        # manifest-only rank check FIRST: non-zero shards are selection-
        # invisible, so deep-hashing them just to discard would double the
        # resume scan's read cost on multi-host checkpoint dirs
        rank = shard_rank(candidate)
        if rank is not None and rank != 0:
            continue
        ok, reason = verify_checkpoint(candidate, deep=deep)
        if not ok:
            skipped.append({"path": candidate, "reason": reason})
            continue
        # the candidate itself was just verified — group_status only hashes
        # its SIBLING shards
        complete, group_reason = group_status(candidate, deep=deep, assume_verified=(0,))
        if not complete:
            skipped.append({"path": candidate, "reason": group_reason})
            continue
        return candidate, skipped
    return None, skipped


def reap_orphan_tmps(root: str, max_age_s: float = 0.0) -> List[str]:
    """Delete ``*.ckpt.tmp`` / manifest ``.tmp`` leftovers of interrupted
    writes under ``root``.  ``max_age_s`` guards against reaping a write that
    is legitimately in flight (pruning passes a generous age; resume passes 0
    — the previous process is definitionally dead)."""
    p = Path(root)
    if not p.is_dir():
        return []
    now = time.time()
    reaped: List[str] = []
    for pattern in ("*.ckpt.tmp", f"*{MANIFEST_SUFFIX}.tmp"):
        for tmp in p.rglob(pattern):
            try:
                if now - os.path.getmtime(tmp) < max_age_s:
                    continue
                tmp.unlink()
                reaped.append(str(tmp))
            except OSError:  # pragma: no cover - racing writer/reaper
                continue
    return reaped


def resolve_resume_from(spec: str) -> str:
    """Resolve ``checkpoint.resume_from`` to a verified checkpoint file.

    A directory (run dir, ``version_N`` dir, or checkpoint dir) selects the
    newest checkpoint whose manifest verifies, queueing a ``ckpt_skipped``
    journal event per rejected sibling; an explicit file must itself verify.
    Interrupted-write ``.tmp`` files never match the ``*.ckpt`` selection and
    are simply ignored — reaping them is ``keep_last`` pruning's (age-guarded)
    job, because the donor run may still be alive and mid-write (resuming
    *from* a live run's directory is a supported way to fork it).
    """
    path = Path(str(spec))
    # discard events queued by a previous resolution this process never
    # journaled (e.g. a diagnostics-off run): they describe the wrong resume
    _PENDING_JOURNAL.clear()
    if path.is_dir():
        best, skipped = newest_verified_checkpoint(str(path), deep=True)
        for skip in skipped:
            queue_journal_event("ckpt_skipped", **skip)
        if best is None:
            raise FileNotFoundError(
                f"No verifiable checkpoint under '{spec}' "
                f"({len(skipped)} candidate(s) rejected: "
                f"{[s['reason'] for s in skipped[:5]]})"
            )
        return best
    if not path.is_file():
        raise FileNotFoundError(f"Checkpoint '{spec}' does not exist")
    ok, reason = verify_checkpoint(str(path), deep=True)
    if not ok:
        raise ValueError(
            f"Checkpoint '{spec}' fails verification ({reason}); pass its run "
            "directory instead to resume from the newest verified checkpoint"
        )
    return str(path)
