"""Async off-critical-path checkpointing.

The train loop's cost is one :func:`host_snapshot` — a batched
``jax.device_get`` for device arrays plus a private copy of host-numpy leaves
(replay-buffer slabs are mutated in place by the very next vector step, and
the truncated-flag surgery in ``CheckpointCallback`` is *undone* right after
submit, so the snapshot must not alias caller memory) — and an enqueue.  A
single background thread serializes/fsyncs through the atomic tmp+rename in
``utils/checkpoint.py::save_state``, writes the manifest sidecar, and
journals ``ckpt_begin`` / ``ckpt_end`` (write duration, bytes, queued time)
so the goodput train spans no longer absorb checkpoint cost.

Double-buffering with backpressure: at most ``max_pending`` snapshots wait in
the queue; a loop that checkpoints faster than the disk can absorb blocks in
``submit`` instead of accumulating unbounded host copies.  A failed write
journals ``ckpt_end`` with ``status="failed"`` and warns — it never raises
into the training loop (the next periodic checkpoint is the retry).
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, Mapping, Optional

import numpy as np

#: Queue-entry sentinel marking a submitted callable (``submit_task``) rather
#: than a checkpoint snapshot.
_TASK = object()


def host_snapshot(tree: Any):
    """Cheap, self-owned host copy of a checkpoint state tree: numpy leaves
    are copied (they may alias live replay storage), device arrays ride ONE
    batched ``jax.device_get``, everything else (scalars, strings) passes
    through.  Containers are rebuilt by ``tree_map``, so later mutation of
    the caller's dicts/lists cannot reach the snapshot either."""
    import jax

    def copy_host(x: Any) -> Any:
        return x.copy() if isinstance(x, np.ndarray) else x

    copied = jax.tree_util.tree_map(copy_host, tree)
    return jax.device_get(copied)


class AsyncCheckpointWriter:
    """Background checkpoint writer behind ``ResilienceMonitor.save``.

    ``journal_fn(kind, **fields)`` may be None (direct/bench callers);
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        journal_fn: Optional[Callable[..., None]] = None,
        max_pending: int = 2,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self._journal_fn = journal_fn
        self.max_pending = max(1, int(max_pending))
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._writing = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None

        self.written_total = 0
        self.failed_total = 0
        self.write_seconds_total = 0.0
        self.last_write_ms: Optional[float] = None
        self.last_step: Optional[int] = None
        self.last_path: Optional[str] = None
        # wall-clock stamps feeding the ckpt age / cadence gauges
        self.last_end_t: Optional[float] = None
        self.last_interval_s: Optional[float] = None

    # -- producer side (the training loop) ----------------------------------
    def submit(
        self,
        path: str,
        state: Mapping[str, Any],
        step: Optional[int] = None,
        group: Optional[Mapping[str, Any]] = None,
        delay_s: Optional[float] = None,
    ) -> float:
        """Snapshot ``state`` to host and enqueue the write; returns the
        critical-path seconds the caller paid.  Blocks only when
        ``max_pending`` snapshots are already waiting (backpressure).
        ``group`` is the coordinated-snapshot manifest record; ``delay_s``
        is the chaos ``slow_write`` injection — the writer thread sleeps it
        before serializing, inflating write cost OFF the critical path."""
        t0 = self._clock()
        snapshot = host_snapshot(state)
        with self._cond:
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            while len(self._queue) >= self.max_pending and not self._closed:
                self._cond.wait(timeout=1.0)
            self._queue.append((str(path), snapshot, step, time.time(), group, delay_s))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._worker, name="sheeprl-ckpt-writer", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return self._clock() - t0

    def submit_task(self, fn: Callable[[], None]) -> None:
        """Enqueue an arbitrary off-critical-path task on the writer thread
        (dataset shard serialization rides here behind ``buffer.export`` —
        same FIFO as checkpoint writes, same backpressure, drained by
        ``drain``/``close`` so a preemption never abandons queued shards).
        A failing task warns and is dropped; it never raises into the loop."""
        with self._cond:
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            while len(self._queue) >= self.max_pending and not self._closed:
                self._cond.wait(timeout=1.0)
            self._queue.append((_TASK, fn, None, time.time(), None, None))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._worker, name="sheeprl-ckpt-writer", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()

    # -- consumer side (the writer thread) -----------------------------------
    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(timeout=1.0)
                if not self._queue:
                    return  # closed and drained
                path, snapshot, step, enqueued_t, group, delay_s = self._queue.popleft()
                self._writing = True
                self._cond.notify_all()
            try:
                if path is _TASK:
                    try:
                        snapshot()  # the submitted callable
                    except Exception as err:
                        warnings.warn(
                            f"async writer task failed: {err!r} (the run continues)",
                            RuntimeWarning,
                        )
                else:
                    self._write_one(path, snapshot, step, enqueued_t, group, delay_s)
            finally:
                with self._cond:
                    self._writing = False
                    self._cond.notify_all()

    def _write_one(
        self,
        path: str,
        snapshot: Any,
        step: Optional[int],
        enqueued_t: float,
        group: Optional[Mapping[str, Any]] = None,
        delay_s: Optional[float] = None,
    ) -> None:
        from sheeprl_tpu.resilience.manifest import checkpoint_step, save_verified_checkpoint

        if delay_s:
            time.sleep(delay_s)  # chaos slow_write: cost lands in write_ms/queued_s
        step = step if step is not None else checkpoint_step(path, snapshot)
        queued_s = round(max(0.0, time.time() - enqueued_t), 3)
        self._journal("ckpt_begin", path=path, step=step, blocking=False, queued_s=queued_s)
        try:
            # group threaded only when coordinated: the single-process call is
            # bit-identical to the pre-coordination one (and compatible with
            # test doubles carrying the original signature)
            kwargs = {"group": group} if group is not None else {}
            result = save_verified_checkpoint(path, snapshot, step=step, **kwargs)
        except Exception as err:
            # stats mutate under the condition's lock (stats() reads there);
            # the journal emission stays outside — fsync under a contended
            # lock would stall submit()/drain()
            with self._cond:
                self.failed_total += 1
            self._journal(
                "ckpt_end",
                path=path,
                step=step,
                blocking=False,
                status="failed",
                error=repr(err)[:200],
            )
            warnings.warn(
                f"async checkpoint write to '{path}' failed: {err!r} "
                "(the run continues; the next periodic checkpoint is the retry)",
                RuntimeWarning,
            )
            return
        now = time.time()
        with self._cond:
            if self.last_end_t is not None:
                self.last_interval_s = round(max(0.0, now - self.last_end_t), 3)
            self.last_end_t = now
            self.written_total += 1
            self.write_seconds_total += result["write_ms"] / 1e3
            self.last_write_ms = result["write_ms"]
            self.last_step = result["step"]
            self.last_path = result["path"]
        self._journal(
            "ckpt_end", blocking=False, status="ok", verified=True, queued_s=queued_s, **result
        )

    def _journal(self, kind: str, **fields: Any) -> None:
        if self._journal_fn is not None:
            self._journal_fn(kind, **fields)

    # -- lifecycle -----------------------------------------------------------
    @property
    def busy(self) -> bool:
        with self._cond:
            return bool(self._queue) or self._writing

    def drain(self, timeout: Optional[float] = 120.0) -> bool:
        """Block until every submitted snapshot is on disk (True) or the
        timeout passes (False) — the preemption path calls this so the
        emergency snapshot is durable before the process exits."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._queue or self._writing:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=min(1.0, remaining) if remaining is not None else 1.0)
        return True

    def close(self, timeout: Optional[float] = 120.0) -> None:
        self.drain(timeout=timeout)
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        # one consistent snapshot: the worker publishes all write stats in a
        # single locked block, so written_total/last_* never mix two writes
        with self._cond:
            return {
                "written_total": self.written_total,
                "failed_total": self.failed_total,
                "write_seconds_total": round(self.write_seconds_total, 3),
                "last_write_ms": self.last_write_ms,
                "last_step": self.last_step,
                "last_path": self.last_path,
                "last_end_t": self.last_end_t,
                "last_interval_s": self.last_interval_s,
            }
