"""Fault isolation for the decoupled topology: last-good param fencing +
train-step quarantine & rollback (ISSUE 14 — the robustness half of ROADMAP
item 4).

The actor/learner decoupling contract (IMPALA, Espeholt et al. 2018; SEED RL,
Espeholt et al. 2020) is that the *player* tolerates learner trouble: the
learner may stall, reject an update, or roll back while actors keep
generating experience on the last parameters known to be good.  Before this
module, ``ppo_decoupled``/``sac_decoupled`` handed every trainer update to
the player unconditionally — one NaN batch corrupted the acting policy and a
halting sentinel killed the whole run.  Two mechanisms, both configured by
``diagnostics.resilience.isolation``:

* **Promotion gate** (:meth:`IsolationMonitor.judge`) — the trainer→player
  params hop only happens when the update judges healthy.  The verdict
  consumes signals the loop ALREADY fetched for the health/sentinel layers
  (the in-graph nonfinite count and the ``health_stats`` norms ride the
  train step's one blocking ``fetch_values``), so fencing costs zero extra
  device syncs.  A rejection journals ``params_reject`` (reason, step,
  staleness) and the player keeps its last-good params; the
  ``Telemetry/param_staleness`` gauge counts iterations-behind.  When
  staleness exhausts ``max_staleness``, the monitor arms a *fence halt*: the
  loop forces its checkpoint branch (an emergency snapshot of the last-good
  state) and raises :class:`IsolationHalt`.

* **Quarantine & rollback** (:meth:`IsolationMonitor.rollback`) — every
  healthy promotion also refreshes an in-memory *last-good* host snapshot of
  the trainer's params + optimizer state (double-buffered: the refresh
  lands in the spare slot and swaps, so an interrupt mid-refresh can never
  tear the restore source — same discipline as the async writer's
  snapshot).  When the sentinel's ``halt`` policy trips, or ``chaos``
  injects a trainer exception, the loop restores from that snapshot,
  journals ``rollback`` (fsync'd), and keeps going — ``retry_budget``
  bounds the incidents; the budget-exhausting failure re-raises and the run
  dies the old way, now with N survivable incidents behind it.

Single-process / coupled loops never call the hooks, so default-on costs
them nothing.  See ``howto/resilience.md``.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, Mapping, Optional, Sequence

from sheeprl_tpu.diagnostics.sentinel import SentinelHalt


class IsolationHalt(SentinelHalt):
    """Raised when the param-staleness budget is exhausted (after the
    emergency snapshot landed): a :class:`SentinelHalt` subclass so the CLI
    closes the run with status ``halted`` exactly like a sentinel halt."""


class IsolationMonitor:
    """Promotion gate + last-good snapshot behind ``ResilienceMonitor``.

    Configured by ``diagnostics.resilience.isolation``:

    * ``enabled`` — arm the gate/rollback hooks (decoupled loops only);
    * ``max_staleness`` — consecutive rejected promotions the player may act
      through before the fence escalates to emergency-snapshot + halt;
    * ``retry_budget`` — rollbacks allowed before a quarantined train-step
      failure re-raises;
    * ``reject_on_anomaly`` — also fence promotions while a learning-health
      detector has an open anomaly (the "open sentinel anomaly" signal).
    """

    def __init__(self, cfg: Optional[Mapping[str, Any]], clock: Callable[[], float] = time.time):
        cfg = cfg or {}
        iso_cfg = ((cfg.get("diagnostics") or {}).get("resilience") or {}).get("isolation") or {}
        self.enabled = bool(iso_cfg.get("enabled", True))
        raw_staleness = iso_cfg.get("max_staleness")
        self.max_staleness = 8 if raw_staleness is None else int(raw_staleness)
        if self.max_staleness < 1:
            raise ValueError(
                f"diagnostics.resilience.isolation.max_staleness must be >= 1, got {self.max_staleness}"
            )
        raw_budget = iso_cfg.get("retry_budget")
        self.retry_budget = 3 if raw_budget is None else int(raw_budget)
        if self.retry_budget < 0:
            raise ValueError(
                f"diagnostics.resilience.isolation.retry_budget must be >= 0, got {self.retry_budget}"
            )
        self.reject_on_anomaly = bool(iso_cfg.get("reject_on_anomaly", True))
        raw_refresh = iso_cfg.get("refresh_every")
        self.refresh_every = 1 if raw_refresh is None else int(raw_refresh)
        if self.refresh_every < 1:
            raise ValueError(
                f"diagnostics.resilience.isolation.refresh_every must be >= 1, got {self.refresh_every}"
            )

        self._clock = clock
        self._journal_fn: Optional[Callable[..., None]] = None
        self._sync_fn: Optional[Callable[[], None]] = None
        self._opened = False
        # gate state
        self._gate_used = False
        self.staleness = 0
        self._rejected_total = 0
        self._halt_due = False
        # last-good snapshot: double-buffered (refresh fills the spare slot,
        # then one reference assignment promotes it — never a torn current)
        self._slots: list = [None, None]
        self._current: Optional[int] = None
        # rollback bookkeeping
        self._rollbacks_total = 0
        self._retries_left = self.retry_budget
        self._healthy_promotions = 0

    # -- lifecycle -----------------------------------------------------------
    def open(
        self,
        journal_fn: Optional[Callable[..., None]] = None,
        sync_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        if self._opened:
            return
        self._journal_fn = journal_fn
        self._sync_fn = sync_fn
        self._opened = True

    def _journal(self, event: str, **fields: Any) -> None:
        if self._journal_fn is not None:
            self._journal_fn(event, **fields)

    # -- promotion gate ------------------------------------------------------
    @staticmethod
    def _nonfinite_stat(stats: Mapping[str, Any]) -> Optional[str]:
        """First non-finite entry among the global health norms, or None.
        Only the global scalars are judged — per-module detail can carry a
        legitimately-zero dead module without vetoing the whole update."""
        for key in ("grad_norm", "update_norm", "param_norm"):
            value = stats.get(key)
            if value is None:
                continue
            try:
                if not math.isfinite(float(value)):
                    return key
            except (TypeError, ValueError):
                continue
        return None

    def judge(
        self,
        iter_num: int,
        step: Optional[int],
        stats: Mapping[str, Any],
        nonfinite: float = 0.0,
        anomalies: Sequence[str] = (),
    ) -> bool:
        """One promotion verdict: True = hand the params to the player.

        Reject reasons, in precedence order: ``nonfinite_update`` (the
        in-graph sentinel flag counted > 0 optimizer steps non-finite),
        ``nonfinite:<stat>`` (a fetched health norm is NaN/Inf), and
        ``open_anomaly:<kinds>`` (a learning-health detector is active and
        ``reject_on_anomaly`` is set).  A rejection journals
        ``params_reject`` and bumps the staleness counter; exhausting
        ``max_staleness`` arms the fence halt (fsync'd, one-shot).
        """
        if not self._opened or not self.enabled:
            return True
        self._gate_used = True
        reason = None
        if nonfinite and float(nonfinite) > 0:
            reason = "nonfinite_update"
        if reason is None:
            bad = self._nonfinite_stat(stats or {})
            if bad is not None:
                reason = f"nonfinite:{bad}"
        if reason is None and self.reject_on_anomaly and anomalies:
            reason = "open_anomaly:" + ",".join(sorted(anomalies)[:4])
        if reason is None:
            self.staleness = 0
            return True
        self.staleness += 1
        self._rejected_total += 1
        # only NON-FINITE rejections may escalate to the fatal fence halt: an
        # open learning-health anomaly is an advisory signal — it fences the
        # player (staleness climbs, the banner fires) but a warn-level
        # detector must never terminate a run that is updating finitely
        escalate = (
            self.staleness > self.max_staleness
            and not self._halt_due
            and not reason.startswith("open_anomaly")
        )
        self._journal(
            "params_reject",
            reason=reason,
            step=step,
            iter_num=int(iter_num),
            staleness=self.staleness,
            budget=self.max_staleness,
            escalate=escalate,
        )
        if escalate:
            self._halt_due = True
            if self._sync_fn is not None:
                # the escalation record must survive the halt that follows it
                self._sync_fn()
        return False

    @property
    def halt_due(self) -> bool:
        """True once staleness exhausted the budget: the loop forces its
        checkpoint branch (emergency snapshot) and raises through
        ``Diagnostics.on_fence_halt``."""
        return self._halt_due

    # -- last-good snapshot --------------------------------------------------
    def refresh(self, iter_num: int, params: Any, opt_state: Any) -> None:
        """Refresh the last-good host snapshot after a healthy promotion
        (one batched device→host fetch; self-owned copies, so later in-place
        donation/mutation of the live trees cannot reach it).

        The fetch is the one real cost of the layer — the full params +
        optimizer state cross to the host — so ``refresh_every`` (default 1)
        amortizes it: only every Nth healthy promotion snapshots, trading a
        rollback target up to N-1 updates staler (by design already
        tolerated — the player tolerates ``max_staleness`` of it).  The
        FIRST healthy promotion always snapshots, so rollback is armed as
        early as possible."""
        if not self._opened or not self.enabled:
            return
        self._healthy_promotions += 1
        if self._current is not None and (self._healthy_promotions - 1) % self.refresh_every != 0:
            return
        from sheeprl_tpu.resilience.async_writer import host_snapshot

        spare = 1 - (self._current if self._current is not None else 1)
        self._slots[spare] = {
            "params": host_snapshot(params),
            "opt_state": host_snapshot(opt_state),
            "iter_num": int(iter_num),
        }
        self._current = spare

    @property
    def last_good(self) -> Optional[Dict[str, Any]]:
        return self._slots[self._current] if self._current is not None else None

    def can_absorb(self) -> bool:
        """True while a quarantined failure could be rolled back: the layer
        is armed, a last-good snapshot exists and retries remain.  Consulted
        by ``Diagnostics.on_update`` so a halt the loop is about to absorb
        does not close the facade under it."""
        return (
            self._opened
            and self.enabled
            and self._current is not None
            and self._retries_left > 0
            and not self._halt_due
        )

    def rollback(self, err: BaseException, iter_num: int, step: Optional[int]) -> Optional[Dict[str, Any]]:
        """Consume one retry and return the last-good ``{params, opt_state,
        iter_num}`` snapshot (the caller device-puts it back onto the
        trainer mesh), or None when nothing can be restored — no snapshot
        yet, layer off, or the retry budget is spent — in which case the
        caller re-raises and the run dies the pre-isolation way."""
        if not self.can_absorb():
            return None
        self._retries_left -= 1
        self._rollbacks_total += 1
        restored = self.last_good
        self._journal(
            "rollback",
            iter_num=int(iter_num),
            step=step,
            error=repr(err)[:200],
            restored_iter=restored["iter_num"],
            retries_left=self._retries_left,
            budget=self.retry_budget,
        )
        if self._sync_fn is not None:
            # an incident record that must survive the next failure killing us
            self._sync_fn()
        return restored

    # -- observability -------------------------------------------------------
    def interval_metrics(self) -> Dict[str, float]:
        """The staleness gauge, merged into every metric interval once the
        gate has been consulted (coupled runs never grow the key)."""
        if not self._gate_used:
            return {}
        return {"Telemetry/param_staleness": float(self.staleness)}

    def gauges(self) -> Dict[str, float]:
        if not self._gate_used:
            return {}
        return {
            "Telemetry/param_staleness": float(self.staleness),
            "Telemetry/param_staleness_budget": float(self.max_staleness),
        }

    def counters(self) -> Dict[str, Any]:
        return {
            "params_rejected_total": self._rejected_total,
            "rollbacks_total": self._rollbacks_total,
        }

    def summary(self) -> Dict[str, Any]:
        return {
            "params_rejected": self._rejected_total,
            "rollbacks": self._rollbacks_total,
            "rollback_retries_left": self._retries_left,
        }
