"""Chaos harness: scripted fault schedules + the ``sheeprl-chaos`` drill.

The resilience subsystem's fault injections grew one knob at a time
(``inject_nan_iter``, ``inject_preempt_iter``, ``inject_stall_iter``, ...),
each drilling ONE failure in isolation.  Production preemptible pools deliver
*schedules* of faults; this module scripts them:

``diagnostics.resilience.chaos.schedule`` is a list of
``{iter: N, fault: <name>}`` entries (one-shot each):

* ``nan_grads`` — poison every float leaf of the train batch at loop
  iteration N (the sentinel/fencing path end-to-end: ``params_reject`` →
  ``rollback`` under ``sentinel.policy=halt``);
* ``trainer_exception`` — raise :class:`ChaosTrainerError` at the train
  dispatch boundary (the quarantine path without NaNs);
* ``slow_write`` — the next checkpoint write sleeps
  ``chaos.slow_write_s`` inside the (async) writer: drills write-cost
  accounting and the ``!! NO-RECENT-CKPT`` freshness banner without
  touching the critical path;
* ``preempt`` — behave as if a preemption signal arrived (same chain as
  ``inject_preempt_iter``: emergency snapshot → ``preempted`` → exit 75).

Every firing journals ``fault_injection`` with ``kind=<fault>`` and
``source=chaos``.  ``tools/chaos_drill.py`` / ``sheeprl-chaos`` runs a
schedule through the REAL CLI in a subprocess and asserts the recovery
invariants (run survives, the journal carries the expected event chain, the
final checkpoint manifest verifies) — the executable form of the recovery
contract in ``howto/resilience.md``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

#: The fault vocabulary a schedule entry may name.
FAULTS = ("nan_grads", "trainer_exception", "slow_write", "preempt")


class ChaosTrainerError(RuntimeError):
    """Injected trainer failure (fault ``trainer_exception``): raised at the
    train dispatch boundary so the loop's quarantine path absorbs it exactly
    like a real mid-dispatch blowup."""


def parse_schedule(schedule: Any) -> List[Dict[str, Any]]:
    """Validate a chaos schedule (list of ``{iter, fault}`` mappings) into
    normalized entries; raises ``ValueError`` with the offending entry."""
    if schedule in (None, ""):
        return []
    if not isinstance(schedule, Sequence) or isinstance(schedule, (str, bytes)):
        raise ValueError(
            f"diagnostics.resilience.chaos.schedule must be a list of "
            f"{{iter: N, fault: name}} entries, got {schedule!r}"
        )
    out: List[Dict[str, Any]] = []
    for entry in schedule:
        if not isinstance(entry, Mapping):
            raise ValueError(f"chaos schedule entry must be a mapping, got {entry!r}")
        fault = entry.get("fault")
        if fault not in FAULTS:
            raise ValueError(
                f"chaos schedule entry names unknown fault {fault!r}; valid: {list(FAULTS)}"
            )
        raw_iter = entry.get("iter")
        if raw_iter is None or int(raw_iter) < 1:
            raise ValueError(
                f"chaos schedule entry needs iter >= 1 (1 = first loop iteration), got {entry!r}"
            )
        out.append({"iter": int(raw_iter), "fault": str(fault), "fired": False})
    return out


class ChaosMonitor:
    """Schedule executor behind ``ResilienceMonitor``: one-shot fault firings
    keyed by loop iteration, each journaled as ``fault_injection``."""

    def __init__(self, cfg: Optional[Mapping[str, Any]]):
        cfg = cfg or {}
        chaos_cfg = ((cfg.get("diagnostics") or {}).get("resilience") or {}).get("chaos") or {}
        self.schedule = parse_schedule(chaos_cfg.get("schedule"))
        raw_slow = chaos_cfg.get("slow_write_s")
        self.slow_write_s = 2.0 if raw_slow is None else float(raw_slow)
        if self.slow_write_s <= 0:
            raise ValueError(
                f"diagnostics.resilience.chaos.slow_write_s must be > 0, got {self.slow_write_s}"
            )
        self.enabled = bool(self.schedule)
        self._journal_fn: Optional[Callable[..., None]] = None
        self._opened = False

    def open(self, journal_fn: Optional[Callable[..., None]] = None) -> None:
        if self._opened:
            return
        self._journal_fn = journal_fn
        self._opened = True

    def take(self, iter_num: int, fault: str) -> bool:
        """True when an unfired schedule entry matches ``(iter_num, fault)``
        — marks it fired and journals the injection."""
        if not self._opened or not self.enabled:
            return False
        for entry in self.schedule:
            if entry["fired"] or entry["fault"] != fault or entry["iter"] != int(iter_num):
                continue
            entry["fired"] = True
            if self._journal_fn is not None:
                self._journal_fn(
                    "fault_injection", iter_num=int(iter_num), kind=fault, source="chaos"
                )
            return True
        return False


# ---------------------------------------------------------------------------
# the drill CLI (tools/chaos_drill.py / sheeprl-chaos)
# ---------------------------------------------------------------------------

#: Out-of-the-box drill workload: a tiny decoupled PPO run (1 player + 1
#: trainer) on the dummy env — the topology the fencing/rollback contract is
#: about.  Callers targeting real hardware pass their own overrides after
#: ``--``.
DEFAULT_OVERRIDES = [
    "exp=ppo_decoupled",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=2",
    "env.capture_video=False",
    "buffer.memmap=False",
    "metric.log_level=1",
    "metric.log_every=1",
    "fabric.devices=2",
    "fabric.accelerator=cpu",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.run_test=False",
    "algo.total_steps=96",
    "checkpoint.every=16",
    "checkpoint.save_last=True",
]

#: Per-fault recovery invariants: (expected exit codes, journal event kinds
#: that must appear IN ORDER after the injection).
_EXPECTED = {
    "nan_grads": ((0,), ("fault_injection", "params_reject", "rollback", "run_end")),
    "trainer_exception": ((0,), ("fault_injection", "rollback", "run_end")),
    "slow_write": ((0,), ("fault_injection", "ckpt_end", "run_end")),
    "preempt": ((75,), ("fault_injection", "preempted", "run_end")),
}


def _ordered_subsequence(kinds: Sequence[str], expected: Sequence[str]) -> bool:
    it = iter(kinds)
    return all(kind in it for kind in expected)


def run_drill(
    schedule: List[Dict[str, Any]],
    overrides: Sequence[str],
    run_dir_root: str = "logs/runs",
    timeout_s: float = 600.0,
) -> Dict[str, Any]:
    """Run one scripted schedule through the real CLI (subprocess) and check
    the recovery invariants; returns the machine-readable verdict."""
    from sheeprl_tpu.diagnostics.journal import find_journal, read_journal
    from sheeprl_tpu.resilience.manifest import newest_verified_checkpoint

    faults = [e["fault"] for e in schedule]
    schedule_yaml = "[" + ",".join(f"{{iter: {e['iter']}, fault: {e['fault']}}}" for e in schedule) + "]"
    run_name = "chaos_drill"
    args = list(overrides) + [
        f"run_name={run_name}",
        f"diagnostics.resilience.chaos.schedule={schedule_yaml}",
        # the rollback chain needs the halting sentinel armed; harmless for
        # the other faults (the drill IS the halt-policy recovery proof)
        "diagnostics.sentinel.enabled=True",
        "diagnostics.sentinel.policy=halt",
    ]
    from sheeprl_tpu.utils.utils import subprocess_cli_env

    # the default decoupled workload needs >= 2 (virtual) devices; the shared
    # helper replaces any inherited device-count pin and makes the checkout
    # importable from the drill's cwd
    env = subprocess_cli_env(device_count=2)
    proc = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu", *args],
        env=env,
        timeout=timeout_s,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )

    verdict: Dict[str, Any] = {
        "schedule": [{k: e[k] for k in ("iter", "fault")} for e in schedule],
        "exit_code": proc.returncode,
        "checks": {},
        "ok": True,
    }

    def check(name: str, ok: bool, detail: Any = None) -> None:
        verdict["checks"][name] = {"ok": bool(ok), **({"detail": detail} if detail is not None else {})}
        verdict["ok"] = verdict["ok"] and bool(ok)

    expected_codes = tuple({c for f in faults for c in _EXPECTED[f][0]}) or (0,)
    check("exit_code", proc.returncode in expected_codes, {"got": proc.returncode, "want": list(expected_codes)})

    # the run dir is derived from the composed root_dir/run_name; search for
    # the journal under the conventional layout.  Newest-mtime wins: a second
    # drill in the same logs tree must judge ITS run, not a stale version_N
    candidates = []
    for root, _dirs, files in os.walk(run_dir_root):
        if "journal.jsonl" in files and f"/{run_name}/" in (root + "/"):
            found = find_journal(root)
            if found is not None:
                candidates.append(found)
    journal_path = max(candidates, key=os.path.getmtime, default=None)
    if journal_path is None:
        check("journal", False, f"no journal.jsonl for run_name={run_name} under {run_dir_root}")
        return verdict
    events = read_journal(journal_path)
    kinds = [e.get("event") for e in events]
    verdict["journal"] = journal_path

    for fault in faults:
        chain = _EXPECTED[fault][1]
        check(f"chain:{fault}", _ordered_subsequence(kinds, chain), {"want_in_order": list(chain)})
    if "nan_grads" in faults and proc.returncode == 0:
        # after the rollback, promotions must be healthy again: the run's
        # LAST metric interval carries staleness 0 (gauge present => gate ran)
        last_metrics = next(
            (e.get("metrics") or {} for e in reversed(events) if e.get("event") == "metrics"), {}
        )
        staleness = last_metrics.get("Telemetry/param_staleness")
        check("healthy_promotions", staleness == 0, {"final_param_staleness": staleness})
    run_end = next((e for e in reversed(events) if e.get("event") == "run_end"), None)
    want_status = "preempted" if "preempt" in faults else "completed"
    check("run_end", run_end is not None and run_end.get("status") == want_status, run_end)

    best, skipped = newest_verified_checkpoint(os.path.dirname(journal_path))
    check("final_checkpoint_verifies", best is not None, {"checkpoint": best, "skipped": skipped})
    return verdict


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``sheeprl-chaos``: run a scripted fault schedule through the real CLI
    and assert the recovery invariants.

    Usage::

        sheeprl-chaos --drill nan_grads [--iter 2]
        sheeprl-chaos --schedule '[{iter: 2, fault: nan_grads}, {iter: 4, fault: slow_write}]'
        sheeprl-chaos --drill trainer_exception -- exp=ppo_decoupled env=dummy ...

    Without explicit overrides after ``--`` a tiny 1-player+1-trainer
    decoupled PPO run on the dummy env is used (CPU, ~a minute).  Exit 0 =
    every invariant held; 1 = a recovery invariant failed.
    """
    import argparse

    import yaml

    argv = list(sys.argv[1:] if argv is None else argv)
    overrides: List[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, overrides = argv[:split], argv[split + 1 :]
    parser = argparse.ArgumentParser(
        prog="sheeprl-chaos", description=main.__doc__.splitlines()[0]
    )
    parser.add_argument("--drill", choices=FAULTS, help="single-fault shorthand")
    parser.add_argument("--iter", type=int, default=2, help="iteration for --drill (default 2)")
    parser.add_argument("--schedule", help="YAML list of {iter: N, fault: name} entries")
    parser.add_argument("--timeout", type=float, default=600.0, help="drill wall-clock budget (s)")
    args = parser.parse_args(argv)

    if bool(args.drill) == bool(args.schedule):
        parser.error("pass exactly one of --drill or --schedule")
    raw = [{"iter": args.iter, "fault": args.drill}] if args.drill else yaml.safe_load(args.schedule)
    schedule = parse_schedule(raw)
    if not schedule:
        parser.error("empty chaos schedule")

    verdict = run_drill(schedule, overrides or DEFAULT_OVERRIDES, timeout_s=args.timeout)
    print(json.dumps(verdict), flush=True)
    for name, result in verdict["checks"].items():
        mark = "ok " if result["ok"] else "FAIL"
        detail = result.get("detail")
        print(f"  [{mark}] {name}" + (f" — {detail}" if detail is not None and not result["ok"] else ""))
    print("chaos drill: " + ("PASSED" if verdict["ok"] else "FAILED"))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":  # pragma: no cover - exercised via tools/chaos_drill.py
    sys.exit(main())
