"""The resilience pillar behind the ``Diagnostics`` facade.

Owns the async checkpoint writer, the preemption guard and the checkpoint
bookkeeping the ``/metrics`` endpoint and ``tools/run_monitor.py`` surface
(``sheeprl_ckpt_*`` gauges/counters, ``sheeprl_restarts_total`` from the
supervisor's hand-off env var).  Configured by ``diagnostics.resilience``:

* ``async_checkpoint`` — route ``Runtime.save`` through the background
  writer (one host snapshot on the critical path, serialize/fsync off it);
* ``max_pending_snapshots`` — double-buffer depth / backpressure bound;
* ``preempt.enabled`` — install the SIGTERM/SIGINT graceful-preemption guard;
* ``inject_preempt_iter`` — fault injection: behave as if a preemption signal
  arrived at the Nth loop iteration (1 = first), drilling the emergency-
  snapshot → ``preempted`` → exit-75 chain through the real CLI;
* ``isolation.*`` — last-good param fencing + quarantine/rollback for the
  decoupled topology (:mod:`~sheeprl_tpu.resilience.isolation`);
* ``chaos.*`` — scripted multi-fault schedules and the ``sheeprl-chaos``
  drill (:mod:`~sheeprl_tpu.resilience.chaos`).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Mapping, Optional

from sheeprl_tpu.resilience.async_writer import AsyncCheckpointWriter
from sheeprl_tpu.resilience.chaos import ChaosMonitor
from sheeprl_tpu.resilience.isolation import IsolationMonitor
from sheeprl_tpu.resilience.preemption import PreemptionGuard

#: Set by the supervisor on every child it (re)spawns; exported as the
#: ``sheeprl_restarts_total`` counter so a scrape of the training process
#: shows how many kill/resume cycles this run has survived.
RESTARTS_ENV_VAR = "SHEEPRL_SUPERVISOR_RESTARTS"


class ResilienceMonitor:
    """Rank-0-journaling, every-rank-preemptible elasticity monitor."""

    def __init__(self, cfg: Optional[Mapping[str, Any]], clock: Callable[[], float] = time.time):
        cfg = cfg or {}
        diag_cfg = cfg.get("diagnostics") or {}
        res_cfg = diag_cfg.get("resilience") or {}
        self.enabled = bool(res_cfg.get("enabled", True))
        self.async_checkpoint = bool(res_cfg.get("async_checkpoint", True))
        raw_pending = res_cfg.get("max_pending_snapshots")
        max_pending = 2 if raw_pending is None else int(raw_pending)
        if max_pending < 1:
            raise ValueError(
                f"diagnostics.resilience.max_pending_snapshots must be >= 1, got {max_pending}"
            )
        self.max_pending = max_pending
        preempt_cfg = res_cfg.get("preempt") or {}
        self.preempt_signals = bool(preempt_cfg.get("enabled", True))
        inject = res_cfg.get("inject_preempt_iter")
        self.inject_preempt_iter = None if inject is None else int(inject)
        # fault-isolation pillar (decoupled loops' fencing/rollback hooks) and
        # the chaos schedule executor — both None when disabled, so every
        # consumer is a cheap attribute check
        isolation = IsolationMonitor(cfg)
        self.isolation: Optional[IsolationMonitor] = isolation if isolation.enabled else None
        chaos = ChaosMonitor(cfg)
        self.chaos: Optional[ChaosMonitor] = chaos if chaos.enabled else None
        self._chaos_preempt = False
        self._slow_write_pending: Optional[float] = None
        self._chaos_slow_write_s = chaos.slow_write_s

        self._clock = clock
        self._journal_fn: Optional[Callable[..., None]] = None
        self._sync_fn: Optional[Callable[[], None]] = None
        self._writer: Optional[AsyncCheckpointWriter] = None
        self._writer_final_stats: Optional[Dict[str, Any]] = None
        self._guard: Optional[PreemptionGuard] = None
        self._opened = False
        self._rank_zero = True
        self._inject_fired = False
        self._preempt_reason: Optional[str] = None
        self._restarts_total = 0
        # blocking-save bookkeeping (the writer tracks its own async stats)
        self._sync_written = 0
        self._sync_failed = 0
        self._sync_write_seconds = 0.0
        self._last_end_t: Optional[float] = None
        self._last_interval_s: Optional[float] = None
        self._last_step: Optional[int] = None
        self._last_path: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------
    def open(
        self,
        journal_fn: Optional[Callable[..., None]] = None,
        sync_fn: Optional[Callable[[], None]] = None,
        rank_zero: bool = True,
    ) -> None:
        if self._opened:
            return
        self._journal_fn = journal_fn
        self._sync_fn = sync_fn
        self._rank_zero = bool(rank_zero)
        self._opened = True
        try:
            self._restarts_total = int(os.environ.get(RESTARTS_ENV_VAR, "0") or 0)
        except ValueError:  # pragma: no cover - malformed env
            self._restarts_total = 0
        # resume selection ran before the journal existed: land its skip
        # records now, so a planted-corrupt-checkpoint resume is observable
        from sheeprl_tpu.resilience.manifest import drain_journal_events

        for kind, fields in drain_journal_events():
            self._journal(kind, **fields)
        if self.isolation is not None:
            self.isolation.open(self._journal, self._sync)
        if self.chaos is not None:
            self.chaos.open(self._journal)
        if self._rank_zero and self.async_checkpoint:
            self._writer = AsyncCheckpointWriter(
                journal_fn=self._journal, max_pending=self.max_pending
            )
        if self.preempt_signals:
            # every rank: each process of a decoupled topology must
            # snapshot-and-exit on its own signal (journaling stays rank-0)
            self._guard = PreemptionGuard()
            self._guard.install()

    def close(self) -> None:
        if not self._opened:
            return
        if self._writer is not None:
            # pending (possibly emergency) snapshots must land — and journal
            # their ckpt_end — before the caller writes run_end
            self._writer.close()
            self._writer_final_stats = self._writer.stats()
            self._writer = None
        if self._guard is not None:
            self._guard.uninstall()
            self._guard = None
        self._opened = False

    def _journal(self, event: str, **fields: Any) -> None:
        # first param deliberately not named `kind`: fault_injection events
        # carry a `kind=` field (matching the sentinel/telemetry drills)
        if self._journal_fn is not None:
            self._journal_fn(event, **fields)

    def _sync(self) -> None:
        if self._sync_fn is not None:
            self._sync_fn()

    # -- checkpoint routing (Runtime.save on global rank 0) ------------------
    def save(self, path: str, state: Mapping[str, Any], group: Optional[Mapping[str, Any]] = None) -> None:
        from sheeprl_tpu.resilience.manifest import checkpoint_step, save_verified_checkpoint

        step = checkpoint_step(path, state)
        delay_s, self._slow_write_pending = self._slow_write_pending, None
        if self._writer is not None:
            self._writer.submit(path, state, step=step, group=group, delay_s=delay_s)
            return
        if delay_s:
            # chaos slow_write on the blocking path: the sleep IS on the
            # critical path here — exactly the cost async_checkpoint removes
            time.sleep(delay_s)
        self._journal("ckpt_begin", path=str(path), step=step, blocking=True, queued_s=0.0)
        try:
            kwargs = {"group": group} if group is not None else {}
            result = save_verified_checkpoint(path, state, step=step, **kwargs)
        except Exception as err:
            # mirror the async path's contract (ckpt_begin is never left
            # dangling, the failure counter moves), then re-raise: a blocking
            # save failure keeps its pre-resilience abort semantics
            self._sync_failed += 1
            self._journal(
                "ckpt_end",
                path=str(path),
                step=step,
                blocking=True,
                status="failed",
                error=repr(err)[:200],
            )
            raise
        now = self._clock()
        if self._last_end_t is not None:
            self._last_interval_s = round(max(0.0, now - self._last_end_t), 3)
        self._last_end_t = now
        self._last_step = result["step"]
        self._last_path = result["path"]
        self._sync_written += 1
        self._sync_write_seconds += result["write_ms"] / 1e3
        self._journal("ckpt_end", blocking=True, status="ok", verified=True, **result)

    def flush(self, timeout: Optional[float] = 120.0) -> bool:
        """Wait for every in-flight async write to hit disk."""
        return self._writer.drain(timeout=timeout) if self._writer is not None else True

    # -- preemption ----------------------------------------------------------
    def preempt_due(self, iter_num: int) -> bool:
        """True once a preemption (signal, injected, or chaos-scheduled) is
        pending — the loop then forces its checkpoint branch and calls
        ``Diagnostics.on_preempted``.  Doubles as the chaos layer's per-
        iteration tick (every loop already calls it right before the
        checkpoint branch): a scheduled ``slow_write`` is armed here so the
        very next save pays it."""
        if not self._opened:
            return False
        if self.chaos is not None and self.chaos.take(iter_num, "slow_write"):
            self._slow_write_pending = self._chaos_slow_write_s
        if self._guard is not None and self._guard.requested:
            self._preempt_reason = f"signal:{self._guard.signal_name}"
            return True
        if self.chaos is not None and self.chaos.take(iter_num, "preempt"):
            self._chaos_preempt = True
            self._preempt_reason = "chaos"
        if self._chaos_preempt:
            return True
        if self.inject_preempt_iter is not None and int(iter_num) == self.inject_preempt_iter:
            if not self._inject_fired:
                self._inject_fired = True
                self._preempt_reason = "injected"
                self._journal("fault_injection", iter_num=int(iter_num), kind="preempt")
            return True
        return False

    @property
    def preempt_reason(self) -> str:
        return self._preempt_reason or "preempt"

    # -- observability -------------------------------------------------------
    def _ckpt_state(self) -> Dict[str, Any]:
        """Latest-checkpoint view merged across the async writer and the
        blocking path (exactly one of them is active per run)."""
        stats = self._writer.stats() if self._writer is not None else self._writer_final_stats
        if stats is not None:
            return {
                "written": stats["written_total"],
                "failed": stats["failed_total"],
                "write_seconds": stats["write_seconds_total"],
                "last_step": stats["last_step"],
                "last_path": stats["last_path"],
                "last_end_t": stats["last_end_t"],
                "interval_s": stats["last_interval_s"],
            }
        return {
            "written": self._sync_written,
            "failed": self._sync_failed,
            "write_seconds": round(self._sync_write_seconds, 3),
            "last_step": self._last_step,
            "last_path": self._last_path,
            "last_end_t": self._last_end_t,
            "interval_s": self._last_interval_s,
        }

    def interval_metrics(self) -> Dict[str, float]:
        """Per-interval resilience gauges merged into the metric stream by
        the facade — currently the fencing staleness counter (present only
        once the decoupled promotion gate has run)."""
        if self.isolation is None:
            return {}
        return self.isolation.interval_metrics()

    def snapshot(self) -> Dict[str, Any]:
        state = self._ckpt_state()
        gauges: Dict[str, float] = {}
        if state["last_step"] is not None:
            gauges["Telemetry/ckpt_last_step"] = float(state["last_step"])
        if state["last_end_t"] is not None:
            gauges["Telemetry/ckpt_age_seconds"] = round(
                max(0.0, time.time() - state["last_end_t"]), 3
            )
        if state["interval_s"] is not None:
            gauges["Telemetry/ckpt_interval_seconds"] = state["interval_s"]
        counters = {
            "ckpts_written_total": state["written"],
            "ckpt_failures_total": state["failed"],
            "ckpt_write_seconds_total": state["write_seconds"],
            "restarts_total": self._restarts_total,
        }
        info = {"last_ckpt_path": state["last_path"]}
        if self.isolation is not None:
            gauges.update(self.isolation.gauges())
            counters.update(self.isolation.counters())
        return {"gauges": gauges, "counters": counters, "info": info}

    def summary(self) -> Dict[str, Any]:
        """Closing totals merged into the ``telemetry_summary`` event."""
        state = self._ckpt_state()
        out = {
            "ckpts_written": state["written"],
            "ckpt_failures": state["failed"],
            "ckpt_write_seconds": state["write_seconds"],
            "restarts": self._restarts_total,
        }
        if self.isolation is not None:
            out.update(self.isolation.summary())
        return out
