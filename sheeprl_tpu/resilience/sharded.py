"""Truly-sharded (partial) checkpoints for FSDP runs.

``resilience/coordination.py`` made the checkpoint a *group* artifact — one
``ckpt_<step>_<rank>.ckpt`` per rank with torn-group skipping — but every
shard still held the full replicated tree.  This module makes shards true
partials: shard ``k`` serializes only the leaf **slices** the ``model``-axis
shard ``k`` owns, so an XXL checkpoint's bytes scale down with
``fsdp_axis_size`` instead of multiplying by it.

Which leaves are sliced, and along which dimension, is decided by re-running
the deterministic FSDP partition rule (``parallel/fsdp.py::shard_axis``) on
each host leaf — the writer can never disagree with the train step about a
leaf's layout.  The layout is recorded in every shard's manifest group:

``{"world_size": axis_size, "rank": k, "group_step": step, "partial": true,
"layout": {dotted-path: {"shape", "dtype", "axis", "parts"}}}``

- shard 0 is the **canonical** file: the full nested state with each sliced
  leaf replaced by its rank-0 slice (un-sliced leaves ride whole), so resume
  selection, step parsing, and the doc'd tree-spec all keep working on it;
- shards 1..k-1 are flat ``{dotted-path: slice}`` dicts — pure payload.

Reassembly (:func:`load_sharded_checkpoint`) walks shard 0's structure and
concatenates the recorded slices back along their recorded axis, returning
the full host tree.  That tree is axis-size-agnostic: resuming under a
*different* ``fsdp_axis_size`` (or pure DP) just re-places it under the new
rule — resharding is free.

Group completeness reuses the coordination layer unchanged ("rank" here is
the model-axis shard index of a single-process run): a torn partial group is
skipped at resume with ``ckpt_skipped reason=incomplete_group``, and
group-aware ``keep_last`` pruning already deletes step groups atomically.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from sheeprl_tpu.resilience.coordination import rank_shard_path
from sheeprl_tpu.resilience.manifest import (
    checkpoint_step,
    read_manifest,
    save_verified_checkpoint,
)


def _walk(node: Any, prefix: str, leaf_fn):
    """Rebuild ``node`` with every array leaf passed through
    ``leaf_fn(dotted_path, leaf)`` — same path grammar as
    ``manifest.tree_spec`` (``a.b[0].c``), NamedTuples preserved."""
    if isinstance(node, Mapping):
        return {
            key: _walk(value, f"{prefix}.{key}" if prefix else str(key), leaf_fn)
            for key, value in node.items()
        }
    if isinstance(node, (list, tuple)):
        items = [_walk(value, f"{prefix}[{i}]", leaf_fn) for i, value in enumerate(node)]
        if isinstance(node, tuple):
            return type(node)(*items) if hasattr(node, "_fields") else tuple(items)
        return items
    return leaf_fn(prefix, node)


def partial_group_record(
    axis_size: int, rank: int, step: Optional[int], layout: Mapping[str, Any]
) -> Dict[str, Any]:
    return {
        "world_size": int(axis_size),
        "rank": int(rank),
        "group_step": step,
        "partial": True,
        "layout": dict(layout),
    }


def save_sharded_checkpoint(
    path: str,
    state: Mapping[str, Any],
    axis_size: int,
    min_shard_bytes: Optional[int] = None,
) -> Dict[str, Any]:
    """Write ``state`` as an ``axis_size``-way partial-shard group.

    Shards 1..k-1 land first, the canonical shard 0 last — a crash mid-group
    either leaves no selectable candidate (shard 0 missing) or a torn group
    the resume rule skips; it can never surface a half-group as resumable.
    Returns ``{path, step, shards, bytes, bytes_shard0}``.
    """
    from sheeprl_tpu.parallel.fsdp import shard_axis

    if axis_size <= 1:
        raise ValueError(f"sharded save needs axis_size > 1, got {axis_size}")
    path = str(path)
    step = checkpoint_step(path, state)
    layout: Dict[str, Dict[str, Any]] = {}
    partials: List[Dict[str, Any]] = [dict() for _ in range(axis_size - 1)]

    def slice_leaf(leaf_path: str, leaf: Any) -> Any:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return leaf
        axis = shard_axis(tuple(shape), dtype, axis_size, min_shard_bytes)
        if axis is None:
            return leaf
        arr = np.asarray(leaf)
        layout[leaf_path] = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "axis": int(axis),
            "parts": int(axis_size),
        }
        pieces = np.split(arr, axis_size, axis=axis)
        for rank in range(1, axis_size):
            partials[rank - 1][leaf_path] = pieces[rank]
        return pieces[0]

    shard0_state = _walk(state, "", slice_leaf)

    total = 0
    for rank in range(1, axis_size):
        group = partial_group_record(axis_size, rank, step, layout)
        result = save_verified_checkpoint(
            rank_shard_path(path, rank), partials[rank - 1], step=step, group=group
        )
        total += result["bytes"]
    group0 = partial_group_record(axis_size, 0, step, layout)
    result0 = save_verified_checkpoint(path, shard0_state, step=step, group=group0)
    total += result0["bytes"]
    return {
        "path": path,
        "step": step,
        "shards": axis_size,
        "bytes": total,
        "bytes_shard0": result0["bytes"],
    }


def partial_layout(ckpt_path: str) -> Optional[Dict[str, Any]]:
    """The partial-shard layout from a checkpoint's manifest group, or None
    when the checkpoint is not a partial shard."""
    entry = read_manifest(ckpt_path)
    group = (entry or {}).get("group")
    if not isinstance(group, Mapping) or not group.get("partial"):
        return None
    layout = group.get("layout")
    return dict(layout) if isinstance(layout, Mapping) else {}


def is_partial_checkpoint(ckpt_path: str) -> bool:
    return partial_layout(ckpt_path) is not None


def load_sharded_checkpoint(ckpt_path: str) -> Dict[str, Any]:
    """Reassemble a partial-shard group into the full host state tree.

    ``ckpt_path`` is the canonical shard 0.  The group is required to be
    complete (every sibling present with the same ``group_step`` — shallow
    check here; deep digest verification is resume selection's job); a torn
    group raises instead of returning a silently-truncated tree.
    """
    from sheeprl_tpu.resilience.coordination import group_status
    from sheeprl_tpu.utils.checkpoint import load_state

    ckpt_path = str(ckpt_path)
    entry = read_manifest(ckpt_path)
    group = (entry or {}).get("group") or {}
    layout = partial_layout(ckpt_path)
    if layout is None:
        raise ValueError(f"'{ckpt_path}' is not a partial-shard checkpoint")
    complete, reason = group_status(ckpt_path, deep=False)
    if not complete:
        raise ValueError(f"partial-shard group for '{ckpt_path}' is torn ({reason})")
    axis_size = int(group.get("world_size", 1) or 1)
    shard0 = load_state(ckpt_path)
    siblings = [load_state(rank_shard_path(ckpt_path, rank)) for rank in range(1, axis_size)]

    def join_leaf(leaf_path: str, leaf: Any) -> Any:
        record = layout.get(leaf_path)
        if record is None:
            return leaf
        pieces = [np.asarray(leaf)]
        for flat in siblings:
            if leaf_path not in flat:
                raise KeyError(f"shard is missing slice for '{leaf_path}'")
            pieces.append(np.asarray(flat[leaf_path]))
        full = np.concatenate(pieces, axis=int(record["axis"]))
        if list(full.shape) != list(record["shape"]):
            raise ValueError(
                f"reassembled '{leaf_path}' has shape {list(full.shape)}, "
                f"manifest records {record['shape']}"
            )
        return full

    return _walk(shard0, "", join_leaf)
