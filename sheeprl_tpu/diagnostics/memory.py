"""Memory & data-movement telemetry: the other half of TPU performance.

The telemetry layer (ISSUE 3) answers "is the run *fast*?" in compute terms —
MFU, recompiles, phase breakdown.  This module makes the *memory* side of the
same question observable, because on a TPU the second way a run dies or slows
down is invisible by default: HBM fills up until ``RESOURCE_EXHAUSTED``, a
stray host sync serializes the pipeline, a buffer you meant to donate gets a
second allocation, or a large array is silently replicated across every chip.
Four pillars, all journal-backed and surfaced on ``/metrics``:

* **HBM telemetry** — per-device ``memory_stats()`` (bytes in use, peak,
  largest allocation) sampled once per metric interval as the
  ``Telemetry/hbm_*`` gauges.  Backends without the API (CPU, some forced-host
  platforms) fall back to summing the bytes of all live ``jax.Array``s — a
  real measure of framework-held memory, journaled with its ``source`` so the
  two are never confused — plus the process RSS as ``Telemetry/host_rss_bytes``.
  A one-shot ``memory_breakdown`` event decomposes the static footprint:
  per-component tree bytes (params / optimizer state / replay buffers,
  registered by the training loops) and the compiled train step's own
  ``memory_analysis()`` (argument / output / activation-temp bytes) taken from
  the AOT executable the telemetry layer already builds — zero extra compiles.

* **Host-transfer guard** — ``diagnostics.transfers`` = ``off | log |
  disallow`` wraps every instrumented train/rollout dispatch in
  ``jax.transfer_guard``.  ``log`` makes the runtime print every implicit
  transfer (aval + destination sharding) to stderr; ``disallow`` turns one
  into an error, which is caught at the dispatch boundary, journaled as a
  ``host_transfer`` event with provenance (fn, dispatch index) and re-raised.
  ``diagnostics.memory.inject_transfer_iter`` drills the detector end-to-end:
  under ``log`` it forces a real device→host sync inside the guarded scope
  (journaled, exactly once); under ``disallow`` it forces an implicit
  host→device transfer the guard rejects on every backend.

* **Donation & sharding audit** — at the first train dispatch the declared
  ``donate_argnums`` buffers are verified to have actually been consumed
  (``is_deleted``): XLA silently keeps both copies when it cannot alias, which
  doubles the params+optimizer footprint.  Misses are journaled as
  ``donation_miss`` with the offending leaf paths.  The same first dispatch
  emits a ``sharding_audit`` event: a per-leaf bytes/sharding table of the
  dispatch arguments that flags large fully-replicated arrays on multi-device
  meshes (``tools/memory_report.py`` renders it).

* **OOM forensics** — ``RESOURCE_EXHAUSTED`` (or any allocator out-of-memory)
  escaping an instrumented dispatch is intercepted to journal an ``oom`` event
  carrying a final memory snapshot (device stats, component footprints,
  largest live arrays), fsync'd before the exception is re-raised — so the
  post-mortem survives even when the process is killed moments later.
  ``diagnostics.memory.inject_oom_iter`` simulates the failure for drills.

Everything here is rank-0-journal-backed, costs a few host-side counters per
dispatch plus one ``memory_stats``/``live_arrays`` walk per metric interval,
and rides the same ``Diagnostics`` facade / ``JournalingLogger`` proxy /
``/metrics`` endpoint as the rest of the diagnostics subsystem.
"""

from __future__ import annotations

import os
import threading
from contextlib import nullcontext
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

# journal event types this module emits (declared centrally in the schema
# registry; re-exported here for the existing import surface)
from sheeprl_tpu.diagnostics.schema import MEMORY_EVENTS  # noqa: E402

_TRANSFER_MODES = ("off", "log", "disallow")

# a replicated leaf at/above this many bytes on a >1-device mesh is flagged
# in the sharding audit (overridable: diagnostics.memory.replicated_warn_bytes)
DEFAULT_REPLICATED_WARN_BYTES = 16 * 1024 * 1024


def normalize_transfer_mode(value: Any) -> str:
    """``diagnostics.transfers`` arrives as a string from the CLI but YAML 1.1
    resolves bare ``off``/``on`` to booleans — accept both spellings."""
    if value is None or value is False:
        return "off"
    if value is True:
        return "log"
    mode = str(value).strip().lower()
    if mode in ("", "none", "null", "0", "false"):
        return "off"
    if mode not in _TRANSFER_MODES:
        raise ValueError(f"diagnostics.transfers must be one of {_TRANSFER_MODES}, got {value!r}")
    return mode


# ---------------------------------------------------------------------------
# byte accounting primitives


def _leaf_nbytes(leaf: Any) -> int:
    nbytes = getattr(leaf, "nbytes", None)
    if isinstance(nbytes, (int, float)):
        return int(nbytes)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        try:
            import numpy as np

            size = 1
            for dim in shape:
                size *= int(dim)
            return size * np.dtype(dtype).itemsize
        except Exception:
            return 0
    return 0


def tree_bytes(tree: Any) -> int:
    """Total bytes of every array leaf in a pytree (non-arrays contribute 0)."""
    import jax

    return sum(_leaf_nbytes(leaf) for leaf in jax.tree_util.tree_leaves(tree))


def tree_leaf_sizes(tree: Any) -> List[Tuple[str, Any]]:
    """``[(path, leaf), ...]`` over a pytree's array leaves, with readable
    key paths (the sharding/donation audits label their findings with these)."""
    import jax

    try:
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]
    except Exception:  # pragma: no cover - keystr availability
        return [(f"leaf[{i}]", leaf) for i, leaf in enumerate(jax.tree_util.tree_leaves(tree))]


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device ``memory_stats()`` where the backend provides it.

    Returns one dict per device with at least ``device``/``kind`` plus the
    backend's counters (TPU/GPU: ``bytes_in_use``, ``peak_bytes_in_use``,
    ``largest_alloc_size``...).  Backends without the API (CPU) return ``[]``
    — the caller falls back to live-array accounting, never to a guess.
    """
    import jax

    out: List[Dict[str, Any]] = []
    try:
        devices = jax.local_devices()
    except Exception:  # pragma: no cover - pre-init probes
        return out
    for dev in devices:
        stats_fn = getattr(dev, "memory_stats", None)
        if stats_fn is None:
            continue
        try:
            stats = stats_fn()
        except Exception:
            stats = None
        if not stats:
            continue
        row = {"device": str(dev.id), "kind": str(dev.device_kind)}
        row.update({str(k): v for k, v in stats.items()})
        out.append(row)
    return out


def live_array_bytes() -> Dict[str, Any]:
    """Framework-held memory from ``jax.live_arrays()``: total bytes, array
    count, and the largest single allocation.  This is the CPU-testable
    fallback for ``memory_stats()`` — it counts what *jax* holds (not raw
    allocator pages), which is exactly the number the training loop controls.
    """
    import jax

    total = 0
    largest = 0
    count = 0
    try:
        arrays = jax.live_arrays()
    except Exception:  # pragma: no cover - API drift
        return {"bytes_in_use": 0, "largest_alloc_bytes": 0, "n_arrays": 0}
    for arr in arrays:
        n = _leaf_nbytes(arr)
        total += n
        count += 1
        if n > largest:
            largest = n
    return {"bytes_in_use": total, "largest_alloc_bytes": largest, "n_arrays": count}


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process (Linux ``/proc/self/statm``), or None
    where unreadable — replay buffers in host RAM show up here."""
    try:
        with open("/proc/self/statm") as fp:
            pages = int(fp.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return None


def executable_memory_analysis(compiled: Any) -> Optional[Dict[str, int]]:
    """Byte breakdown of a compiled executable (``memory_analysis()``), or
    None where the backend/API doesn't provide one.  ``temp_bytes`` is the
    activation/scratch high-water mark — the part of the footprint no tree
    walk can see."""
    try:
        analysis = compiled.memory_analysis()
    except Exception:
        return None
    if analysis is None:
        return None
    fields = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
        "alias_bytes": "alias_size_in_bytes",
        "generated_code_bytes": "generated_code_size_in_bytes",
    }
    out: Dict[str, int] = {}
    for name, attr in fields.items():
        value = getattr(analysis, attr, None)
        if isinstance(value, (int, float)):
            out[name] = int(value)
    return out or None


def buffer_footprint(buffer: Any) -> Dict[str, int]:
    """Host/disk/device byte footprint of a replay buffer (any of the
    ``sheeprl_tpu.data`` classes exposing ``footprint()``)."""
    fp = getattr(buffer, "footprint", None)
    if callable(fp):
        try:
            out = fp()
            return {str(k): int(v) for k, v in out.items() if isinstance(v, (int, float))}
        except Exception:
            return {}
    return {}


# ---------------------------------------------------------------------------
# sharding / donation inspection


def _sharding_row(path: str, leaf: Any) -> Optional[Dict[str, Any]]:
    nbytes = _leaf_nbytes(leaf)
    if nbytes <= 0 or not hasattr(leaf, "shape"):
        return None
    row: Dict[str, Any] = {
        "path": path,
        "shape": list(getattr(leaf, "shape", ())),
        "dtype": str(getattr(leaf, "dtype", "?")),
        "bytes": nbytes,
    }
    sharding = getattr(leaf, "sharding", None)
    n_devices = 1
    replicated = False
    if sharding is not None:
        try:
            n_devices = max(1, len(sharding.device_set))
        except Exception:
            n_devices = 1
        try:
            replicated = bool(sharding.is_fully_replicated) and n_devices > 1
        except Exception:
            replicated = False
        row["sharding"] = str(sharding)[:120]
    row["n_devices"] = n_devices
    row["replicated"] = replicated
    # a replicated array costs its FULL size on every device; a sharded one
    # costs its shard — shard_shape is exact for partially-replicated 2-D
    # layouts (replicated over "data", sharded over "model")
    per_device = nbytes if replicated else max(1, nbytes) // n_devices
    if sharding is not None and not replicated:
        try:
            import numpy as np

            shard_shape = sharding.shard_shape(tuple(leaf.shape))
            itemsize = np.dtype(leaf.dtype).itemsize
            per_device = int(np.prod(shard_shape, dtype=np.int64)) * itemsize
        except Exception:
            pass
    row["bytes_per_device"] = per_device
    return row


def sharding_table(
    args: Tuple[Any, ...],
    kwargs: Mapping[str, Any],
    top_n: int = 20,
    replicated_warn_bytes: Optional[int] = None,
    fsdp_axis_size: Optional[int] = None,
    fsdp_min_shard_bytes: Optional[int] = None,
) -> Dict[str, Any]:
    """Per-leaf bytes/sharding rows of a dispatch's arguments, largest
    per-device cost first, plus totals (the ``sharding_audit`` payload).

    ``flagged_replicated`` is computed over ALL leaves before the table is
    truncated to ``top_n`` rows — a large replicated array must be flagged
    even when many sharded leaves outrank it.  Under FSDP
    (``fsdp_axis_size > 1``) leaves below ``fsdp_min_shard_bytes`` are exempt
    — the partition rule replicates them *on purpose* — and the flag comes
    with an actionable ``hint`` naming the knob instead of a bare list."""
    rows: List[Dict[str, Any]] = []
    for path, leaf in tree_leaf_sizes((args, dict(kwargs))):
        row = _sharding_row(path, leaf)
        if row is not None:
            rows.append(row)
    rows.sort(key=lambda r: r["bytes_per_device"], reverse=True)
    total = sum(r["bytes"] for r in rows)
    total_per_device = sum(r["bytes_per_device"] for r in rows)
    out: Dict[str, Any] = {
        "n_leaves": len(rows),
        "total_bytes": total,
        "total_bytes_per_device": total_per_device,
        "rows": rows[: max(1, int(top_n))],
    }
    if replicated_warn_bytes is not None:
        fsdp_on = fsdp_axis_size is not None and int(fsdp_axis_size) > 1
        exempt_below = int(fsdp_min_shard_bytes or 0) if fsdp_on else 0
        out["flagged_replicated"] = [
            r["path"]
            for r in rows
            if r["replicated"] and r["bytes"] >= max(replicated_warn_bytes, exempt_below)
        ]
        if out["flagged_replicated"]:
            if fsdp_on:
                out["hint"] = (
                    f"replicated leaves >= distribution.fsdp_min_shard_bytes under "
                    f"fsdp_axis_size={int(fsdp_axis_size)}: no dimension is divisible by "
                    "the model axis — consider padding the layer width or lowering the "
                    "axis size (howto/sharding.md)"
                )
            else:
                out["hint"] = (
                    "large replicated leaves on a multi-device mesh: set "
                    "distribution.fsdp_axis_size > 1 (fabric.fsdp) to shard them over "
                    "a second 'model' mesh axis (howto/sharding.md)"
                )
    return out


def donation_misses(args: Tuple[Any, ...], donate_argnums: Tuple[int, ...]) -> List[Dict[str, Any]]:
    """After a dispatch, the leaves of every donated argument should be
    consumed (``is_deleted``).  A live leaf means XLA kept both copies — the
    donation silently failed (dtype/layout mismatch, an extra reference, or a
    jit wrapper that dropped ``donate_argnums``)."""
    misses: List[Dict[str, Any]] = []
    for argnum in donate_argnums:
        if argnum >= len(args):
            continue
        for path, leaf in tree_leaf_sizes(args[argnum]):
            deleted = getattr(leaf, "is_deleted", None)
            if deleted is None or not hasattr(leaf, "shape"):
                # host numpy leaves can never be donated: that IS a miss
                if hasattr(leaf, "shape") and _leaf_nbytes(leaf) > 0:
                    misses.append({"argnum": argnum, "path": path, "bytes": _leaf_nbytes(leaf), "reason": "host array"})
                continue
            try:
                if not deleted():
                    misses.append({"argnum": argnum, "path": path, "bytes": _leaf_nbytes(leaf), "reason": "not donated"})
            except Exception:  # pragma: no cover - API drift
                continue
    return misses


# ---------------------------------------------------------------------------
# error classification


def is_resource_exhausted(err: BaseException) -> bool:
    text = f"{type(err).__name__}: {err}"
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text


def is_transfer_guard_error(err: BaseException) -> bool:
    text = str(err)
    return "Disallowed" in text and "transfer" in text


# ---------------------------------------------------------------------------
# the monitor


class MemoryMonitor:
    """Per-run memory/data-movement accounting behind the facade.

    Thread-safe counters (decoupled loops dispatch from worker threads; the
    metrics server snapshots from its own).  All journal writes go through the
    facade's ``journal_fn`` so rank gating stays in one place.
    """

    def __init__(self, cfg: Optional[Mapping[str, Any]] = None):
        cfg = cfg or {}
        diag_cfg = (cfg.get("diagnostics") or {}) if cfg else {}
        mem_cfg = diag_cfg.get("memory") or {}
        self.enabled = bool(mem_cfg.get("enabled", True))
        self.transfer_mode = normalize_transfer_mode(diag_cfg.get("transfers"))
        self.hbm_enabled = bool(mem_cfg.get("hbm", True))
        self.replicated_warn_bytes = int(
            mem_cfg.get("replicated_warn_bytes", DEFAULT_REPLICATED_WARN_BYTES)
        )
        self.audit_top_n = int(mem_cfg.get("audit_top_n", 20))
        inject_transfer = mem_cfg.get("inject_transfer_iter")
        self._inject_transfer_iter = None if inject_transfer is None else int(inject_transfer)
        inject_oom = mem_cfg.get("inject_oom_iter")
        self._inject_oom_iter = None if inject_oom is None else int(inject_oom)

        self._lock = threading.Lock()
        self._journal_fn: Optional[Callable[..., None]] = None
        self._sync_fn: Optional[Callable[[], None]] = None
        self._footprints: Dict[str, int] = {}
        self._footprints_per_device: Dict[str, int] = {}
        # armed by the facade's on_fsdp_shard_map: {"axis_size", "min_shard_bytes"}
        self._fsdp: Optional[Dict[str, int]] = None
        self._buffers: Dict[str, Any] = {}
        self._executables: Dict[str, Dict[str, int]] = {}
        self._train_calls = 0
        self._audited = False
        self._post_audit_done = False
        self._breakdown_emitted = False
        self._hbm_source: Optional[str] = None
        self._live_peak = 0
        self._latest: Dict[str, float] = {}
        # counters mirrored to /metrics
        self._host_transfers = 0
        self._donation_miss_leaves = 0
        self._oom_events = 0

    # -- lifecycle ---------------------------------------------------------
    def open(self, journal_fn: Optional[Callable[..., None]] = None, sync_fn: Optional[Callable[[], None]] = None) -> None:
        self._journal_fn = journal_fn
        self._sync_fn = sync_fn

    def _journal(self, event: str, **fields: Any) -> None:
        if self._journal_fn is not None:
            self._journal_fn(event, **fields)

    def _journal_synced(self, event: str, **fields: Any) -> None:
        """Journal + force the bytes to disk — for events whose whole point is
        surviving the process dying right afterwards (oom)."""
        self._journal(event, **fields)
        if self._sync_fn is not None:
            try:
                self._sync_fn()
            except Exception:  # pragma: no cover
                pass

    # -- component registration (called by the training loops) -------------
    def register_footprint(self, name: str, tree_or_bytes: Any) -> None:
        """Record a static component's byte size (params, optimizer state...)
        for the ``memory_breakdown`` event.  Accepts a pytree or raw bytes."""
        if not self.enabled:
            return
        size = int(tree_or_bytes) if isinstance(tree_or_bytes, (int, float)) else tree_bytes(tree_or_bytes)
        per_device = None
        if not isinstance(tree_or_bytes, (int, float)):
            try:
                from sheeprl_tpu.parallel.fsdp import tree_bytes_per_device

                per_device = tree_bytes_per_device(tree_or_bytes)
            except Exception:  # pragma: no cover - never block registration
                per_device = None
        with self._lock:
            self._footprints[str(name)] = size
            if per_device is not None and per_device != size:
                # only genuinely sharded components get a per-device entry —
                # replicated/host trees cost their full size everywhere
                self._footprints_per_device[str(name)] = per_device

    def note_fsdp(self, summary: Mapping[str, Any]) -> None:
        """Arm FSDP-aware accounting (called via the facade's
        ``on_fsdp_shard_map``): the axis-size gauge, the sharding audit's
        ``min_shard_bytes`` exemption, and the per-device breakdown column."""
        if not self.enabled:
            return
        with self._lock:
            self._fsdp = {
                "axis_size": int(summary.get("axis_size", 1) or 1),
                "min_shard_bytes": int(summary.get("min_shard_bytes", 0) or 0),
            }

    def track_buffer(self, name: str, buffer: Any) -> None:
        """Track a replay buffer's live footprint (re-queried every metric
        interval: memmap growth and host-RAM growth both show up)."""
        if not self.enabled:
            return
        with self._lock:
            self._buffers[str(name)] = buffer

    def note_executable(self, name: str, compiled: Any) -> None:
        """Capture the compiled step's memory analysis (called by the
        telemetry AOT path at first compile — zero extra compiles)."""
        if not self.enabled:
            return
        analysis = executable_memory_analysis(compiled)
        if analysis:
            with self._lock:
                self._executables[str(name)] = analysis

    # -- guarded dispatch ---------------------------------------------------
    def guarded_call(
        self,
        inst: Any,
        call: Callable[[], Any],
        args: Tuple[Any, ...],
        kwargs: Mapping[str, Any],
        count_call: bool = True,
    ):
        """Run one instrumented dispatch under the transfer guard with fault
        injection, first-dispatch audits and OOM forensics.

        ``count_call=False`` marks a RETRY of the same logical step (the
        telemetry AOT-fallback re-dispatch) so one train iteration never
        advances the dispatch counter — and hence the injection targets and
        the journaled ``call`` provenance — twice.

        Errors this layer has already journaled are tagged
        ``_sheeprl_diag_handled`` so the telemetry AOT-fallback handler
        re-raises them instead of mistaking them for an AOT dispatch problem.
        """
        is_train = getattr(inst, "kind", "train") == "train"
        call_idx = 0
        first_train = False
        if is_train:
            with self._lock:
                if count_call:
                    self._train_calls += 1
                call_idx = self._train_calls
                first_train = not self._audited
                if first_train:
                    self._audited = True
        if first_train:
            self._sharding_audit(inst, args, kwargs)

        guard = self._guard_context()
        try:
            with guard:
                if is_train and self._inject_oom_iter is not None and call_idx == self._inject_oom_iter:
                    self._inject_oom_iter = None
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: injected out-of-memory "
                        "(diagnostics.memory.inject_oom_iter) — OOM-forensics drill"
                    )
                out = call()
                if (
                    is_train
                    and self.transfer_mode != "off"  # the drill drills the GUARD: no guard, nothing to drill
                    and self._inject_transfer_iter is not None
                    and call_idx == self._inject_transfer_iter
                ):
                    self._inject_transfer_iter = None
                    self._fire_transfer_injection(inst, call_idx, out)
        except Exception as err:
            handled = self._handle_dispatch_error(inst, call_idx, err)
            if handled:
                err._sheeprl_diag_handled = True  # type: ignore[attr-defined]
            raise
        if is_train and not self._post_audit_done:
            # tracked separately from the pre-call audit: if the first
            # dispatch died mid-call (AOT fallback retry), the donation check
            # and breakdown still run on the first call that completes
            self._post_audit_done = True
            self._donation_audit(inst, args)
            self._emit_breakdown(inst)
        return out

    def _guard_context(self):
        if self.transfer_mode == "off":
            return nullcontext()
        import jax

        return jax.transfer_guard(self.transfer_mode)

    def _fire_transfer_injection(self, inst: Any, call_idx: int, out: Any) -> None:
        """The end-to-end drill.  ``log`` mode: force a REAL device→host sync
        on an output leaf inside the guarded scope (the runtime logs it, the
        journal records it, the run continues).  ``disallow`` mode: force an
        *implicit* host→device transfer — the one direction every backend's
        guard rejects — so the blocked-transfer path is exercised too."""
        import numpy as np

        if self.transfer_mode == "disallow":
            import jax.numpy as jnp

            # numpy operand entering a jitted computation = implicit h2d;
            # raises inside the surrounding guard and is journaled by the
            # dispatch error handler
            jnp.add(jnp.zeros((4,), jnp.float32), np.ones((4,), np.float32)).block_until_ready()
            return
        import jax

        leaves = [l for l in jax.tree_util.tree_leaves(out) if hasattr(l, "shape")]
        if not leaves:  # nothing to sync on: still record that the drill ran
            synced_bytes = 0
        else:
            fetched = np.asarray(leaves[0])  # device->host sync
            synced_bytes = int(fetched.nbytes)
        with self._lock:
            self._host_transfers += 1
        self._journal(
            "host_transfer",
            fn=getattr(inst, "name", "?"),
            call=call_idx,
            direction="device_to_host",
            injected=True,
            policy=self.transfer_mode,
            bytes=synced_bytes,
        )

    def _handle_dispatch_error(self, inst: Any, call_idx: int, err: BaseException) -> bool:
        if getattr(err, "_sheeprl_diag_handled", False):
            return True
        if is_transfer_guard_error(err):
            with self._lock:
                self._host_transfers += 1
            self._journal_synced(
                "host_transfer",
                fn=getattr(inst, "name", "?"),
                call=call_idx,
                blocked=True,
                policy=self.transfer_mode,
                error=str(err)[:300],
            )
            return True
        if is_resource_exhausted(err):
            with self._lock:
                self._oom_events += 1
            self._journal_synced(
                "oom",
                fn=getattr(inst, "name", "?"),
                call=call_idx,
                error=str(err)[:500],
                **self._forensics_snapshot(),
            )
            return True
        return False

    def _forensics_snapshot(self) -> Dict[str, Any]:
        """What a post-mortem needs, gathered defensively (the process may be
        in a bad state — never let forensics raise over the real error)."""
        snap: Dict[str, Any] = {}
        try:
            stats = device_memory_stats()
            if stats:
                snap["device_memory"] = stats
            else:
                snap["live_arrays"] = live_array_bytes()
        except Exception:  # pragma: no cover
            pass
        try:
            rss = host_rss_bytes()
            if rss is not None:
                snap["host_rss_bytes"] = rss
        except Exception:  # pragma: no cover
            pass
        with self._lock:
            if self._footprints:
                snap["components"] = dict(self._footprints)
            if self._executables:
                snap["executables"] = {k: dict(v) for k, v in self._executables.items()}
        try:
            buffers = {name: buffer_footprint(buf) for name, buf in list(self._buffers.items())}
            buffers = {k: v for k, v in buffers.items() if v}
            if buffers:
                snap["buffers"] = buffers
        except Exception:  # pragma: no cover
            pass
        return snap

    # -- first-dispatch audits ----------------------------------------------
    def _sharding_audit(self, inst: Any, args: Tuple[Any, ...], kwargs: Mapping[str, Any]) -> None:
        with self._lock:
            fsdp = dict(self._fsdp) if self._fsdp else {}
        try:
            table = sharding_table(
                args,
                kwargs,
                top_n=self.audit_top_n,
                replicated_warn_bytes=self.replicated_warn_bytes,
                fsdp_axis_size=fsdp.get("axis_size"),
                fsdp_min_shard_bytes=fsdp.get("min_shard_bytes"),
            )
        except Exception:  # pragma: no cover - never block the dispatch
            return
        self._journal("sharding_audit", fn=getattr(inst, "name", "?"), **table)

    def _donation_audit(self, inst: Any, args: Tuple[Any, ...]) -> None:
        donate = tuple(getattr(inst, "donate_argnums", ()) or ())
        if not donate:
            return
        try:
            misses = donation_misses(args, donate)
        except Exception:  # pragma: no cover
            return
        if not misses:
            return
        with self._lock:
            self._donation_miss_leaves += len(misses)
        self._journal(
            "donation_miss",
            fn=getattr(inst, "name", "?"),
            n_leaves=len(misses),
            bytes=sum(m["bytes"] for m in misses),
            leaves=misses[: self.audit_top_n],
        )

    def _emit_breakdown(self, inst: Any) -> None:
        with self._lock:
            if self._breakdown_emitted:
                return
            self._breakdown_emitted = True
        self._journal("memory_breakdown", fn=getattr(inst, "name", "?"), **self.breakdown())

    def breakdown(self) -> Dict[str, Any]:
        """The static footprint decomposition (``memory_breakdown`` payload
        and the ``tools/memory_report.py`` table)."""
        out: Dict[str, Any] = {}
        with self._lock:
            components = dict(self._footprints)
            per_device = dict(self._footprints_per_device)
            fsdp = dict(self._fsdp) if self._fsdp else None
            executables = {k: dict(v) for k, v in self._executables.items()}
            buffers = dict(self._buffers)
        for name, buf in buffers.items():
            fp = buffer_footprint(buf)
            for kind, size in fp.items():
                components[f"{name}_{kind}"] = size
        out["components"] = components
        if per_device:
            # present only when something is genuinely sharded (FSDP runs):
            # the per-device cost of each component, report.py renders the
            # extra column
            out["components_per_device"] = per_device
        if fsdp:
            out["fsdp_axis_size"] = fsdp["axis_size"]
        if executables:
            out["executables"] = executables
        stats = device_memory_stats()
        if stats:
            out["device_memory"] = stats
            out["source"] = "memory_stats"
        else:
            out["live_arrays"] = live_array_bytes()
            out["source"] = "live_arrays"
        rss = host_rss_bytes()
        if rss is not None:
            out["host_rss_bytes"] = rss
        return out

    # -- interval gauges -----------------------------------------------------
    def interval_metrics(self) -> Dict[str, float]:
        """``Telemetry/hbm_*`` + buffer/host gauges for one metric interval
        (merged by the facade next to the compute telemetry gauges)."""
        if not (self.enabled and self.hbm_enabled):
            return {}
        out: Dict[str, float] = {}
        stats = device_memory_stats()
        if stats:
            self._hbm_source = "memory_stats"
            in_use = max((s.get("bytes_in_use", 0) or 0) for s in stats)
            peak = max((s.get("peak_bytes_in_use", 0) or 0) for s in stats)
            largest = max((s.get("largest_alloc_size", 0) or 0) for s in stats)
            out["Telemetry/hbm_bytes_in_use"] = float(in_use)
            if peak:
                out["Telemetry/hbm_peak_bytes"] = float(peak)
            if largest:
                out["Telemetry/hbm_largest_alloc_bytes"] = float(largest)
        else:
            self._hbm_source = "live_arrays"
            live = live_array_bytes()
            with self._lock:
                self._live_peak = max(self._live_peak, live["bytes_in_use"])
                peak = self._live_peak
            out["Telemetry/hbm_bytes_in_use"] = float(live["bytes_in_use"])
            out["Telemetry/hbm_peak_bytes"] = float(peak)
            out["Telemetry/hbm_largest_alloc_bytes"] = float(live["largest_alloc_bytes"])
        rss = host_rss_bytes()
        if rss is not None:
            out["Telemetry/host_rss_bytes"] = float(rss)
        with self._lock:
            fsdp = dict(self._fsdp) if self._fsdp else None
            params_per_device = self._footprints_per_device.get("params")
        if fsdp is not None:
            out["Telemetry/fsdp_axis_size"] = float(fsdp["axis_size"])
            if params_per_device is not None:
                out["Telemetry/params_bytes_per_device"] = float(params_per_device)
        with self._lock:
            buffers = dict(self._buffers)
        for name, buf in buffers.items():
            for kind, size in buffer_footprint(buf).items():
                out[f"Telemetry/{name}_{kind}"] = float(size)
        with self._lock:
            self._latest = dict(out)
        return out

    # -- snapshots (metrics server / run summary) ---------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "gauges": dict(self._latest),
                "counters": {
                    "host_transfers_total": self._host_transfers,
                    "donation_miss_leaves_total": self._donation_miss_leaves,
                    "oom_events_total": self._oom_events,
                },
                "info": {"hbm_source": self._hbm_source, "transfer_guard": self.transfer_mode},
            }

    def summary(self) -> Dict[str, Any]:
        """Cumulative totals for the closing ``memory_summary`` event."""
        snap = self.snapshot()
        with self._lock:
            components = dict(self._footprints)
            per_device = dict(self._footprints_per_device)
        out = {
            "host_transfers": snap["counters"]["host_transfers_total"],
            "donation_miss_leaves": snap["counters"]["donation_miss_leaves_total"],
            "oom_events": snap["counters"]["oom_events_total"],
            "hbm_source": self._hbm_source,
            "transfer_guard": self.transfer_mode,
            "components": components,
        }
        if per_device:
            out["components_per_device"] = per_device
        return out


# ---------------------------------------------------------------------------
# stderr-capture transfer counting (bench.py)


def count_guard_log_lines(fn: Callable[[], Any]) -> Tuple[Any, Optional[int]]:
    """Run ``fn`` under ``jax.transfer_guard("log")`` while capturing fd-level
    stderr, and count the runtime's transfer-log lines.

    The guard logs from C++ (not via Python logging), so the only faithful
    counter is the file descriptor itself.  Used by ``bench.py`` around its
    bounded headline stage — NOT in the training hot loop, where hijacking
    fd 2 would eat tracebacks.  Returns ``(result, count)``; count is None
    when the capture could not be set up (the result still lands).
    """
    import re
    import sys
    import tempfile

    import jax

    try:
        sys.stderr.flush()
        saved_fd = os.dup(2)
        tmp = tempfile.TemporaryFile(mode="w+b")
        os.dup2(tmp.fileno(), 2)
    except Exception:
        with jax.transfer_guard("log"):
            return fn(), None
    try:
        with jax.transfer_guard("log"):
            result = fn()
    finally:
        # restore fd 2 FIRST, then replay everything captured — especially
        # when fn raised: the runtime's error output written during the
        # stage must reach the real stderr, not vanish with the temp file
        sys.stderr.flush()
        os.dup2(saved_fd, 2)
        os.close(saved_fd)
        try:
            tmp.seek(0)
            text = tmp.read().decode(errors="replace")
            if text:
                sys.stderr.write(text)
                sys.stderr.flush()
        except Exception:
            text = None
        finally:
            tmp.close()
    if text is None:
        return result, None
    # host crossings only: device-to-device copies (resharding) are logged by
    # the guard too but are not host transfers
    count = len(re.findall(r"(host-to-device|device-to-host) transfer", text))
    return result, count
