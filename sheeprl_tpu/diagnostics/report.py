"""Journal analysis: the library behind ``tools/journal_report.py`` and the
live formatting shared with ``tools/run_monitor.py``.

Everything a post-mortem needs without TensorBoard archaeology: run identity
and config hash, the last logged step counter and metric values (including
``Rewards/rew_avg``), checkpoint and divergence timelines, and a CSV export
of the full metric history.  Works on journals from crashed runs — the reader
already skips a truncated trailing line.
"""

from __future__ import annotations

import csv
import os
import time
from typing import Any, Dict, List, Optional

from sheeprl_tpu.diagnostics.journal import find_journal, read_journal


def summarize(path: str) -> Dict[str, Any]:
    """Summarize a journal file (or a run directory containing one)."""
    journal_path = find_journal(path)
    if journal_path is None:
        raise FileNotFoundError(f"No journal.jsonl found under '{path}'")
    events = read_journal(journal_path)
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    checkpoints = [e for e in events if e.get("event") == "checkpoint"]
    divergences = [e for e in events if e.get("event") == "divergence"]
    run_start = next((e for e in events if e.get("event") == "run_start"), None)
    run_end = next((e for e in reversed(events) if e.get("event") == "run_end"), None)

    last_metrics = metrics_events[-1] if metrics_events else None
    last_rew = None
    last_rew_step = None
    for e in reversed(metrics_events):
        rew = (e.get("metrics") or {}).get("Rewards/rew_avg")
        if isinstance(rew, (int, float)):
            last_rew, last_rew_step = float(rew), e.get("step")
            break

    return {
        "journal_path": journal_path,
        "n_events": len(events),
        "run_start": run_start,
        "run_end": run_end,
        # a journal without run_end is the signature of a killed run
        "clean_shutdown": run_end is not None,
        "n_metrics_events": len(metrics_events),
        "last_step": last_metrics.get("step") if last_metrics else None,
        "last_metrics": (last_metrics.get("metrics") or {}) if last_metrics else {},
        "last_rew_avg": last_rew,
        "last_rew_avg_step": last_rew_step,
        "checkpoints": [{"step": e.get("step"), "path": e.get("path")} for e in checkpoints],
        "divergences": divergences,
    }


def to_csv(path: str, out_path: str) -> int:
    """Export the journal's metric history to CSV; returns the row count.

    Columns: ``t``, ``step``, then the union of metric names over the run
    (sorted).  Non-finite values survive as their journal string form
    ("nan"/"inf") so spreadsheet greps for them still work.
    """
    journal_path = find_journal(path)
    if journal_path is None:
        raise FileNotFoundError(f"No journal.jsonl found under '{path}'")
    rows: List[Dict[str, Any]] = []
    keys: List[str] = []
    seen = set()
    for e in read_journal(journal_path):
        if e.get("event") != "metrics":
            continue
        metrics = e.get("metrics") or {}
        rows.append({"t": e.get("t"), "step": e.get("step"), **metrics})
        for k in metrics:
            if k not in seen:
                seen.add(k)
                keys.append(k)
    fieldnames = ["t", "step"] + sorted(keys)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w", newline="", encoding="utf-8") as fp:
        writer = csv.DictWriter(fp, fieldnames=fieldnames)
        writer.writeheader()
        writer.writerows(rows)
    return len(rows)


def format_summary(summary: Dict[str, Any]) -> str:
    """Human-readable report (what the CLI prints)."""
    lines = [f"journal: {summary['journal_path']}"]
    start = summary.get("run_start") or {}
    if start:
        lines.append(
            "run:     algo={algo} env={env} seed={seed} config_hash={config_hash}".format(
                algo=start.get("algo", "?"),
                env=start.get("env", "?"),
                seed=start.get("seed", "?"),
                config_hash=start.get("config_hash", "?"),
            )
        )
    end = summary.get("run_end")
    lines.append(
        "status:  "
        + (f"{end.get('status', 'unknown')} (clean shutdown)" if end else "NO run_end event — run was killed or is still going")
    )
    lines.append(f"events:  {summary['n_events']} total, {summary['n_metrics_events']} metric intervals")
    if summary.get("last_step") is not None:
        lines.append(f"last logged step: {summary['last_step']}")
    if summary.get("last_rew_avg") is not None:
        lines.append(
            f"last Rewards/rew_avg: {summary['last_rew_avg']:.4f} (at step {summary['last_rew_avg_step']})"
        )
    if summary.get("last_metrics"):
        lines.append("last metrics:")
        for k, v in sorted(summary["last_metrics"].items()):
            lines.append(f"  {k}: {v}")
    ckpts = summary.get("checkpoints") or []
    lines.append(f"checkpoints: {len(ckpts)}" + (f" (last at step {ckpts[-1]['step']})" if ckpts else ""))
    divs = summary.get("divergences") or []
    if divs:
        lines.append(f"divergence events: {len(divs)}")
        for d in divs[-5:]:
            lines.append(
                "  step {step}: {kind} {detail}".format(
                    step=d.get("step", "?"),
                    kind=d.get("kind", "?"),
                    detail={k: v for k, v in d.items() if k not in ("t", "event", "step", "kind")},
                )
            )
    else:
        lines.append("divergence events: none")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# live formatting (shared by journal_report --follow and run_monitor)

_TELEMETRY_COLUMNS = (
    ("Rewards/rew_avg", "rew", "{:.2f}"),
    ("Telemetry/sps", "sps", "{:.0f}"),
    ("Telemetry/tflops_per_sec", "tflops", "{:.2f}"),
    ("Telemetry/mfu", "mfu", "{:.1%}"),
)


def _phase_summary(metrics: Dict[str, Any]) -> Optional[str]:
    phases = {
        k.rsplit("/", 1)[1]: v
        for k, v in metrics.items()
        if k.startswith("Telemetry/phase_pct/") and isinstance(v, (int, float))
    }
    if not phases:
        return None
    order = ("train", "env", "fetch", "other", "idle")
    keys = [k for k in order if k in phases] + sorted(set(phases) - set(order))
    return " ".join(f"{k}:{phases[k]:.0f}%" for k in keys)


def format_event_line(event: Dict[str, Any]) -> str:
    """One journal event as one compact terminal line (the tail/monitor
    format)."""
    t = event.get("t")
    clock = time.strftime("%H:%M:%S", time.localtime(t)) if isinstance(t, (int, float)) else "--:--:--"
    kind = str(event.get("event", "?"))
    if kind == "metrics":
        metrics = event.get("metrics") or {}
        parts = [f"step {event.get('step')}"]
        for key, label, fmt in _TELEMETRY_COLUMNS:
            value = metrics.get(key)
            if isinstance(value, (int, float)):
                parts.append(f"{label} {fmt.format(value)}")
        phases = _phase_summary(metrics)
        if phases:
            parts.append(phases)
        recompiles = metrics.get("Telemetry/recompiles")
        if isinstance(recompiles, (int, float)) and recompiles > 0:
            parts.append(f"recompiles {recompiles:g}")
        return f"[{clock}] {kind:<12s} " + "  ".join(parts)
    payload = {k: v for k, v in event.items() if k not in ("t", "event")}
    if kind == "recompile":
        diff = payload.get("diff") or []
        head = "; ".join(str(d) for d in diff[:3])
        return f"[{clock}] {kind:<12s} {payload.get('fn')} #{payload.get('count')}: {head}"
    if kind == "divergence":
        return f"[{clock}] {kind:<12s} step {payload.get('step')}: {payload.get('kind')}"
    detail = " ".join(f"{k}={v}" for k, v in payload.items() if not isinstance(v, (dict, list)))
    return f"[{clock}] {kind:<12s} {detail}".rstrip()


def status_block(events: List[Dict[str, Any]]) -> str:
    """Multi-line run status from a journal event list (run_monitor's view)."""
    run_start = next((e for e in events if e.get("event") == "run_start"), None)
    run_end = next((e for e in reversed(events) if e.get("event") == "run_end"), None)
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    last = metrics_events[-1] if metrics_events else None
    lines = []
    if run_start:
        lines.append(
            "run     {algo} on {env} (seed {seed})  id={rid}".format(
                algo=run_start.get("algo", "?"),
                env=run_start.get("env", "?"),
                seed=run_start.get("seed", "?"),
                rid=run_start.get("run_id", run_start.get("config_hash", "?")),
            )
        )
    age = None
    if events:
        newest = max((e.get("t") for e in events if isinstance(e.get("t"), (int, float))), default=None)
        if newest is not None:
            age = time.time() - newest
    state = f"ended: {run_end.get('status')}" if run_end else "running"
    if age is not None and run_end is None:
        state += f" (last journal write {age:.0f}s ago)"
    lines.append(f"state   {state}")
    if last:
        lines.append(format_event_line(last))
    server = next((e for e in reversed(events) if e.get("event") == "metrics_server"), None)
    if server and server.get("status") == "serving":
        lines.append(f"metrics http://{server.get('host')}:{server.get('port')}/metrics")
    n_div = sum(1 for e in events if e.get("event") == "divergence")
    n_rec = sum(1 for e in events if e.get("event") == "recompile")
    n_ckpt = sum(1 for e in events if e.get("event") == "checkpoint")
    lines.append(f"events  {len(events)} total · {len(metrics_events)} intervals · "
                 f"{n_ckpt} checkpoints · {n_rec} recompiles · {n_div} divergences")
    return "\n".join(lines)
