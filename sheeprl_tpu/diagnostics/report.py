"""Journal analysis: the library behind ``tools/journal_report.py`` and the
live formatting shared with ``tools/run_monitor.py``.

Everything a post-mortem needs without TensorBoard archaeology: run identity
and config hash, the last logged step counter and metric values (including
``Rewards/rew_avg``), checkpoint and divergence timelines, and a CSV export
of the full metric history.  Works on journals from crashed runs — the reader
already skips a truncated trailing line.
"""

from __future__ import annotations

import csv
import os
import time
from typing import Any, Dict, List, Optional

from sheeprl_tpu.diagnostics.journal import find_journal, read_journal


def summarize(path: str) -> Dict[str, Any]:
    """Summarize a journal file (or a run directory containing one)."""
    journal_path = find_journal(path)
    if journal_path is None:
        raise FileNotFoundError(f"No journal.jsonl found under '{path}'")
    events = read_journal(journal_path)
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    checkpoints = [e for e in events if e.get("event") == "checkpoint"]
    divergences = [e for e in events if e.get("event") == "divergence"]
    run_start = next((e for e in events if e.get("event") == "run_start"), None)
    run_end = next((e for e in reversed(events) if e.get("event") == "run_end"), None)

    last_metrics = metrics_events[-1] if metrics_events else None
    last_rew = None
    last_rew_step = None
    for e in reversed(metrics_events):
        rew = (e.get("metrics") or {}).get("Rewards/rew_avg")
        if isinstance(rew, (int, float)):
            last_rew, last_rew_step = float(rew), e.get("step")
            break

    return {
        "journal_path": journal_path,
        "n_events": len(events),
        "run_start": run_start,
        "run_end": run_end,
        # a journal without run_end is the signature of a killed run
        "clean_shutdown": run_end is not None,
        "n_metrics_events": len(metrics_events),
        "last_step": last_metrics.get("step") if last_metrics else None,
        "last_metrics": (last_metrics.get("metrics") or {}) if last_metrics else {},
        "last_rew_avg": last_rew,
        "last_rew_avg_step": last_rew_step,
        "checkpoints": [{"step": e.get("step"), "path": e.get("path")} for e in checkpoints],
        "divergences": divergences,
    }


def to_csv(path: str, out_path: str) -> int:
    """Export the journal's metric history to CSV; returns the row count.

    Columns: ``t``, ``step``, then the union of metric names over the run
    (sorted).  Non-finite values survive as their journal string form
    ("nan"/"inf") so spreadsheet greps for them still work.
    """
    journal_path = find_journal(path)
    if journal_path is None:
        raise FileNotFoundError(f"No journal.jsonl found under '{path}'")
    rows: List[Dict[str, Any]] = []
    keys: List[str] = []
    seen = set()
    for e in read_journal(journal_path):
        if e.get("event") != "metrics":
            continue
        metrics = e.get("metrics") or {}
        rows.append({"t": e.get("t"), "step": e.get("step"), **metrics})
        for k in metrics:
            if k not in seen:
                seen.add(k)
                keys.append(k)
    fieldnames = ["t", "step"] + sorted(keys)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w", newline="", encoding="utf-8") as fp:
        writer = csv.DictWriter(fp, fieldnames=fieldnames)
        writer.writeheader()
        writer.writerows(rows)
    return len(rows)


def format_summary(summary: Dict[str, Any]) -> str:
    """Human-readable report (what the CLI prints)."""
    lines = [f"journal: {summary['journal_path']}"]
    start = summary.get("run_start") or {}
    if start:
        lines.append(
            "run:     algo={algo} env={env} seed={seed} config_hash={config_hash}".format(
                algo=start.get("algo", "?"),
                env=start.get("env", "?"),
                seed=start.get("seed", "?"),
                config_hash=start.get("config_hash", "?"),
            )
        )
    end = summary.get("run_end")
    lines.append(
        "status:  "
        + (f"{end.get('status', 'unknown')} (clean shutdown)" if end else "NO run_end event — run was killed or is still going")
    )
    lines.append(f"events:  {summary['n_events']} total, {summary['n_metrics_events']} metric intervals")
    if summary.get("last_step") is not None:
        lines.append(f"last logged step: {summary['last_step']}")
    if summary.get("last_rew_avg") is not None:
        lines.append(
            f"last Rewards/rew_avg: {summary['last_rew_avg']:.4f} (at step {summary['last_rew_avg_step']})"
        )
    if summary.get("last_metrics"):
        lines.append("last metrics:")
        for k, v in sorted(summary["last_metrics"].items()):
            lines.append(f"  {k}: {v}")
    ckpts = summary.get("checkpoints") or []
    lines.append(f"checkpoints: {len(ckpts)}" + (f" (last at step {ckpts[-1]['step']})" if ckpts else ""))
    divs = summary.get("divergences") or []
    if divs:
        lines.append(f"divergence events: {len(divs)}")
        for d in divs[-5:]:
            lines.append(
                "  step {step}: {kind} {detail}".format(
                    step=d.get("step", "?"),
                    kind=d.get("kind", "?"),
                    detail={k: v for k, v in d.items() if k not in ("t", "event", "step", "kind")},
                )
            )
    else:
        lines.append("divergence events: none")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# live formatting (shared by journal_report --follow and run_monitor)


def format_bytes(n: Any) -> str:
    """Human bytes (binary units) — '—' for missing values."""
    if not isinstance(n, (int, float)):
        return "—"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.0f} {unit}" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TiB"  # pragma: no cover - loop always returns


_TELEMETRY_COLUMNS = (
    ("Rewards/rew_avg", "rew", "{:.2f}"),
    ("Telemetry/sps", "sps", "{:.0f}"),
    ("Telemetry/env_steps_per_sec", "env-sps", "{:.0f}"),
    ("Telemetry/fetch_amortization", "fetch-amort", "{:.0f}x"),
    # offline mode (howto/offline_rl.md): the dataset feed replaces env-sps
    ("Telemetry/dataset_read_sps", "dataset-sps", "{:.0f}"),
    ("Telemetry/dataset_epoch", "epoch", "{:.0f}"),
    ("Telemetry/tflops_per_sec", "tflops", "{:.2f}"),
    ("Telemetry/mfu", "mfu", "{:.1%}"),
)


def _phase_summary(metrics: Dict[str, Any]) -> Optional[str]:
    phases = {
        k.rsplit("/", 1)[1]: v
        for k, v in metrics.items()
        if k.startswith("Telemetry/phase_pct/") and isinstance(v, (int, float))
    }
    if not phases:
        return None
    order = ("train", "env", "fetch", "other", "idle")
    keys = [k for k in order if k in phases] + sorted(set(phases) - set(order))
    return " ".join(f"{k}:{phases[k]:.0f}%" for k in keys)


def format_event_line(event: Dict[str, Any]) -> str:
    """One journal event as one compact terminal line (the tail/monitor
    format)."""
    t = event.get("t")
    clock = time.strftime("%H:%M:%S", time.localtime(t)) if isinstance(t, (int, float)) else "--:--:--"
    kind = str(event.get("event", "?"))
    if kind == "metrics":
        metrics = event.get("metrics") or {}
        parts = [f"step {event.get('step')}"]
        for key, label, fmt in _TELEMETRY_COLUMNS:
            value = metrics.get(key)
            if isinstance(value, (int, float)):
                parts.append(f"{label} {fmt.format(value)}")
        phases = _phase_summary(metrics)
        if phases:
            parts.append(phases)
        hbm = metrics.get("Telemetry/hbm_bytes_in_use")
        if isinstance(hbm, (int, float)):
            peak = metrics.get("Telemetry/hbm_peak_bytes")
            hbm_s = format_bytes(hbm)
            if isinstance(peak, (int, float)) and peak > 0:
                hbm_s += f"/{format_bytes(peak)}"
            parts.append(f"hbm {hbm_s}")
        recompiles = metrics.get("Telemetry/recompiles")
        if isinstance(recompiles, (int, float)) and recompiles > 0:
            parts.append(f"recompiles {recompiles:g}")
        return f"[{clock}] {kind:<12s} " + "  ".join(parts)
    payload = {k: v for k, v in event.items() if k not in ("t", "event")}
    if kind == "state_change":
        return f"[{clock}] {kind:<12s} {payload.get('prev')} -> {payload.get('state')}"
    if kind == "stall":
        # `stacks` is a multi-KB forensics blob — never dump it on a tail line
        return (
            f"[{clock}] {'!! STALL':<12s} no progress for {payload.get('idle_s')}s "
            f"(threshold {payload.get('threshold_s')}s, was {payload.get('last_state')}; "
            "thread stacks in the journal)"
        )
    if kind == "stall_end":
        return (
            f"[{clock}] {kind:<12s} recovered after {payload.get('stalled_s')}s "
            f"-> {payload.get('state')}"
        )
    if kind == "profile_capture":
        where = f" -> {payload.get('dir')}" if payload.get("dir") else ""
        return f"[{clock}] {kind:<12s} {payload.get('status')}{where}"
    if kind == "recompile":
        diff = payload.get("diff") or []
        head = "; ".join(str(d) for d in diff[:3])
        return f"[{clock}] {kind:<12s} {payload.get('fn')} #{payload.get('count')}: {head}"
    if kind == "divergence":
        return f"[{clock}] {kind:<12s} step {payload.get('step')}: {payload.get('kind')}"
    if kind == "anomaly":
        window = payload.get("window") or []
        head = ", ".join(f"{v:g}" for v in window[-4:] if isinstance(v, (int, float)))
        return (
            f"[{clock}] {'!! ANOMALY':<12s} {payload.get('kind')} on {payload.get('subject')} "
            f"at step {payload.get('step')} (window tail: {head})"
        )
    if kind == "anomaly_end":
        return (
            f"[{clock}] {kind:<12s} {payload.get('kind')} on {payload.get('subject')} cleared "
            f"at step {payload.get('step')} (active since step {payload.get('since_step')})"
        )
    if kind == "ckpt_end":
        if payload.get("status") == "failed":
            return (
                f"[{clock}] {'!! CKPT-FAIL':<12s} step {payload.get('step')}: "
                f"{str(payload.get('error', ''))[:80]}"
            )
        mode = "blocking" if payload.get("blocking") else "async"
        return (
            f"[{clock}] {kind:<12s} step {payload.get('step')} "
            f"{format_bytes(payload.get('bytes'))} in {payload.get('write_ms')}ms ({mode})"
        )
    if kind == "ckpt_skipped":
        return f"[{clock}] {kind:<12s} {payload.get('path')}: {payload.get('reason')}"
    if kind == "params_reject":
        mark = "!! PARAMS-REJ" if payload.get("escalate") else kind
        return (
            f"[{clock}] {mark:<12s} {payload.get('reason')} at iter {payload.get('iter_num')} "
            f"(staleness {payload.get('staleness')}/{payload.get('budget')}; player on last-good params)"
        )
    if kind == "rollback":
        return (
            f"[{clock}] {'!! ROLLBACK':<12s} restored iter-{payload.get('restored_iter')} snapshot at iter "
            f"{payload.get('iter_num')} ({payload.get('retries_left')}/{payload.get('budget')} retries left): "
            f"{str(payload.get('error', ''))[:60]}"
        )
    if kind == "preempted":
        return (
            f"[{clock}] {'!! PREEMPT':<12s} {payload.get('reason')} at iter "
            f"{payload.get('iter_num')}; emergency checkpoint {payload.get('path')}"
        )
    if kind == "memory_breakdown":
        components = payload.get("components") or {}
        total = sum(v for v in components.values() if isinstance(v, (int, float)))
        return (
            f"[{clock}] {kind:<12s} {len(components)} components, {format_bytes(total)} static"
            f" (source {payload.get('source', '?')})"
        )
    if kind == "sharding_audit":
        flagged = payload.get("flagged_replicated") or []
        head = f"{payload.get('n_leaves')} leaves, {format_bytes(payload.get('total_bytes_per_device'))}/device"
        if flagged:
            head += f"  REPLICATED: {', '.join(str(f) for f in flagged[:3])}"
        return f"[{clock}] {kind:<12s} {payload.get('fn')}: {head}"
    if kind == "host_transfer":
        what = "BLOCKED" if payload.get("blocked") else ("injected d2h" if payload.get("injected") else "detected")
        return f"[{clock}] {kind:<12s} {payload.get('fn')} call #{payload.get('call')}: {what} (policy {payload.get('policy')})"
    if kind == "donation_miss":
        return (
            f"[{clock}] {kind:<12s} {payload.get('fn')}: {payload.get('n_leaves')} leaves kept alive "
            f"({format_bytes(payload.get('bytes'))} not donated)"
        )
    if kind == "oom":
        return f"[{clock}] {kind:<12s} {payload.get('fn')} call #{payload.get('call')}: {str(payload.get('error', ''))[:80]}"
    if kind == "slo_breach":
        return (
            f"[{clock}] {'!! SLO-BREACH':<12s} {payload.get('model') or 'default'}: "
            f"burn {payload.get('burn')} (target {payload.get('target_ms')}ms, "
            f"objective {payload.get('objective')}, window {payload.get('window')})"
        )
    if kind == "slo_breach_end":
        breach_s = payload.get("breach_s")
        took = f" after {breach_s:.0f}s" if isinstance(breach_s, (int, float)) else ""
        return (
            f"[{clock}] {kind:<12s} {payload.get('model') or 'default'} recovered{took} "
            f"(burn {payload.get('burn')})"
        )
    if kind == "slow_request":
        phases = payload.get("phases") or {}
        breakdown = " + ".join(
            f"{name.replace('_ms', '')} {phases[name]:.0f}"
            for name in ("queue_ms", "batch_form_ms", "dispatch_ms", "scatter_ms")
            if isinstance(phases.get(name), (int, float))
        )
        return (
            f"[{clock}] {'!! SLOW-REQ':<12s} {payload.get('request_id')} on "
            f"{payload.get('model') or 'default'}: {payload.get('total_ms')}ms "
            f"({breakdown}ms; width {payload.get('batch_width')}, "
            f"queue depth {payload.get('queue_depth')})"
        )
    detail = " ".join(f"{k}={v}" for k, v in payload.items() if not isinstance(v, (dict, list)))
    return f"[{clock}] {kind:<12s} {detail}".rstrip()


def status_block(events: List[Dict[str, Any]]) -> str:
    """Multi-line run status from a journal event list (run_monitor's view)."""
    run_start = next((e for e in events if e.get("event") == "run_start"), None)
    run_end = next((e for e in reversed(events) if e.get("event") == "run_end"), None)
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    last = metrics_events[-1] if metrics_events else None
    lines = []
    if run_start:
        lines.append(
            "run     {algo} on {env} (seed {seed})  id={rid}".format(
                algo=run_start.get("algo", "?"),
                env=run_start.get("env", "?"),
                seed=run_start.get("seed", "?"),
                rid=run_start.get("run_id", run_start.get("config_hash", "?")),
            )
        )
    age = None
    if events:
        newest = max((e.get("t") for e in events if isinstance(e.get("t"), (int, float))), default=None)
        if newest is not None:
            age = time.time() - newest
    state = f"ended: {run_end.get('status')}" if run_end else "running"
    if age is not None and run_end is None:
        state += f" (last journal write {age:.0f}s ago)"
    lines.append(f"state   {state}")
    if last:
        lines.append(format_event_line(last))
    server = next((e for e in reversed(events) if e.get("event") == "metrics_server"), None)
    if server and server.get("status") == "serving":
        lines.append(f"metrics http://{server.get('host')}:{server.get('port')}/metrics")
    n_div = sum(1 for e in events if e.get("event") == "divergence")
    n_rec = sum(1 for e in events if e.get("event") == "recompile")
    n_ckpt = sum(1 for e in events if e.get("event") == "checkpoint")
    lines.append(f"events  {len(events)} total · {len(metrics_events)} intervals · "
                 f"{n_ckpt} checkpoints · {n_rec} recompiles · {n_div} divergences")
    lines.extend(goodput_status_lines(events, live=run_end is None))
    lines.extend(checkpoint_status_lines(events, live=run_end is None))
    lines.extend(isolation_status_lines(events, live=run_end is None))
    lines.extend(health_status_lines(events, live=run_end is None))
    lines.extend(memory_status_lines(events))
    lines.extend(serving_status_lines(events, live=run_end is None))
    lines.extend(serving_latency_lines(events, live=run_end is None))
    return "\n".join(lines)


#: A live run whose newest verified checkpoint is older than this many
#: observed checkpoint intervals (with a 30 s floor) gets the
#: ``!! NO-RECENT-CKPT`` banner — it would lose everything since then on a
#: preemption.  Shared by the journal view here and run_monitor's --url mode.
NO_RECENT_CKPT_INTERVALS = 3.0

#: Banner fallback when no cadence is observable yet (a single checkpoint so
#: far, or an endpoint that has not exported an interval): age alone past
#: this hard ceiling still fires — the single-stuck-checkpoint run is exactly
#: the case the banner exists for.
NO_RECENT_CKPT_FALLBACK_S = 1800.0


def no_recent_ckpt_banner(age_s: Optional[float], cadence_s: Optional[float]) -> Optional[str]:
    """The ``!! NO-RECENT-CKPT`` banner line (or None): ONE owner for the
    threshold/wording so the journal view and run_monitor's endpoint mode
    can never drift."""
    if age_s is None:
        return None
    if cadence_s:
        if age_s > max(30.0, NO_RECENT_CKPT_INTERVALS * cadence_s):
            return (
                f"!! NO-RECENT-CKPT — newest verified checkpoint is {age_s:.0f}s old "
                f"(~{age_s / cadence_s:.0f} intervals); a preemption now loses everything since"
            )
        return None
    if age_s > NO_RECENT_CKPT_FALLBACK_S:
        return (
            f"!! NO-RECENT-CKPT — newest verified checkpoint is {age_s:.0f}s old "
            "(no cadence observed yet); a preemption now loses everything since"
        )
    return None


def _median(values: List[float]) -> Optional[float]:
    values = sorted(v for v in values if isinstance(v, (int, float)) and v > 0)
    if not values:
        return None
    return values[len(values) // 2]


def checkpoint_status_lines(events: List[Dict[str, Any]], live: bool = True) -> List[str]:
    """The checkpoint-freshness panel (run_monitor + journal_report share
    it): newest checkpoint step/age, verified-write counters from the
    resilience layer's ``ckpt_end`` events, mean write cost, and — live mode
    only — the ``!! NO-RECENT-CKPT`` banner when the newest verified
    checkpoint is older than :data:`NO_RECENT_CKPT_INTERVALS` observed
    checkpoint intervals.  Empty when the run journaled no checkpoints."""
    writes = [
        e
        for e in events
        if e.get("event") == "ckpt_end" and e.get("status", "ok") == "ok"
    ]
    failures = sum(1 for e in events if e.get("event") == "ckpt_end" and e.get("status") == "failed")
    plain = [e for e in events if e.get("event") == "checkpoint"]
    marks = writes or plain
    if not marks:
        return []
    newest = max(marks, key=lambda e: e.get("t") or 0.0)
    step = newest.get("step")
    parts = [f"{len(marks)} written"]
    if step is not None:
        parts.append(f"last step {step}")
    verified = [e for e in writes if e.get("verified")]
    if verified:
        v_step = max(verified, key=lambda e: e.get("t") or 0.0).get("step")
        if v_step is not None and v_step != step:
            parts.append(f"last verified step {v_step}")
        elif v_step is not None:
            parts.append("verified")
    write_ms = [e.get("write_ms") for e in writes if isinstance(e.get("write_ms"), (int, float))]
    if write_ms:
        mode = "async" if any(e.get("blocking") is False for e in writes) else "blocking"
        parts.append(f"mean write {sum(write_ms) / len(write_ms):.0f}ms {mode}")
    if failures:
        parts.append(f"{failures} FAILED")
    age = None
    newest_t = newest.get("t")
    if isinstance(newest_t, (int, float)):
        age = max(0.0, time.time() - newest_t)
        if live:
            parts.append(f"age {age:.0f}s")
    lines = ["ckpts   " + " · ".join(parts)]
    if live:
        ts = sorted(e.get("t") for e in marks if isinstance(e.get("t"), (int, float)))
        cadence = _median([b - a for a, b in zip(ts, ts[1:])])
        if cadence is None:
            # single checkpoint so far: fall back to the metric-interval pace
            mt = sorted(
                e.get("t") for e in events if e.get("event") == "metrics" and isinstance(e.get("t"), (int, float))
            )
            cadence = _median([b - a for a, b in zip(mt, mt[1:])])
        banner = no_recent_ckpt_banner(age, cadence)
        if banner is not None:
            lines.append(banner)
    return lines


def stale_params_banner(staleness: Any, budget: Any) -> Optional[str]:
    """The ``!! STALE-PARAMS`` banner line (or None): ONE owner for the
    threshold/wording so run_monitor's journal and endpoint modes can never
    drift.  Fires once the decoupled player has been fenced off fresh
    trainer params for more than HALF the staleness budget — the window in
    which escalation (emergency snapshot + halt) is approaching."""
    if not isinstance(staleness, (int, float)) or not isinstance(budget, (int, float)):
        return None
    if budget <= 0 or staleness <= budget / 2.0:
        return None
    return (
        f"!! STALE-PARAMS — player is {staleness:.0f} trainer updates behind "
        f"(budget {budget:.0f}); the fence halts the run when the budget is exhausted"
    )


def isolation_status_lines(events: List[Dict[str, Any]], live: bool = True) -> List[str]:
    """The param-staleness / rollback panel (run_monitor + journal_report
    share it): reject/rollback counters, the latest staleness gauge, and —
    live mode only — the ``!! STALE-PARAMS`` banner past half the budget.
    Empty when the run journaled no fencing activity (coupled runs, and
    decoupled runs that never rejected)."""
    rejects = [e for e in events if e.get("event") == "params_reject"]
    rollbacks = [e for e in events if e.get("event") == "rollback"]
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    last = (metrics_events[-1].get("metrics") or {}) if metrics_events else {}
    staleness = last.get("Telemetry/param_staleness")
    if not rejects and not rollbacks and not isinstance(staleness, (int, float)):
        return []
    parts = [f"{len(rejects)} rejects", f"{len(rollbacks)} rollbacks"]
    if isinstance(staleness, (int, float)):
        parts.append(f"staleness {staleness:.0f}")
    if rejects:
        newest = rejects[-1]
        parts.append(f"last reject: {newest.get('reason')} at iter {newest.get('iter_num')}")
    if rollbacks:
        retries_left = rollbacks[-1].get("retries_left")
        if retries_left is not None:
            parts.append(f"{retries_left} retries left")
    lines = ["fencing " + " · ".join(parts)]
    if live:
        budget = rejects[-1].get("budget") if rejects else None
        banner = stale_params_banner(staleness, budget)
        if banner is not None:
            lines.append(banner)
    return lines


def goodput_status_lines(events: List[Dict[str, Any]], live: bool = True) -> List[str]:
    """The run-state / goodput / stall panel (run_monitor + goodput_report
    share it).  ``live=False`` suppresses the ``!! STALLED`` banner — a
    post-mortem over a killed-while-stalled journal states the fact in the
    stall counters instead of shouting about a run that no longer exists.
    Empty when the run journaled no goodput telemetry (pre-ISSUE-8 runs)."""
    from sheeprl_tpu.diagnostics.goodput import journal_run_state, stalled_seconds

    metrics_events = [e for e in events if e.get("event") == "metrics"]
    # only render when the goodput layer actually ran: run_start/run_end alone
    # would map to a state, and a pre-ISSUE-8 journal must not grow a panel
    # implying the layer was active
    has_goodput = any(
        e.get("event") in ("state_change", "stall", "stall_end") for e in events
    ) or any("Telemetry/run_state" in (e.get("metrics") or {}) for e in metrics_events)
    if not has_goodput:
        return []
    freshest = journal_run_state(events)
    last = (metrics_events[-1].get("metrics") or {}) if metrics_events else {}
    lines: List[str] = []
    if freshest is not None:
        parts = [f"run-state {freshest[1]}"]
        goodput = last.get("Telemetry/goodput")
        if isinstance(goodput, (int, float)):
            parts.append(f"goodput {goodput:.1%}")
        ttfs = last.get("Telemetry/time_to_first_step")
        if isinstance(ttfs, (int, float)):
            parts.append(f"first step after {ttfs:.1f}s")
        lines.append("goodput " + " · ".join(parts))
    n_stalls = sum(1 for e in events if e.get("event") == "stall")
    if n_stalls:
        n_profiles = sum(
            1 for e in events if e.get("event") == "profile_capture" and e.get("status") == "ok"
        )
        stall_line = f"stalls  {n_stalls} · {stalled_seconds(events):.1f}s stalled"
        if n_profiles:
            stall_line += f" · {n_profiles} profile capture{'s' if n_profiles != 1 else ''}"
        lines.append(stall_line)
    if live and freshest is not None and freshest[1] == "stalled":
        age = time.time() - freshest[0]
        lines.append(f"!! STALLED — no progress journaled for {max(0.0, age):.0f}s")
    return lines


def health_status_lines(events: List[Dict[str, Any]], live: bool = True) -> List[str]:
    """The learn-health panel (run_monitor, journal_report --follow status
    block and tools/health_report.py share it): the latest
    ``Telemetry/health/*`` gauges, anomaly counters, and — ``live`` mode
    only — an ``!! ANOMALY`` banner while a detector is active.  ``live=False``
    (post-mortem, mirroring the goodput panel) states the open anomalies in
    the counters line instead of shouting about a run that no longer exists.
    Empty when the run journaled no learning-health telemetry."""
    from sheeprl_tpu.diagnostics.health import active_anomalies

    metrics_events = [e for e in events if e.get("event") == "metrics"]
    last = (metrics_events[-1].get("metrics") or {}) if metrics_events else {}
    has_health = any(e.get("event") in ("anomaly", "anomaly_end") for e in events) or any(
        k.startswith("Telemetry/health/") for k in last
    )
    if not has_health:
        return []
    lines: List[str] = []
    parts: List[str] = []
    for key, label, fmt in (
        ("Telemetry/health/grad_norm", "grad-norm", "{:.3g}"),
        ("Telemetry/health/update_ratio", "upd/w", "{:.2g}"),
        ("Telemetry/health/dead_frac", "dead", "{:.0%}"),
        ("Telemetry/health/value_ev", "value-ev", "{:.2f}"),
    ):
        value = last.get(key)
        if isinstance(value, (int, float)):
            parts.append(f"{label} {fmt.format(value)}")
    if parts:
        lines.append("health  " + " · ".join(parts))
    n_anomalies = sum(1 for e in events if e.get("event") == "anomaly")
    open_anomalies = active_anomalies(events)
    if n_anomalies:
        line = f"anomalies  {n_anomalies} fired"
        if open_anomalies:
            line += " · open: " + ", ".join(
                f"{e.get('kind')}({e.get('subject')})" for e in open_anomalies[:4]
            )
        lines.append(line)
    if live and open_anomalies:
        newest = open_anomalies[-1]
        lines.append(
            f"!! ANOMALY — {newest.get('kind')} on {newest.get('subject')} "
            f"(since step {newest.get('step')}; window in the journal)"
        )
    return lines


def memory_status_lines(events: List[Dict[str, Any]]) -> List[str]:
    """The HBM / transfers panel (run_monitor + memory_report share it):
    latest hbm in-use vs peak, buffer/host bytes, and the data-movement
    counters.  Empty when the run journaled no memory telemetry."""
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    last = (metrics_events[-1].get("metrics") or {}) if metrics_events else {}
    lines: List[str] = []
    hbm = last.get("Telemetry/hbm_bytes_in_use")
    if isinstance(hbm, (int, float)):
        breakdown = next((e for e in events if e.get("event") == "memory_breakdown"), None)
        source = (breakdown or {}).get("source", "")
        parts = [f"hbm {format_bytes(hbm)} in use"]
        peak = last.get("Telemetry/hbm_peak_bytes")
        if isinstance(peak, (int, float)) and peak > 0:
            parts[0] += f" / {format_bytes(peak)} peak"
        if source:
            parts[0] += f" ({source})"
        for key, label in (
            ("Telemetry/replay_host_bytes", "replay host"),
            ("Telemetry/replay_disk_bytes", "replay disk"),
            ("Telemetry/replay_device_bytes", "replay HBM"),
            ("Telemetry/host_rss_bytes", "rss"),
        ):
            value = last.get(key)
            if isinstance(value, (int, float)) and value > 0:
                parts.append(f"{label} {format_bytes(value)}")
        lines.append("memory  " + " · ".join(parts))
    n_xfer = sum(1 for e in events if e.get("event") == "host_transfer")
    n_miss = sum(int(e.get("n_leaves", 1)) for e in events if e.get("event") == "donation_miss")
    n_oom = sum(1 for e in events if e.get("event") == "oom")
    audit = next((e for e in events if e.get("event") == "sharding_audit"), None)
    n_flagged = len((audit or {}).get("flagged_replicated") or [])
    if n_xfer or n_miss or n_oom or n_flagged:
        lines.append(
            f"moves   {n_xfer} host transfers · {n_miss} donation-miss leaves · "
            f"{n_flagged} flagged replicated · {n_oom} ooms"
        )
    return lines


def sessions_full_banner(active: Any, capacity: Any) -> Optional[str]:
    """The ``!! SESSIONS-FULL`` banner line (or None): ONE owner for the
    threshold/wording so run_monitor's journal and endpoint modes can never
    drift.  Fires when the session slab is at capacity — every additional
    NEW session now evicts a resident one (journaled ``session_evict``) and
    the evictee replays its episode from a reset state if it comes back."""
    if not isinstance(active, (int, float)) or not isinstance(capacity, (int, float)):
        return None
    if capacity <= 0 or active < capacity:
        return None
    return (
        f"!! SESSIONS-FULL — {active:.0f}/{capacity:.0f} session slots resident; "
        "every new session evicts the LRU one (raise serving.sessions.capacity)"
    )


def serving_status_lines(events: List[Dict[str, Any]], live: bool = True) -> List[str]:
    """The serving panel (run_monitor's journal mode + journal_report share
    it): resident models with their last promoted step, session-layer
    counters, request-log rotation totals, and — live mode only — the
    ``!! SESSIONS-FULL`` banner off the latest metrics heartbeat's
    ``Telemetry/sessions/*`` gauges.  Empty for journals that never served
    (training runs)."""
    serve_start = next((e for e in reversed(events) if e.get("event") == "serve_start"), None)
    if serve_start is None:
        return []
    models = list(serve_start.get("models") or [])
    if not models:
        models = ["default"]
    promotes = [e for e in events if e.get("event") == "ckpt_promote"]
    rejects = [e for e in events if e.get("event") == "ckpt_reject"]
    lines: List[str] = []
    parts = [f"{len(models)} model{'s' if len(models) != 1 else ''}"]
    for name in models:
        step = next(
            (e.get("step") for e in reversed(promotes) if (e.get("model") or "default") == name),
            serve_start.get("ckpt_step") if name == (serve_start.get("model") or "default") else None,
        )
        parts.append(f"{name}@{step if step is not None else '?'}")
    if promotes or rejects:
        parts.append(f"{len(promotes)} promotes · {len(rejects)} rejects")
    lines.append("serving " + " · ".join(parts))
    evicts = [e for e in events if e.get("event") == "session_evict"]
    rotations = [e for e in events if e.get("event") == "request_log_rotate"]
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    last = (metrics_events[-1].get("metrics") or {}) if metrics_events else {}
    active = last.get("Telemetry/sessions/active")
    capacity = last.get("Telemetry/sessions/capacity")
    if evicts or isinstance(active, (int, float)):
        session_parts = []
        if isinstance(active, (int, float)):
            cap_s = f"/{capacity:.0f}" if isinstance(capacity, (int, float)) else ""
            session_parts.append(f"{active:.0f}{cap_s} active")
        session_parts.append(f"{len(evicts)} evictions")
        lines.append("session " + " · ".join(session_parts))
    if rotations:
        rows = sum(int(e.get("rows") or 0) for e in rotations if not e.get("dropped"))
        dropped = sum(int(e.get("rows") or 0) for e in rotations if e.get("dropped"))
        log_line = f"reqlog  {len(rotations)} shards · {rows} rows logged"
        if dropped:
            log_line += f" · {dropped} rows DROPPED (writer backlog)"
        lines.append(log_line)
    if live:
        banner = sessions_full_banner(active, capacity)
        if banner is not None:
            lines.append(banner)
    return lines


def slo_burn_banner(model: str, burn: Any) -> Optional[str]:
    """The ``!! SLO-BURN`` banner line (or None): ONE owner for the
    threshold/wording so run_monitor's journal and endpoint modes can never
    drift.  Fires while the rolling error-budget burn rate exceeds 1.0 —
    the point at which the ``serving.slo.objective`` is being spent faster
    than the window earns it back (howto/serving.md, "Tracing & SLOs")."""
    if not isinstance(burn, (int, float)) or burn <= 1.0:
        return None
    return (
        f"!! SLO-BURN — {model} is burning error budget at {burn:.2f}x "
        "(>1.0 means the latency objective fails if this traffic holds)"
    )


def serving_latency_lines(events: List[Dict[str, Any]], live: bool = True) -> List[str]:
    """The per-model latency-breakdown panel (run_monitor's journal AND
    endpoint modes share it — the endpoint mode synthesizes journal-shaped
    events from the labeled Prometheus series and feeds them here): queue /
    dispatch / scatter p50·p99 from the latest heartbeat's
    ``Telemetry/serve/*_ms_p50|p99`` gauges, the SLO burn gauge, and — live
    mode only — the ``!! SLO-BURN`` banner past 1.0 plus a ``!! SLOW-REQ``
    line naming the most recent journaled ``slow_request`` id.  Empty for
    journals with no serving latency telemetry."""
    last_by_model: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("event") != "metrics":
            continue
        metrics = e.get("metrics") or {}
        if any(k.startswith("Telemetry/serve/") for k in metrics):
            last_by_model[str(e.get("model") or "default")] = metrics
    lines: List[str] = []
    burns: Dict[str, Any] = {}
    for model in sorted(last_by_model):
        metrics = last_by_model[model]
        parts: List[str] = []
        for phase in ("queue", "dispatch", "scatter"):
            p50 = metrics.get(f"Telemetry/serve/{phase}_ms_p50")
            p99 = metrics.get(f"Telemetry/serve/{phase}_ms_p99")
            if isinstance(p50, (int, float)) and isinstance(p99, (int, float)):
                parts.append(f"{phase} {p50:.1f}/{p99:.1f}")
        burn = metrics.get("Telemetry/serve/slo_burn")
        if isinstance(burn, (int, float)):
            parts.append(f"burn {burn:.2f}")
            burns[model] = burn
        shed_wait = metrics.get("Telemetry/serve/shed_wait_ms")
        if isinstance(shed_wait, (int, float)):
            parts.append(f"shed-wait {shed_wait:.1f}ms")
        if parts:
            lines.append(f"latency {model}: " + " · ".join(parts) + "  (p50/p99 ms)")
    if live:
        for model in sorted(burns):
            banner = slo_burn_banner(model, burns[model])
            if banner is not None:
                lines.append(banner)
        slow = next((e for e in reversed(events) if e.get("event") == "slow_request"), None)
        if slow is not None:
            total = slow.get("total_ms")
            took = f" took {total}ms" if isinstance(total, (int, float)) else ""
            lines.append(
                f"!! SLOW-REQ — last slow request {slow.get('request_id')} on "
                f"{slow.get('model') or 'default'}{took} "
                "(full phase breakdown in the journal)"
            )
    return lines


def format_memory_breakdown(event: Dict[str, Any]) -> str:
    """The ``memory_breakdown`` journal event as a footprint table."""
    header = "static footprint breakdown" + (f" (source: {event.get('source', '?')})" if event.get("source") else "")
    if event.get("fsdp_axis_size"):
        header += f" [fsdp axis={event['fsdp_axis_size']}]"
    lines = [header]
    components = event.get("components") or {}
    per_device = event.get("components_per_device") or {}
    total = 0
    total_per_device = 0
    for name, size in sorted(components.items(), key=lambda kv: -(kv[1] if isinstance(kv[1], (int, float)) else 0)):
        if not isinstance(size, (int, float)) or size <= 0:
            continue
        total += size
        row = f"  {name:<24s} {format_bytes(size):>12s}"
        dev = per_device.get(name)
        total_per_device += dev if isinstance(dev, (int, float)) else size
        if isinstance(dev, (int, float)):
            row += f"  ({format_bytes(dev)}/device)"
        lines.append(row)
    total_row = f"  {'total (components)':<24s} {format_bytes(total):>12s}"
    if per_device:
        total_row += f"  ({format_bytes(total_per_device)}/device)"
    lines.append(total_row)
    for fn, analysis in sorted((event.get("executables") or {}).items()):
        lines.append(f"  executable {fn}:")
        for key in ("argument_bytes", "output_bytes", "temp_bytes", "generated_code_bytes", "alias_bytes"):
            if key in analysis:
                lines.append(f"    {key.replace('_bytes', ''):<22s} {format_bytes(analysis[key]):>12s}")
    for row in event.get("device_memory") or []:
        lines.append(
            f"  device {row.get('device')}: {format_bytes(row.get('bytes_in_use'))} in use"
            + (f", {format_bytes(row.get('peak_bytes_in_use'))} peak" if row.get("peak_bytes_in_use") else "")
        )
    live = event.get("live_arrays")
    if live:
        lines.append(
            f"  live jax arrays: {live.get('n_arrays')} arrays, {format_bytes(live.get('bytes_in_use'))}"
            f" (largest {format_bytes(live.get('largest_alloc_bytes'))})"
        )
    if event.get("host_rss_bytes") is not None:
        lines.append(f"  process RSS: {format_bytes(event['host_rss_bytes'])}")
    return "\n".join(lines)


def format_sharding_audit(event: Dict[str, Any]) -> str:
    """The ``sharding_audit`` journal event as a per-leaf table (largest
    per-device cost first; replicated leaves marked)."""
    lines = [
        "sharding audit ({fn}): {n} leaves, {total} total, {per_dev}/device".format(
            fn=event.get("fn", "?"),
            n=event.get("n_leaves", "?"),
            total=format_bytes(event.get("total_bytes")),
            per_dev=format_bytes(event.get("total_bytes_per_device")),
        )
    ]
    flagged = set(event.get("flagged_replicated") or [])
    for row in event.get("rows") or []:
        mark = " REPLICATED!" if row.get("path") in flagged else (" repl" if row.get("replicated") else "")
        lines.append(
            "  {per_dev:>12s}/dev  {dtype:<10s} {shape:<18s} x{nd}  {path}{mark}".format(
                per_dev=format_bytes(row.get("bytes_per_device")),
                dtype=str(row.get("dtype", "?")),
                shape=str(row.get("shape", "?")),
                nd=row.get("n_devices", 1),
                path=row.get("path", "?"),
                mark=mark,
            )
        )
    if event.get("hint"):
        lines.append(f"  hint: {event['hint']}")
    return "\n".join(lines)


def format_fsdp_shard_map(event: Dict[str, Any]) -> str:
    """The ``fsdp_shard_map`` journal event: how the partition rule laid out
    each train-state tree over the ``model`` mesh axis."""
    lines = [
        "fsdp shard map: axis_size={axis} min_shard_bytes={floor}".format(
            axis=event.get("axis_size", "?"), floor=event.get("min_shard_bytes", "?")
        )
    ]
    for name, row in sorted((event.get("trees") or {}).items()):
        lines.append(
            "  {name:<12s} {sharded}/{leaves} leaves sharded ({repl} replicated) · "
            "{total} global → {per_dev}/device".format(
                name=name,
                sharded=row.get("sharded", "?"),
                leaves=row.get("leaves", "?"),
                repl=row.get("replicated", "?"),
                total=format_bytes(row.get("bytes")),
                per_dev=format_bytes(row.get("bytes_per_device")),
            )
        )
    return "\n".join(lines)
