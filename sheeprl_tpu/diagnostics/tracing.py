"""Step-phase tracing: Chrome-trace (Trace Event Format) span timers.

Complements the existing whole-run ``jax.profiler`` gate (cfg.metric.profiler)
which captures *device* activity: these spans time the **host-side phases** of
the training loops — rollout, buffer-sample, train dispatch, checkpoint — and
serialize them as Trace Event ``"X"`` (complete) events, one JSON object per
line inside a streaming array.  Open the file in ``chrome://tracing`` or
https://ui.perfetto.dev.

Crash behaviour mirrors the journal: every event is flushed as written and
the closing ``]`` only lands in :meth:`PhaseTracer.close` — both Chrome and
Perfetto explicitly accept a truncated (unterminated) trace array, so a
SIGKILL'd run still leaves a loadable trace.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

TRACE_NAME = "trace.json"

# Span names the training loops emit (free-form names are fine too; these are
# the vocabulary howto/diagnostics.md documents).  ``env_step_async`` times
# issuing the split-phase env dispatch and ``env_wait`` the blocking collect —
# in Perfetto the gap between an ``env_step_async`` span and its iteration's
# ``env_wait`` span is exactly the env time hidden behind device dispatch, so
# the async env pipeline's overlap (howto/async_envs.md) is directly visible.
KNOWN_PHASES = (
    "rollout",
    "env_step_async",
    "env_wait",
    "buffer-sample",
    "train",
    "checkpoint",
)


class PhaseTracer:
    """Streaming Trace-Event writer with a ``span`` context manager."""

    def __init__(self, path: str, pid: int = 0, flush_every: int = 1):
        self.path = str(path)
        self._pid = int(pid)
        self._flush_every = max(1, int(flush_every))
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        self._fp = open(self.path, "w", encoding="utf-8")
        self._fp.write("[\n")
        self._first = True
        self._count = 0
        self._closed = False
        self._lock = threading.Lock()
        # perf_counter origin so ts deltas are monotonic within the run
        self._t0_ns = time.perf_counter_ns()
        self._emit(
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "tid": 0,
                "args": {"name": f"sheeprl_tpu host {self._pid}"},
            }
        )

    def _now_us(self) -> int:
        return (time.perf_counter_ns() - self._t0_ns) // 1000

    def _emit(self, event: Dict[str, Any]) -> None:
        if self._closed:
            return
        with self._lock:
            if not self._first:
                self._fp.write(",\n")
            self._first = False
            self._fp.write(json.dumps(event, separators=(",", ":")))
            self._count += 1
            if self._count % self._flush_every == 0:
                self._fp.flush()

    @contextmanager
    def span(self, name: str, **args: Any):
        """Time a phase as a complete ("X") event."""
        start = self._now_us()
        try:
            yield
        finally:
            self._emit(
                {
                    "name": str(name),
                    "cat": "phase",
                    "ph": "X",
                    "ts": start,
                    "dur": max(0, self._now_us() - start),
                    "pid": self._pid,
                    "tid": threading.get_ident() % (1 << 31),
                    **({"args": args} if args else {}),
                }
            )

    def instant(self, name: str, **args: Any) -> None:
        """Mark a point event (checkpoint written, divergence detected...)."""
        self._emit(
            {
                "name": str(name),
                "cat": "event",
                "ph": "i",
                "s": "g",  # global-scope instant: full-height line in the UI
                "ts": self._now_us(),
                "pid": self._pid,
                "tid": threading.get_ident() % (1 << 31),
                **({"args": args} if args else {}),
            }
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._fp.write("\n]\n")
            self._fp.flush()
        except ValueError:  # pragma: no cover - interpreter teardown
            pass
        self._fp.close()


class NullTracer:
    """No-op stand-in when tracing is disabled or on non-zero ranks."""

    path: Optional[str] = None

    @contextmanager
    def span(self, name: str, **args: Any):
        yield

    def instant(self, name: str, **args: Any) -> None:
        pass

    def close(self) -> None:
        pass
