"""Step-phase tracing: Chrome-trace (Trace Event Format) span timers.

Complements the existing whole-run ``jax.profiler`` gate (cfg.metric.profiler)
which captures *device* activity: these spans time the **host-side phases** of
the training loops — rollout, buffer-sample, train dispatch, checkpoint — and
serialize them as Trace Event ``"X"`` (complete) events, one JSON object per
line inside a streaming array.  Open the file in ``chrome://tracing`` or
https://ui.perfetto.dev.

Crash behaviour mirrors the journal: every event is flushed as written and
the closing ``]`` only lands in :meth:`PhaseTracer.close` — both Chrome and
Perfetto explicitly accept a truncated (unterminated) trace array, so a
SIGKILL'd run still leaves a loadable trace.

Cross-process correlation (ISSUE 3): every trace file opens with a
``clock_sync`` instant carrying the run id, rank, role and the Unix-epoch
microsecond corresponding to ``ts=0`` of this file's monotonic clock.
``tools/trace_report.py`` uses those anchors to merge traces written by
different processes (multi-host ranks, or a decoupled player/trainer pair)
onto one absolute timeline.

Growth cap: ``max_events`` rotates the file (``trace.json`` →
``trace.json.1`` → ``.2`` …, keeping ``rotate_keep`` rotated generations).
Each rotated generation is a *complete*, Perfetto-loadable JSON array with its
own metadata preamble, and the monotonic ``ts`` values continue across
generations, so rotated files can be merged back into one timeline.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

TRACE_NAME = "trace.json"
# The serving tier writes its own file next to the serving journal so the
# dispatcher/HTTP-handler spans never interleave with a co-located training
# trace; tools/trace_report.py merges both onto one absolute timeline via
# their clock_sync anchors (howto/serving.md "Tracing & SLOs").
TRACE_SERVE_NAME = "trace_serve.json"

# Span names the training loops and the serving tier emit (free-form names
# are fine too; these are the vocabulary howto/diagnostics.md documents).
# ``env_step_async`` times issuing the split-phase env dispatch and
# ``env_wait`` the blocking collect — in Perfetto the gap between an
# ``env_step_async`` span and its iteration's ``env_wait`` span is exactly
# the env time hidden behind device dispatch, so the async env pipeline's
# overlap (howto/async_envs.md) is directly visible.  The ``serve-*`` phases
# tile one /act request: queue-wait → batch formation → (session checkout
# inside) AOT dispatch → result scatter → response serialization, plus the
# request-log writer thread's shard flush.  tools/lint TRC501 pins every
# span-name literal in serving/ and the loops to this tuple.
KNOWN_PHASES = (
    "rollout",
    "env_step_async",
    "env_wait",
    "buffer-sample",
    "train",
    "checkpoint",
    "serve-queue",
    "serve-batch-form",
    "serve-session-checkout",
    "serve-dispatch",
    "serve-scatter",
    "serve-serialize",
    "serve-request-log",
)


class PhaseTracer:
    """Streaming Trace-Event writer with a ``span`` context manager."""

    def __init__(
        self,
        path: str,
        pid: int = 0,
        flush_every: int = 1,
        max_events: Optional[int] = None,
        rotate_keep: int = 2,
        run_id: Optional[str] = None,
        role: Optional[str] = None,
    ):
        self.path = str(path)
        self._pid = int(pid)
        self._flush_every = max(1, int(flush_every))
        self._max_events = int(max_events) if max_events else None
        self._rotate_keep = max(1, int(rotate_keep))
        self.run_id = run_id
        self.role = role or "main"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        self._count = 0
        self._closed = False
        self._lock = threading.Lock()
        # perf_counter origin so ts deltas are monotonic within the run; the
        # paired wall-clock reading anchors ts=0 on the Unix epoch for the
        # cross-process merge (taken back-to-back: sub-ms anchor skew)
        self._t0_ns = time.perf_counter_ns()
        self._epoch_t0_us = time.time_ns() // 1000
        self._fp = open(self.path, "w", encoding="utf-8")
        self._fp.write("[\n")
        self._first = True
        self._write_preamble()

    def _preamble_events(self):
        return (
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "tid": 0,
                "args": {"name": f"sheeprl_tpu {self.role} rank{self._pid}"},
            },
            {
                "name": "clock_sync",
                "cat": "meta",
                "ph": "i",
                "s": "g",
                "ts": self._now_us(),
                "pid": self._pid,
                "tid": 0,
                "args": {
                    "run_id": self.run_id,
                    "rank": self._pid,
                    "role": self.role,
                    # Unix-epoch µs at this file's ts=0: merge key for
                    # tools/trace_report.py (abs_us = epoch_t0_us + ts)
                    "epoch_t0_us": self._epoch_t0_us,
                },
            },
        )

    def _write_preamble(self) -> None:
        for event in self._preamble_events():
            self._emit(event)

    def _now_us(self) -> int:
        return (time.perf_counter_ns() - self._t0_ns) // 1000

    def _emit(self, event: Dict[str, Any]) -> None:
        if self._closed:
            return
        with self._lock:
            if self._closed:  # re-check: close() may have won the lock race
                return
            if not self._first:
                self._fp.write(",\n")
            self._first = False
            self._fp.write(json.dumps(event, separators=(",", ":")))
            self._count += 1
            if self._count % self._flush_every == 0:
                self._fp.flush()
            if self._max_events is not None and self._count >= self._max_events:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Close the current generation as a complete array and start a new
        one (caller holds the lock).  ``ts`` keeps counting from the same
        origin, so generations concatenate into one coherent timeline."""
        try:
            self._fp.write("\n]\n")
            self._fp.flush()
        finally:
            self._fp.close()
        for i in range(self._rotate_keep - 1, 0, -1):
            older = f"{self.path}.{i}"
            if os.path.exists(older):
                os.replace(older, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        # drop any generation beyond the keep budget
        overflow = f"{self.path}.{self._rotate_keep + 1}"
        if os.path.exists(overflow):
            os.remove(overflow)
        self._fp = open(self.path, "w", encoding="utf-8")
        self._fp.write("[\n")
        self._first = True
        self._count = 0
        # new generation gets its own preamble (same run/clock identity) so
        # it is independently loadable; written directly — the lock is held
        self._write_preamble_direct()

    def _write_preamble_direct(self) -> None:
        """Write the metadata preamble straight to the (fresh) file while the
        lock is already held."""
        for event in self._preamble_events():
            if not self._first:
                self._fp.write(",\n")
            self._first = False
            self._fp.write(json.dumps(event, separators=(",", ":")))
            self._count += 1
        self._fp.flush()

    @contextmanager
    def span(self, name: str, **args: Any):
        """Time a phase as a complete ("X") event."""
        start = self._now_us()
        try:
            yield
        finally:
            self._emit(
                {
                    "name": str(name),
                    "cat": "phase",
                    "ph": "X",
                    "ts": start,
                    "dur": max(0, self._now_us() - start),
                    "pid": self._pid,
                    "tid": threading.get_ident() % (1 << 31),
                    **({"args": args} if args else {}),
                }
            )

    def now_us(self) -> int:
        """Current trace-clock reading (µs since this tracer's ts=0).

        Callers that can only attribute a phase after the fact (the batcher
        learns a request's queue-wait when the dispatcher pops it) capture
        timestamps with this and emit retroactively via :meth:`emit_complete`.
        """
        return self._now_us()

    def emit_complete(self, name: str, ts_us: int, dur_us: int, **args: Any) -> None:
        """Emit a complete ("X") event at explicit trace-clock coordinates."""
        self._emit(
            {
                "name": str(name),
                "cat": "phase",
                "ph": "X",
                "ts": int(ts_us),
                "dur": max(0, int(dur_us)),
                "pid": self._pid,
                "tid": threading.get_ident() % (1 << 31),
                **({"args": args} if args else {}),
            }
        )

    def instant(self, name: str, **args: Any) -> None:
        """Mark a point event (checkpoint written, divergence detected...)."""
        self._emit(
            {
                "name": str(name),
                "cat": "event",
                "ph": "i",
                "s": "g",  # global-scope instant: full-height line in the UI
                "ts": self._now_us(),
                "pid": self._pid,
                "tid": threading.get_ident() % (1 << 31),
                **({"args": args} if args else {}),
            }
        )

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._fp.write("\n]\n")
                self._fp.flush()
            except ValueError:  # pragma: no cover - interpreter teardown
                pass
            self._fp.close()


class NullTracer:
    """No-op stand-in when tracing is disabled or on non-zero ranks."""

    path: Optional[str] = None

    @contextmanager
    def span(self, name: str, **args: Any):
        yield

    def now_us(self) -> int:
        return 0

    def emit_complete(self, name: str, ts_us: int, dur_us: int, **args: Any) -> None:
        pass

    def instant(self, name: str, **args: Any) -> None:
        pass

    def close(self) -> None:
        pass
