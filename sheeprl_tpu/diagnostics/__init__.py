"""Run-health & observability subsystem.

Three pillars behind one facade (ISSUE 1 tentpole):

* :mod:`~sheeprl_tpu.diagnostics.journal` — crash-safe JSONL run journal
  (write-ahead metric/event log; makes TensorBoard archaeology and the
  reward-recovery toolchain unnecessary for new runs);
* :mod:`~sheeprl_tpu.diagnostics.sentinel` — jit-compatible NaN/divergence
  sentinel (``warn`` / ``skip_update`` / ``halt``) + host-side rolling
  divergence detector;
* :mod:`~sheeprl_tpu.diagnostics.tracing` — step-phase Chrome-trace spans
  (rollout / buffer-sample / train / checkpoint) viewable in Perfetto,
  complementing the device-side ``jax.profiler`` gate.

The facade is constructed once in ``cli.run_algorithm`` from the
``configs/diagnostics/`` group and attached to the :class:`Runtime`; training
loops pick it up through ``sheeprl_tpu.utils.utils.get_diagnostics`` and the
rank-0 logger proxy journals every aggregated metric automatically, so
non-flagship algorithms inherit journaling without loop changes.
"""

from __future__ import annotations

import hashlib
import os
import warnings
from contextlib import nullcontext
from typing import Any, Dict, Mapping, Optional

from sheeprl_tpu.diagnostics.journal import JOURNAL_NAME, RunJournal, find_journal, iter_journal, read_journal
from sheeprl_tpu.diagnostics.sentinel import (
    DivergenceDetector,
    SentinelHalt,
    SentinelSpec,
    poison_tree,
    sentinel_spec,
)
from sheeprl_tpu.diagnostics.tracing import TRACE_NAME, NullTracer, PhaseTracer

__all__ = [
    "Diagnostics",
    "DivergenceDetector",
    "JOURNAL_NAME",
    "NullTracer",
    "PhaseTracer",
    "RunJournal",
    "SentinelHalt",
    "SentinelSpec",
    "TRACE_NAME",
    "build_diagnostics",
    "config_hash",
    "find_journal",
    "iter_journal",
    "read_journal",
    "sentinel_spec",
]


def config_hash(cfg: Mapping[str, Any]) -> str:
    """Stable short hash of the composed run config (journaled at run_start,
    so any journal can be matched to the exact configuration that made it)."""
    import yaml

    plain = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
    return hashlib.sha256(yaml.safe_dump(plain, sort_keys=True).encode()).hexdigest()[:16]


class Diagnostics:
    """Facade over journal + sentinel + tracer with rank-0 gating.

    Construct via :func:`build_diagnostics`; call :meth:`open` once the run's
    log dir exists (``get_diagnostics`` does both).  Every method is a no-op
    until opened — and stays one on non-rank-0 hosts or when
    ``diagnostics.enabled=False`` — so hook calls in the training loops are
    unconditional.
    """

    def __init__(self, cfg: Optional[Mapping[str, Any]] = None):
        self._cfg = cfg
        diag_cfg = (cfg or {}).get("diagnostics") or {}
        self.enabled = bool(diag_cfg.get("enabled", False))
        self._journal_cfg = diag_cfg.get("journal") or {}
        self._trace_cfg = diag_cfg.get("trace") or {}
        self.sentinel: SentinelSpec = sentinel_spec(cfg or {})
        div_cfg = (diag_cfg.get("sentinel") or {}).get("divergence") or {}
        self._detector: Optional[DivergenceDetector] = None
        if self.enabled and div_cfg.get("enabled", True):
            self._detector = DivergenceDetector(
                window=int(div_cfg.get("window", 20)),
                min_points=int(div_cfg.get("min_points", 5)),
                loss_explosion_ratio=float(div_cfg.get("loss_explosion_ratio", 10.0) or 0.0),
                entropy_key=div_cfg.get("entropy_key"),
                entropy_floor=div_cfg.get("entropy_floor"),
            )
        self.journal: Optional[RunJournal] = None
        self.tracer = NullTracer()
        self.log_dir: Optional[str] = None
        self._rank_zero = True
        self._closed = False

    # -- lifecycle ---------------------------------------------------------
    def open(self, log_dir: str, rank_zero: bool = True) -> "Diagnostics":
        """Open journal/tracer inside ``log_dir`` (idempotent, rank-0 only)."""
        if not self.enabled or self.log_dir is not None:
            return self
        self.log_dir = str(log_dir)
        self._rank_zero = bool(rank_zero)
        if not self._rank_zero:
            return self
        if self._journal_cfg.get("enabled", True):
            self.journal = RunJournal(
                os.path.join(self.log_dir, JOURNAL_NAME),
                fsync_every=int(self._journal_cfg.get("fsync_every", 1)),
            )
        if self._trace_cfg.get("enabled", False):
            trace_path = self._trace_cfg.get("path") or os.path.join(self.log_dir, TRACE_NAME)
            import jax

            self.tracer = PhaseTracer(trace_path, pid=jax.process_index())
        if self.journal is not None:
            cfg = self._cfg or {}
            self.journal.write(
                "run_start",
                config_hash=config_hash(cfg),
                algo=(cfg.get("algo") or {}).get("name"),
                env=(cfg.get("env") or {}).get("id"),
                seed=cfg.get("seed"),
                exp_name=cfg.get("exp_name"),
                run_name=cfg.get("run_name"),
                log_dir=self.log_dir,
                sentinel_policy=self.sentinel.policy if self.sentinel.enabled else None,
            )
        return self

    def close(self, status: str = "completed") -> None:
        if self._closed:
            return
        self._closed = True
        if self.journal is not None:
            self.journal.write("run_end", status=status)
            self.journal.close()
        self.tracer.close()

    # -- tracing -----------------------------------------------------------
    def span(self, name: str, **args: Any):
        """Phase span context manager (no-op unless tracing is open)."""
        if isinstance(self.tracer, NullTracer):
            return nullcontext()
        return self.tracer.span(name, **args)

    # -- journal hooks -----------------------------------------------------
    def log_metrics(self, step: Optional[int], metrics: Mapping[str, Any]) -> None:
        """Journal one aggregated-metrics interval + run divergence checks.

        Called by the rank-0 logger proxy right after the metrics went to
        TensorBoard/W&B, so the journal mirrors exactly what was logged.
        """
        if not metrics:
            return
        if self.journal is not None:
            self.journal.write("metrics", step=step, metrics=dict(metrics))
        if self._detector is not None and self._rank_zero:
            for event in self._detector.observe(step, metrics):
                self._journal_divergence(event)

    def on_checkpoint(self, step: Optional[int], path: str) -> None:
        if self.journal is not None:
            self.journal.write("checkpoint", step=step, path=str(path))
        self.tracer.instant("checkpoint", step=step)

    def _journal_divergence(self, event: Dict[str, Any]) -> None:
        if self.journal is not None:
            kind = event.pop("kind", "unknown")
            step = event.pop("step", None)
            self.journal.write("divergence", kind=kind, step=step, **event)
            self.tracer.instant(f"divergence:{kind}", step=step)

    # -- sentinel host side ------------------------------------------------
    def on_update(self, step: Optional[int], stats: Mapping[str, Any], nonfinite: float = 0.0) -> None:
        """Digest one (fetched) train-step metric bundle.

        ``nonfinite`` is the in-graph count of optimizer steps whose
        loss/grad-norm finiteness flag tripped.  Journals a structured
        ``divergence`` event and applies the configured policy: ``warn``
        warns, ``skip_update`` already discarded the bad update in-graph (so
        this only records it), ``halt`` raises :class:`SentinelHalt`.
        """
        if not (self.enabled and self.sentinel.enabled):
            return
        nonfinite = float(nonfinite)
        if nonfinite <= 0:
            return
        self._journal_divergence(
            {
                "kind": "nonfinite_update",
                "step": step,
                "nonfinite_steps": nonfinite,
                "policy": self.sentinel.policy,
                **{k: v for k, v in stats.items()},
            }
        )
        if self.sentinel.policy == "halt":
            self.close("halted")
            raise SentinelHalt(
                f"non-finite training update at step {step} "
                f"(nonfinite optimizer steps this interval: {nonfinite:g}); "
                "diagnostics.sentinel.policy=halt"
            )
        if self.sentinel.policy == "warn" and self._rank_zero:
            warnings.warn(
                f"Sentinel: non-finite training update at step {step} "
                f"({nonfinite:g} optimizer steps); params may be corrupted "
                "(diagnostics.sentinel.policy=warn)",
                RuntimeWarning,
            )

    def observe_rows(self, step: Optional[int], names, rows) -> None:
        """Sentinel digest for the Dreamer metric drain: ``rows`` is a list of
        per-gradient-step metric vectors (ordered as ``names``) fetched at the
        log boundary.  Counts rows with any non-finite entry; under
        ``skip_update`` those steps were already discarded in-graph."""
        if not (self.enabled and self.sentinel.enabled) or not rows:
            return
        import numpy as np

        arr = np.asarray(rows, dtype=np.float64)
        bad = ~np.isfinite(arr).all(axis=tuple(range(1, arr.ndim)))
        n_bad = int(bad.sum())
        if n_bad:
            first_bad = arr[bad][0]
            stats = {str(n): float(v) for n, v in zip(names, first_bad)}
            self.on_update(step, stats, nonfinite=n_bad)

    # -- fault injection (tests / chaos drills) ----------------------------
    def maybe_inject_nan(self, iter_num: int, tree):
        """Poison a train batch at the configured iteration
        (``diagnostics.sentinel.inject_nan_iter``) — the documented way to
        drill the sentinel path end-to-end without doctoring model code."""
        inject = self.sentinel.inject_nan_iter
        if inject is None or int(iter_num) != inject:
            return tree
        if self.journal is not None:
            self.journal.write("fault_injection", iter_num=int(iter_num))
        return poison_tree(tree)


def build_diagnostics(cfg: Optional[Mapping[str, Any]]) -> Diagnostics:
    """Construct the facade from a composed run config (never raises on a
    missing ``diagnostics`` section — direct entrypoint callers like bench.py
    simply get a disabled facade)."""
    return Diagnostics(cfg)
