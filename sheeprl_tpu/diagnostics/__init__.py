"""Run-health & observability subsystem.

Seven pillars behind one facade (ISSUE 1 tentpole + ISSUE 3 telemetry layer +
ISSUE 4 memory layer + ISSUE 8 run-lifecycle layer + ISSUE 9 learning-dynamics
layer):

* :mod:`~sheeprl_tpu.diagnostics.journal` — crash-safe JSONL run journal
  (write-ahead metric/event log; makes TensorBoard archaeology and the
  reward-recovery toolchain unnecessary for new runs);
* :mod:`~sheeprl_tpu.diagnostics.sentinel` — jit-compatible NaN/divergence
  sentinel (``warn`` / ``skip_update`` / ``halt``) + host-side rolling
  divergence detector;
* :mod:`~sheeprl_tpu.diagnostics.tracing` — step-phase Chrome-trace spans
  (rollout / buffer-sample / train / checkpoint) viewable in Perfetto,
  complementing the device-side ``jax.profiler`` gate, with run-id/rank/role
  clock anchors so multi-process traces merge (``tools/trace_report.py``);
* :mod:`~sheeprl_tpu.diagnostics.telemetry` — performance telemetry: a
  recompilation watchdog over the instrumented jitted steps, MFU/goodput
  accounting from compiled-step ``cost_analysis()`` FLOPs, phase-level
  wall-clock attribution, and (opt-in) a live rank-0 ``/metrics`` +
  ``/healthz`` HTTP endpoint (:mod:`~sheeprl_tpu.diagnostics.metrics_server`);
* :mod:`~sheeprl_tpu.diagnostics.memory` — memory & data-movement telemetry
  (ISSUE 4): per-interval HBM gauges + a static footprint breakdown, the
  ``diagnostics.transfers`` host-transfer guard around the instrumented
  dispatches, a first-dispatch donation/sharding audit, and OOM forensics
  journaled before a ``RESOURCE_EXHAUSTED`` takes the process down
  (``tools/memory_report.py`` renders the tables);
* :mod:`~sheeprl_tpu.diagnostics.goodput` — run lifecycle & goodput
  (ISSUE 8): a run-state machine (``starting → compiling → training /
  env_wait / checkpointing / stalled → ended``) driven by the hooks above, a
  heartbeat stall watchdog journaling fsync'd ``stall`` forensics
  (all-thread stacks, optional ``jax.profiler`` auto-capture), and the live
  ``Telemetry/run_state`` / ``Telemetry/goodput`` /
  ``Telemetry/time_to_first_step`` gauges (``tools/goodput_report.py``
  groups a resumed run's ``version_N`` segments post-mortem);
* :mod:`~sheeprl_tpu.diagnostics.health` — learning-dynamics observability
  (ISSUE 9): jit-compatible per-module grad/update/param statistics riding
  the guarded train steps' existing output fetch (zero extra device syncs),
  rolling-window anomaly detectors (entropy collapse, value-EV floor,
  update/weight-ratio band, loss plateau, dead gradients) journaling
  flood-controlled ``anomaly``/``anomaly_end`` events, and the live
  ``Telemetry/health/*`` gauges (``tools/health_report.py`` renders the
  post-mortem; ``tools/health_diff.py`` gates cross-run regressions).

The facade is constructed once in ``cli.run_algorithm`` from the
``configs/diagnostics/`` group and attached to the :class:`Runtime`; training
loops pick it up through ``sheeprl_tpu.utils.utils.get_diagnostics`` and the
rank-0 logger proxy journals every aggregated metric automatically — augmented
with the live ``Telemetry/*`` gauges — so non-flagship algorithms inherit
journaling *and* perf telemetry without loop changes.
"""

from __future__ import annotations

import hashlib
import os
import warnings
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Mapping, Optional

from sheeprl_tpu.diagnostics.goodput import GoodputMonitor
from sheeprl_tpu.diagnostics.health import HealthMonitor, HealthSpec, health_spec, health_stats
from sheeprl_tpu.diagnostics.journal import (
    JOURNAL_NAME,
    RunJournal,
    collect_journals,
    find_journal,
    iter_journal,
    read_journal,
)
from sheeprl_tpu.diagnostics.memory import MEMORY_EVENTS, MemoryMonitor, tree_bytes
from sheeprl_tpu.diagnostics.sentinel import (
    DivergenceDetector,
    SentinelHalt,
    SentinelSpec,
    poison_tree,
    sentinel_spec,
)
from sheeprl_tpu.diagnostics.telemetry import TELEMETRY_PREFIX, Telemetry, monitoring_available
from sheeprl_tpu.diagnostics.tracing import TRACE_NAME, NullTracer, PhaseTracer

__all__ = [
    "Diagnostics",
    "DivergenceDetector",
    "GoodputMonitor",
    "HealthMonitor",
    "HealthSpec",
    "JOURNAL_NAME",
    "MEMORY_EVENTS",
    "MemoryMonitor",
    "NullTracer",
    "PhaseTracer",
    "RunJournal",
    "SentinelHalt",
    "SentinelSpec",
    "TELEMETRY_PREFIX",
    "TRACE_NAME",
    "Telemetry",
    "build_diagnostics",
    "collect_journals",
    "config_hash",
    "find_journal",
    "health_spec",
    "health_stats",
    "iter_journal",
    "read_journal",
    "sentinel_spec",
    "tree_bytes",
]


def config_hash(cfg: Mapping[str, Any]) -> str:
    """Stable short hash of the composed run config (journaled at run_start,
    so any journal can be matched to the exact configuration that made it)."""
    import yaml

    plain = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
    return hashlib.sha256(yaml.safe_dump(plain, sort_keys=True).encode()).hexdigest()[:16]


def run_id_of(log_dir: str) -> str:
    """Correlation id shared by every process of a run: the tail of the
    (broadcast) log dir — ``<root_dir>/<run_name>/version_N`` — which is the
    one string all ranks already agree on without extra rendezvous."""
    parts = [p for p in os.path.normpath(str(log_dir)).split(os.sep) if p not in ("", ".")]
    return "/".join(parts[-3:]) if parts else str(log_dir)


class Diagnostics:
    """Facade over journal + sentinel + tracer + telemetry with rank-0 gating.

    Construct via :func:`build_diagnostics`; call :meth:`open` once the run's
    log dir exists (``get_diagnostics`` does both).  Every method is a no-op
    until opened — and stays one on non-rank-0 hosts or when
    ``diagnostics.enabled=False`` — so hook calls in the training loops are
    unconditional.
    """

    def __init__(self, cfg: Optional[Mapping[str, Any]] = None):
        self._cfg = cfg
        diag_cfg = (cfg or {}).get("diagnostics") or {}
        self.enabled = bool(diag_cfg.get("enabled", False))
        self._journal_cfg = diag_cfg.get("journal") or {}
        self._trace_cfg = diag_cfg.get("trace") or {}
        self.compilation_cache_dir = diag_cfg.get("compilation_cache_dir") or None
        self.role = str(diag_cfg.get("role") or "main")
        self.sentinel: SentinelSpec = sentinel_spec(cfg or {})
        div_cfg = (diag_cfg.get("sentinel") or {}).get("divergence") or {}
        self._detector: Optional[DivergenceDetector] = None
        if self.enabled and div_cfg.get("enabled", True):
            self._detector = DivergenceDetector(
                window=int(div_cfg.get("window", 20)),
                min_points=int(div_cfg.get("min_points", 5)),
                loss_explosion_ratio=float(div_cfg.get("loss_explosion_ratio", 10.0) or 0.0),
                entropy_key=div_cfg.get("entropy_key"),
                entropy_floor=div_cfg.get("entropy_floor"),
            )
        self.telemetry: Optional[Telemetry] = None
        if self.enabled:
            telemetry = Telemetry(cfg or {})
            if telemetry.enabled:
                self.telemetry = telemetry
        self.memory: Optional[MemoryMonitor] = None
        if self.enabled:
            memory = MemoryMonitor(cfg or {})
            if memory.enabled:
                self.memory = memory
                if self.telemetry is not None:
                    # instrumented dispatches route through the monitor's
                    # guarded scope (transfer guard / audits / OOM forensics)
                    self.telemetry._memory = memory
                elif memory.transfer_mode != "off" or memory._inject_transfer_iter is not None or memory._inject_oom_iter is not None:
                    # the guard/audits/forensics live at the instrumented
                    # dispatch boundary, which telemetry provides — a config
                    # that asks for enforcement without it must not be
                    # silently inert
                    warnings.warn(
                        f"diagnostics.transfers={memory.transfer_mode!r} (or a memory fault injection) "
                        "is set but diagnostics.telemetry.enabled=False: the transfer guard, "
                        "donation audit and OOM forensics attach to instrumented dispatches and "
                        "will NOT run. Only the passive Telemetry/hbm_* gauges remain active.",
                        RuntimeWarning,
                    )
        self.goodput: Optional[GoodputMonitor] = None
        if self.enabled:
            goodput = GoodputMonitor(cfg or {})
            if goodput.enabled:
                self.goodput = goodput
        self.health: Optional[HealthMonitor] = None
        if self.enabled:
            health = HealthMonitor(cfg or {})
            if health.enabled:
                self.health = health
        self.resilience = None
        if self.enabled:
            from sheeprl_tpu.resilience.monitor import ResilienceMonitor

            resilience = ResilienceMonitor(cfg or {})
            if resilience.enabled:
                self.resilience = resilience
        self.journal: Optional[RunJournal] = None
        self.tracer = NullTracer()
        self.metrics_server = None
        self.log_dir: Optional[str] = None
        self.run_id: Optional[str] = None
        self._rank_zero = True
        self._closed = False

    # -- lifecycle ---------------------------------------------------------
    def open(self, log_dir: str, rank_zero: bool = True) -> "Diagnostics":
        """Open journal/tracer/telemetry inside ``log_dir`` (idempotent;
        journal + endpoint are rank-0 only, the tracer — when
        ``trace.all_ranks`` — and the telemetry accounting run everywhere)."""
        if not self.enabled or self.log_dir is not None:
            return self
        self.log_dir = str(log_dir)
        self.run_id = run_id_of(self.log_dir)
        self._rank_zero = bool(rank_zero)
        if self._trace_cfg.get("enabled", False) and (
            self._rank_zero or self._trace_cfg.get("all_ranks", True)
        ):
            import jax

            rank = jax.process_index()
            if self._rank_zero:
                trace_path = self._trace_cfg.get("path") or os.path.join(self.log_dir, TRACE_NAME)
            else:
                # an explicit trace.path must NOT be honored here: every rank
                # would open the same file in 'w' mode and clobber the others
                trace_path = os.path.join(self.log_dir, f"trace_rank{rank}.json")
            self.tracer = PhaseTracer(
                trace_path,
                pid=rank,
                max_events=self._trace_cfg.get("max_events"),
                rotate_keep=int(self._trace_cfg.get("rotate_keep", 2)),
                run_id=self.run_id,
                role=self.role,
            )
        if self._rank_zero and self._journal_cfg.get("enabled", True):
            self.journal = RunJournal(
                os.path.join(self.log_dir, JOURNAL_NAME),
                fsync_every=int(self._journal_cfg.get("fsync_every", 1)),
            )
        cfg = self._cfg or {}
        if self.journal is not None:
            self.journal.write(
                "run_start",
                config_hash=config_hash(cfg),
                algo=(cfg.get("algo") or {}).get("name"),
                env=(cfg.get("env") or {}).get("id"),
                seed=cfg.get("seed"),
                exp_name=cfg.get("exp_name"),
                run_name=cfg.get("run_name"),
                log_dir=self.log_dir,
                run_id=self.run_id,
                sentinel_policy=self.sentinel.policy if self.sentinel.enabled else None,
            )
            if self.compilation_cache_dir:
                # the cache itself was enabled at CLI startup (before any
                # compile — cli._apply_global_flags); the journal records
                # where it lives so restarts/post-mortems can account for
                # compile time that never shows up
                self.journal.write("compilation_cache", dir=str(self.compilation_cache_dir))
        if self.resilience is not None:
            # opened on every rank: each process of a decoupled topology must
            # honor its own preemption signal; journal writes (ckpt_begin/
            # ckpt_end, drained ckpt_skipped records) no-op off rank 0
            self.resilience.open(
                self._journal_event, self._journal_sync, rank_zero=self._rank_zero
            )
        if self.memory is not None:
            # opened on every rank: the transfer guard must protect every
            # process; journal writes no-op off rank 0 (journal is None there)
            self.memory.open(self._journal_event, self._journal_sync)
        if self.health is not None and self._rank_zero:
            # rank-0 only, like the journal: the detectors describe THE run,
            # and their output is the journal + the Telemetry/health gauges
            self.health.open(self._journal_event, self._journal_sync)
        if self.goodput is not None and self._rank_zero:
            # rank-0 only, like the journal: the state machine / watchdog
            # describe THE run, and their output is journal + gauges
            self.goodput.open(
                self._goodput_event,
                self._journal_sync,
                telemetry=self.telemetry,
                log_dir=self.log_dir,
            )
            if self.telemetry is None:
                # warned HERE (rank-0, at open) rather than in the ctor: the
                # gauges the warning is about only ever exist on this rank.
                # The state machine still runs on span/interval hooks, but
                # Telemetry/goodput + time_to_first_step need telemetry's
                # train-span seconds and dispatch notifications — they will
                # be OMITTED (never a false 0.0), which must not be a silent
                # surprise
                warnings.warn(
                    "diagnostics.goodput.enabled=True but diagnostics.telemetry.enabled=False: "
                    "Telemetry/goodput and Telemetry/time_to_first_step will be omitted "
                    "(the run-state machine and stall watchdog still run on span/interval hooks).",
                    RuntimeWarning,
                )
        if self.telemetry is not None:
            self.telemetry.open(
                self._journal_event,
                {
                    "run_id": self.run_id,
                    "algo": (cfg.get("algo") or {}).get("name"),
                    "env": (cfg.get("env") or {}).get("id"),
                    "role": self.role,
                },
            )
            if self.goodput is not None and self.goodput._opened:
                # telemetry drives the compile/dispatch notifications (and
                # hosts the stall-injection sleep) for the state machine
                self.telemetry._goodput = self.goodput
            if self._rank_zero and self.telemetry.http_enabled:
                self._start_metrics_server()
        return self

    def _start_metrics_server(self) -> None:
        from sheeprl_tpu.diagnostics.metrics_server import MetricsServer

        profile_fn = None
        if self.goodput is not None and self.goodput._opened and self.goodput.profile_enabled:
            profile_fn = self.goodput.capture_profile
        try:
            self.metrics_server = MetricsServer(
                self._server_snapshot,
                host=self.telemetry.http_host,
                port=self.telemetry.http_port,
                profile_fn=profile_fn,
            )
            host, port = self.metrics_server.start()
        except OSError as err:
            # a taken port must not take the run down with it
            self.metrics_server = None
            warnings.warn(f"diagnostics metrics endpoint failed to bind: {err}", RuntimeWarning)
            self._journal_event("metrics_server", status="bind_failed", error=str(err))
            return
        self._journal_event("metrics_server", status="serving", host=host, port=port)
        print(f"Telemetry endpoint: http://{host}:{port}/metrics (and /healthz)", flush=True)

    def _server_snapshot(self) -> Dict[str, Any]:
        snap = self.telemetry.snapshot() if self.telemetry is not None else {}
        if self.memory is not None:
            mem = self.memory.snapshot()
            snap.setdefault("gauges", {}).update(mem["gauges"])
            snap.setdefault("counters", {}).update(mem["counters"])
            info = snap.setdefault("info", {})
            for k, v in mem["info"].items():
                if v is not None:
                    info.setdefault(k, v)
        if self.goodput is not None and self.goodput._opened:
            good = self.goodput.snapshot()
            snap.setdefault("gauges", {}).update(good["gauges"])
            snap.setdefault("counters", {}).update(good["counters"])
            info = snap.setdefault("info", {})
            for k, v in good["info"].items():
                if v is not None:
                    info.setdefault(k, v)
        if self.health is not None and self.health._opened:
            health = self.health.snapshot()
            snap.setdefault("gauges", {}).update(health["gauges"])
            snap.setdefault("counters", {}).update(health["counters"])
            info = snap.setdefault("info", {})
            for k, v in health["info"].items():
                if v is not None:
                    info.setdefault(k, v)
        if self.resilience is not None and self.resilience._opened:
            res = self.resilience.snapshot()
            snap.setdefault("gauges", {}).update(res["gauges"])
            snap.setdefault("counters", {}).update(res["counters"])
            info = snap.setdefault("info", {})
            for k, v in res["info"].items():
                if v is not None:
                    info.setdefault(k, v)
        if self.journal is not None and self.journal.last_write_t is not None:
            import time

            snap["journal_lag_seconds"] = round(time.time() - self.journal.last_write_t, 3)
        return snap

    def _journal_event(self, event: str, **fields: Any) -> None:
        if self.journal is not None:
            self.journal.write(event, **fields)

    def _goodput_event(self, event: str, **fields: Any) -> None:
        """Goodput emissions mirror into the journal AND (as instants) the
        trace, so a Perfetto timeline shows state changes/stalls in place."""
        self._journal_event(event, **fields)
        if event == "state_change":
            self.tracer.instant(f"state:{fields.get('state')}", prev=fields.get("prev"))
        elif event in ("stall", "stall_end"):
            self.tracer.instant(event)

    def _journal_sync(self) -> None:
        """Force journal bytes to disk NOW (OOM forensics: the record must
        survive the process dying right after it is written)."""
        if self.journal is not None:
            self.journal.sync()

    def close(self, status: str = "completed") -> None:
        if self._closed:
            return
        self._closed = True
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self.resilience is not None:
            # FIRST: drain the async checkpoint writer so a pending (possibly
            # emergency) snapshot lands — and journals its ckpt_end — before
            # run_end is written
            self.resilience.close()
        goodput_open = self.goodput is not None and self.goodput._opened
        if goodput_open:
            # close BEFORE summarizing: the ended-transition folds the live
            # state tail (and any open stall) into the state_seconds totals
            self.goodput.close()
        if self.telemetry is not None or goodput_open:
            # one closing summary event whether either (or both) layers ran —
            # telemetry-off + goodput-on must not discard the state/stall
            # accounting
            if self.journal is not None:
                summary = self.telemetry.summary() if self.telemetry is not None else {}
                if goodput_open:
                    summary.update(self.goodput.summary())
                if self.health is not None and self.health._opened:
                    summary.update(self.health.summary())
                if self.resilience is not None:
                    summary.update(self.resilience.summary())
                self.journal.write("telemetry_summary", **summary)
            if self.telemetry is not None:
                self.telemetry.close()
        if self.memory is not None and self.journal is not None:
            self.journal.write("memory_summary", **self.memory.summary())
        if self.journal is not None:
            self.journal.write("run_end", status=status)
            self.journal.close()
        self.tracer.close()

    # -- tracing + phase accounting ----------------------------------------
    def span(self, name: str, **args: Any):
        """Phase span context manager: feeds the telemetry phase-attribution
        accumulator, the run-state machine and (when tracing is open) the
        Chrome trace."""
        tracing = not isinstance(self.tracer, NullTracer)
        # `_opened` (not just `is not None`): goodput is rank-0 only, and
        # telemetry-off workers must not pay a generator per span for a no-op
        goodput = self.goodput if (self.goodput is not None and self.goodput._opened) else None
        if self.telemetry is None and not tracing and goodput is None:
            return nullcontext()
        return self._span(name, args, tracing, goodput)

    @contextmanager
    def _span(self, name: str, args: Dict[str, Any], tracing: bool, goodput=None):
        if goodput is not None:
            goodput.note_span(name)
        token = self.telemetry.span_enter(name) if self.telemetry is not None else None
        try:
            if tracing:
                with self.tracer.span(name, **args):
                    yield
            else:
                yield
        finally:
            if token is not None:
                self.telemetry.span_exit(token)

    # -- telemetry hooks ---------------------------------------------------
    def instrument(self, name: str, fn, kind: str = "train", donate_argnums=(), cost_note=None):
        """Wrap a jitted step for the recompile watchdog + FLOPs accounting
        (``kind="train"``) or signature-watch only (``kind="rollout"``).
        ``donate_argnums`` declares which arguments the wrapped jit donates —
        the memory monitor verifies the donation actually happened at first
        dispatch.  ``cost_note`` is a caveat journaled with the step's
        ``telemetry_cost`` FLOPs (e.g. unrolled scans inflate
        ``cost_analysis()``, so MFU must not be read at face value).
        Identity when telemetry is disabled."""
        if self.telemetry is None:
            return fn
        return self.telemetry.instrument(
            name, fn, kind=kind, donate_argnums=donate_argnums, cost_note=cost_note
        )

    def note_env_steps(self, n: int) -> None:
        """Count ``n`` env steps toward ``Telemetry/env_steps_per_sec`` and
        fetch amortization (loops call it once per vector step with
        ``num_envs``).  No-op when telemetry is disabled."""
        if self.telemetry is not None:
            self.telemetry.note_env_steps(n)

    def note_fetch(self, n: int = 1) -> None:
        """Count a blocking obs→action fetch outside the instrumented rollout
        dispatch path (Dreamer's direct action fetch).  No-op when disabled."""
        if self.telemetry is not None:
            self.telemetry.note_fetch(n)

    def note_dataset_read(self, n: int) -> None:
        """Count ``n`` transitions streamed from the offline dataset loader
        toward ``Telemetry/dataset_read_sps``.  No-op when disabled."""
        if self.telemetry is not None:
            self.telemetry.note_dataset_rows(n)

    def note_dataset_epoch(self, epoch: float) -> None:
        """Record the offline loader's epoch counter
        (``Telemetry/dataset_epoch``).  No-op when disabled."""
        if self.telemetry is not None:
            self.telemetry.note_dataset_epoch(epoch)

    def augment_metrics(self, step: Optional[int], metrics: Mapping[str, Any]) -> Mapping[str, Any]:
        """Merge the interval's ``Telemetry/*`` gauges (compute + memory) into
        an aggregated metrics dict (called by the logger proxy before the
        backend logs)."""
        extra: Dict[str, Any] = {}
        if self.telemetry is not None:
            extra.update(self.telemetry.interval_metrics(step))
        if self.memory is not None and self._rank_zero and self.log_dir is not None:
            extra.update(self.memory.interval_metrics())
        if self.goodput is not None:
            extra.update(self.goodput.interval_metrics())
        if self.health is not None:
            extra.update(self.health.interval_metrics())
        if self.resilience is not None and self._rank_zero:
            extra.update(self.resilience.interval_metrics())
        if not extra:
            return metrics
        merged = dict(metrics)
        merged.update(extra)
        return merged

    # -- learning-health hooks ---------------------------------------------
    def on_health(self, step: Optional[int], stats: Mapping[str, Any]) -> None:
        """Digest one train step's fetched ``health_stats`` dict: updates the
        live ``Telemetry/health/*`` gauges and runs the stats-fed anomaly
        detectors (update/weight-ratio band, dead-gradient, value-EV floor).
        No-op until opened, off rank 0, or with an empty dict (the train
        steps return ``{}`` when ``diagnostics.health`` is disabled, so call
        sites stay unconditional)."""
        if self.health is not None and self._rank_zero and stats:
            self.health.on_stats(step, stats)

    # -- memory hooks ------------------------------------------------------
    def register_footprint(self, name: str, tree_or_bytes: Any) -> None:
        """Record a static component's byte size (params / optimizer state /
        ...) for the ``memory_breakdown`` event.  No-op when disabled."""
        if self.memory is not None:
            self.memory.register_footprint(name, tree_or_bytes)

    def track_buffer(self, name: str, buffer: Any) -> None:
        """Track a replay buffer's live footprint per metric interval
        (host RAM, memmap on-disk, or HBM-resident bytes)."""
        if self.memory is not None:
            self.memory.track_buffer(name, buffer)

    def on_fsdp_shard_map(self, summary: Mapping[str, Any]) -> None:
        """Record how the FSDP partition rule laid out the train state
        (``parallel/fsdp.py::shard_map_summary``): journals the
        ``fsdp_shard_map`` event and arms the memory monitor's per-device
        accounting (``Telemetry/fsdp_axis_size`` gauge + the ``min_shard_bytes``
        exemption in the sharding audit).  No-op when disabled."""
        if not self.enabled:
            return
        if self.memory is not None:
            self.memory.note_fsdp(summary)
        self._journal_event("fsdp_shard_map", **dict(summary))

    # -- journal hooks -----------------------------------------------------
    def log_metrics(self, step: Optional[int], metrics: Mapping[str, Any]) -> None:
        """Journal one aggregated-metrics interval + run divergence checks.

        Called by the rank-0 logger proxy right after the metrics went to
        TensorBoard/W&B, so the journal mirrors exactly what was logged.
        """
        if not metrics:
            return
        if self.journal is not None:
            self.journal.write("metrics", step=step, metrics=dict(metrics))
        if self._detector is not None and self._rank_zero:
            for event in self._detector.observe(step, metrics):
                self._journal_divergence(event)
        if self.health is not None and self._rank_zero:
            # entropy-collapse / loss-plateau windows feed on the same
            # aggregated stream the divergence detector watches
            self.health.observe_metrics(step, metrics)

    def on_checkpoint(self, step: Optional[int], path: str) -> None:
        if self.journal is not None:
            self.journal.write("checkpoint", step=step, path=str(path))
        self.tracer.instant("checkpoint", step=step)

    # -- resilience hooks (ISSUE 13) ----------------------------------------
    def save_checkpoint(self, path: str, state: Mapping[str, Any], group: Optional[Mapping[str, Any]] = None) -> bool:
        """Route one checkpoint save through the resilience layer (async
        writer or blocking-with-journaling, manifest sidecar either way).
        Returns False when the layer is off/unopened — the caller
        (``Runtime.save``) then performs the plain synchronous save itself.
        ``group`` threads the coordinated multi-host record into the
        manifest (``resilience/coordination.py``)."""
        if self.resilience is None or not self.resilience._opened:
            return False
        self.resilience.save(path, state, group=group)
        return True

    # -- fault isolation hooks (ISSUE 14: decoupled fencing & rollback) ------
    def gate_promotion(
        self,
        iter_num: int,
        step: Optional[int],
        stats: Optional[Mapping[str, Any]] = None,
        nonfinite: float = 0.0,
    ) -> bool:
        """Promotion gate for the trainer→player params hop: True = hand the
        freshly trained params to the player.  Judges the signals the loop
        ALREADY fetched (in-graph nonfinite count, ``health_stats`` norms)
        plus any open learning-health anomaly — zero extra device syncs.  A
        rejection journals ``params_reject`` and the player keeps its
        last-good params.  Always True when isolation is off (today's
        unconditional hand-off)."""
        res = self.resilience
        if res is None or res.isolation is None or not res._opened:
            return True
        anomalies = ()
        if self.health is not None and self.health._opened:
            anomalies = self.health.open_anomaly_kinds()
        return res.isolation.judge(iter_num, step, stats or {}, float(nonfinite), anomalies)

    def refresh_last_good(self, iter_num: int, params: Any, opt_state: Any) -> None:
        """Refresh the in-memory last-good snapshot after a healthy
        promotion (one batched device→host fetch, double-buffered)."""
        res = self.resilience
        if res is not None and res.isolation is not None and res._opened:
            res.isolation.refresh(iter_num, params, opt_state)

    def quarantine(
        self, err: BaseException, iter_num: int, step: Optional[int]
    ) -> Optional[Dict[str, Any]]:
        """Absorb one quarantined train-step failure: journal ``rollback``
        and return the last-good ``{params, opt_state, iter_num}`` snapshot
        for the loop to restore, or None (no snapshot / isolation off /
        retry budget spent) — the caller then re-raises."""
        res = self.resilience
        if res is None or res.isolation is None or not res._opened:
            return None
        return res.isolation.rollback(err, iter_num, step)

    def last_good_state(self) -> Optional[Dict[str, Any]]:
        """The in-memory last-good ``{params, opt_state, iter_num}`` host
        snapshot, or None.  The fence-halt checkpoint branch saves THIS, not
        the live trainer trees — under ``sentinel.policy=warn`` the live
        params are exactly the corrupted state the fence escalated about."""
        res = self.resilience
        if res is None or res.isolation is None or not res._opened:
            return None
        return res.isolation.last_good

    def fence_halt_due(self) -> bool:
        """True once the staleness budget is exhausted: the loop forces its
        checkpoint branch (emergency snapshot of the last-good state) and
        then calls :meth:`on_fence_halt`."""
        res = self.resilience
        return res is not None and res.isolation is not None and res._opened and res.isolation.halt_due

    def on_fence_halt(self, step: Optional[int], iter_num: int, ckpt_path: str) -> None:
        """Finish a staleness escalation: journal the structured finding
        (fsync'd), close the run with status ``halted`` and raise
        :class:`~sheeprl_tpu.resilience.isolation.IsolationHalt`."""
        from sheeprl_tpu.resilience.isolation import IsolationHalt

        iso = self.resilience.isolation
        self._journal_divergence(
            {
                "kind": "param_staleness_exhausted",
                "step": step,
                "iter_num": int(iter_num),
                "staleness": iso.staleness,
                "budget": iso.max_staleness,
                "path": str(ckpt_path),
            }
        )
        self._journal_sync()
        self.close("halted")
        raise IsolationHalt(
            f"player param staleness exhausted its budget ({iso.staleness} > "
            f"{iso.max_staleness} consecutive rejected promotions) at iteration {iter_num}; "
            f"emergency checkpoint {ckpt_path} "
            "(diagnostics.resilience.isolation.max_staleness)"
        )

    def maybe_chaos_trainer_fault(self, iter_num: int) -> None:
        """Raise the scheduled :class:`ChaosTrainerError` at the train
        dispatch boundary (chaos fault ``trainer_exception``); no-op
        otherwise."""
        res = self.resilience
        if res is None or res.chaos is None or not res._opened:
            return
        if res.chaos.take(iter_num, "trainer_exception"):
            from sheeprl_tpu.resilience.chaos import ChaosTrainerError

            raise ChaosTrainerError(
                f"chaos: injected trainer exception at iteration {iter_num}"
            )

    def preempt_due(self, iter_num: int) -> bool:
        """True once a preemption (SIGTERM/SIGINT, or the
        ``diagnostics.resilience.inject_preempt_iter`` drill) is pending.
        The loop then forces its checkpoint branch — the emergency snapshot —
        and calls :meth:`on_preempted` with the written path."""
        return self.resilience is not None and self.resilience.preempt_due(iter_num)

    def on_preempted(self, step: Optional[int], iter_num: int, ckpt_path: str) -> None:
        """Finish a graceful preemption: drain the async writer FIRST (the
        ``preempted`` record must not claim a snapshot that never landed),
        journal the fsync'd ``preempted`` record with the observed durability,
        close the run with status ``preempted`` and exit with the distinct
        preemption code by raising :class:`PreemptedExit`."""
        from sheeprl_tpu.resilience.preemption import PreemptedExit

        reason = "preempt"
        durable = True
        if self.resilience is not None:
            reason = self.resilience.preempt_reason
            # bounded: a write slower than the flush timeout is abandoned at
            # exit, and the record says so — resume selection only ever picks
            # VERIFIED checkpoints, so a lost snapshot costs progress, not
            # correctness
            durable = self.resilience.flush()
        self._journal_event(
            "preempted",
            step=step,
            iter_num=int(iter_num),
            path=str(ckpt_path),
            reason=reason,
            snapshot_durable=durable,
        )
        self._journal_sync()
        self.close("preempted")
        raise PreemptedExit(
            f"preempted ({reason}) at iteration {iter_num}: emergency checkpoint {ckpt_path}"
        )

    def _journal_divergence(self, event: Dict[str, Any]) -> None:
        if self.telemetry is not None:
            self.telemetry.count_sentinel_event()
        if self.journal is not None:
            kind = event.pop("kind", "unknown")
            step = event.pop("step", None)
            self.journal.write("divergence", kind=kind, step=step, **event)
            self.tracer.instant(f"divergence:{kind}", step=step)

    # -- sentinel host side ------------------------------------------------
    def on_update(self, step: Optional[int], stats: Mapping[str, Any], nonfinite: float = 0.0) -> None:
        """Digest one (fetched) train-step metric bundle.

        ``nonfinite`` is the in-graph count of optimizer steps whose
        loss/grad-norm finiteness flag tripped.  Journals a structured
        ``divergence`` event and applies the configured policy: ``warn``
        warns, ``skip_update`` already discarded the bad update in-graph (so
        this only records it), ``halt`` raises :class:`SentinelHalt`.
        """
        if not (self.enabled and self.sentinel.enabled):
            return
        nonfinite = float(nonfinite)
        if nonfinite <= 0:
            return
        self._journal_divergence(
            {
                "kind": "nonfinite_update",
                "step": step,
                "nonfinite_steps": nonfinite,
                "policy": self.sentinel.policy,
                **{k: v for k, v in stats.items()},
            }
        )
        if self.sentinel.policy == "halt":
            # a decoupled loop with the isolation layer armed catches this
            # halt and rolls back to the last-good snapshot — closing the
            # facade here would kill the journal under a run that survives
            absorbable = (
                self.resilience is not None
                and self.resilience.isolation is not None
                and self.resilience.isolation.can_absorb()
            )
            if not absorbable:
                self.close("halted")
            raise SentinelHalt(
                f"non-finite training update at step {step} "
                f"(nonfinite optimizer steps this interval: {nonfinite:g}); "
                "diagnostics.sentinel.policy=halt"
            )
        if self.sentinel.policy == "warn" and self._rank_zero:
            warnings.warn(
                f"Sentinel: non-finite training update at step {step} "
                f"({nonfinite:g} optimizer steps); params may be corrupted "
                "(diagnostics.sentinel.policy=warn)",
                RuntimeWarning,
            )

    def observe_rows(self, step: Optional[int], names, rows) -> None:
        """Sentinel digest for the Dreamer metric drain: ``rows`` is a list of
        per-gradient-step metric vectors (ordered as ``names``) fetched at the
        log boundary.  Counts rows with any non-finite entry; under
        ``skip_update`` those steps were already discarded in-graph."""
        if not (self.enabled and self.sentinel.enabled) or not rows:
            return
        import numpy as np

        arr = np.asarray(rows, dtype=np.float64)
        bad = ~np.isfinite(arr).all(axis=tuple(range(1, arr.ndim)))
        n_bad = int(bad.sum())
        if n_bad:
            first_bad = arr[bad][0]
            stats = {str(n): float(v) for n, v in zip(names, first_bad)}
            self.on_update(step, stats, nonfinite=n_bad)

    # -- fault injection (tests / chaos drills) ----------------------------
    def maybe_inject_nan(self, iter_num: int, tree):
        """Poison a train batch at the configured iteration
        (``diagnostics.sentinel.inject_nan_iter``, or a chaos schedule's
        ``nan_grads`` entry) — the documented way to drill the sentinel /
        fencing paths end-to-end without doctoring model code."""
        poison = False
        res = self.resilience
        if res is not None and res.chaos is not None and res._opened:
            # take() journals its own fault_injection (kind=nan_grads)
            poison = res.chaos.take(iter_num, "nan_grads")
        inject = self.sentinel.inject_nan_iter
        if inject is not None and int(iter_num) == inject:
            if self.journal is not None:
                self.journal.write("fault_injection", iter_num=int(iter_num))
            poison = True
        if not poison:
            return tree
        return poison_tree(tree)

    def maybe_inject_shape_change(self, iter_num: int, tree, pad: int = 1):
        """Shape-change fault injection for the recompile watchdog
        (``diagnostics.telemetry.watchdog.inject_shape_change_iter``): pad the
        leading axis of every array leaf by repeating its last row ``pad``
        times at the configured loop iteration.  Only wired into the
        PPO-family loops, whose minibatch indexing reads exactly
        ``num_minibatches * batch_size`` rows — the padding rows are never
        sampled, so training math is untouched while the dispatch signature
        (and hence the compiled graph) genuinely changes.  ``pad`` defaults to
        1; multi-device callers pass their data-axis divisor."""
        telemetry = self.telemetry
        if telemetry is None or telemetry.inject_shape_change_iter is None:
            return tree
        if int(iter_num) != telemetry.inject_shape_change_iter:
            return tree
        import jax
        import jax.numpy as jnp

        if self.journal is not None:
            self.journal.write("fault_injection", iter_num=int(iter_num), kind="shape_change", pad=int(pad))

        def pad_leaf(x):
            if not hasattr(x, "shape") or not getattr(x, "shape", ()):  # scalars
                return x
            tail = jnp.repeat(x[-1:], int(pad), axis=0)
            return jnp.concatenate([x, tail], axis=0)

        return jax.tree_util.tree_map(pad_leaf, tree)


def build_diagnostics(cfg: Optional[Mapping[str, Any]]) -> Diagnostics:
    """Construct the facade from a composed run config (never raises on a
    missing ``diagnostics`` section — direct entrypoint callers like bench.py
    simply get a disabled facade).  Installs the process-wide compile-event
    listener early so compiles that happen before the run dir exists (agent
    build, warmup jits) are still counted."""
    diagnostics = Diagnostics(cfg)
    if diagnostics.telemetry is not None:
        monitoring_available()
    return diagnostics
