"""Learning-dynamics observability: in-graph train-health statistics +
host-side anomaly detectors (ISSUE 9).

The journal/telemetry/memory/goodput pillars say how fast and whether-alive a
run is; this module says whether it is *learning*.  Three layers:

* **In-graph stats, zero extra syncs** — :func:`health_stats` is a
  jit-compatible pure function of ``(grads, updates, params)`` computing
  per-top-level-module gradient/update/parameter norms, the update-to-weight
  ratio and the dead-unit fraction *inside* the already-guarded train steps
  (ppo / a2c / sac family / ``_dreamer_main``, the same sites the NaN
  sentinel instruments).  The returned stats pytree of scalars rides the
  step's existing output fetch — the dispatch count and the ``device_get``
  count are unchanged (the ppo CLI e2e pins both).  The global grad norm it
  computes is *shared* with the sentinel's finiteness check, so enabling
  health removes one whole-tree reduction instead of adding one.

* **Host-side anomaly detectors** — :class:`HealthMonitor` keeps rolling
  windows over the per-step stats (fed by ``diag.on_health``) and the
  aggregated metric stream (fed at every log boundary, like the divergence
  detector): policy-entropy collapse, value explained-variance floor,
  update/weight-ratio band, loss plateau and per-module dead-gradient.  A
  breach must hold for ``diagnostics.health.confirm`` consecutive
  observations before ONE flood-controlled, fsync'd ``anomaly`` event fires
  (carrying the offending window); recovery journals ``anomaly_end``.  The
  live ``Telemetry/health/*`` gauges merge into every metric interval and
  the ``/metrics`` endpoint.

* **Cross-run regression diff** — ``tools/health_report.py`` (per-run
  post-mortem with per-module trajectory tables) and ``tools/health_diff.py``
  (two journals' watched trajectories under tolerance bands, non-zero exit
  on regression — the "did this PR change learning?" CI primitive) consume
  the journal records this module writes; the journal-side helpers they
  share (:func:`metric_series`, :func:`active_anomalies`) live here.

Like :class:`~sheeprl_tpu.diagnostics.sentinel.SentinelSpec`, the in-graph
configuration is a hashable trace-time constant (:class:`HealthSpec`), so the
``make_train_step`` builders read it straight from ``cfg`` without threading
new arguments through ``shard_map``/``jit`` signatures.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, NamedTuple, Optional, Sequence, Tuple


class HealthSpec(NamedTuple):
    """Trace-time health-stats configuration for the jitted train steps."""

    enabled: bool = False
    per_module: bool = False
    dead_eps: float = 1e-8


def health_spec(cfg: Mapping[str, Any]) -> HealthSpec:
    """Extract the :class:`HealthSpec` from a composed run config.

    Tolerates configs without a ``diagnostics`` section (bench.py and the HLO
    tests compose partial configs and call ``make_train_step`` directly):
    missing means disabled, which keeps those compiled graphs byte-identical.
    """
    diag = cfg.get("diagnostics") or {}
    health = diag.get("health") or {}
    enabled = bool(diag.get("enabled", False)) and bool(health.get("enabled", True))
    return HealthSpec(
        enabled=enabled,
        per_module=bool(health.get("per_module", False)),
        dead_eps=float(health.get("dead_eps", 1e-8)),
    )


# ---------------------------------------------------------------------------
# jit-compatible in-graph statistics
# ---------------------------------------------------------------------------


def top_level_modules(tree: Any) -> Dict[str, Any]:
    """Group a parameter-like pytree by its top-level module names.

    Descends through single-key mappings first (flax's ``{"params": {...}}``
    wrapper must not collapse everything into one "params" module) and groups
    by the keys of the first multi-key mapping.  A non-mapping tree (or a
    mapping of leaves) grouped as a single ``all`` module keeps the helper
    total on exotic structures.
    """
    node = tree
    while isinstance(node, Mapping) and len(node) == 1:
        (only,) = node.values()
        if not isinstance(only, Mapping):
            break
        node = only
    if isinstance(node, Mapping) and len(node) > 1:
        return {str(k): node[k] for k in node}
    return {"all": node}


def _unit_counts(tree: Any, dead_eps: float):
    """(dead units, total units) over a gradient tree (jit-compatible).

    A *unit* is a slice along a leaf's LAST axis (the output-feature axis of
    dense/conv kernels; each element of a bias/scalar).  A unit is dead when
    the max |grad| over its slice is <= ``dead_eps`` — the in-graph
    formulation of "this neuron received no learning signal this step".
    """
    import jax
    import jax.numpy as jnp

    dead = jnp.asarray(0.0, jnp.float32)
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = jnp.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.inexact):
            continue
        if arr.ndim == 0:
            unit_mag = jnp.abs(arr)[None]
            n_units = 1
        else:
            axes = tuple(range(arr.ndim - 1))
            unit_mag = jnp.max(jnp.abs(arr), axis=axes) if axes else jnp.abs(arr)
            n_units = int(arr.shape[-1])
        dead = dead + jnp.sum((unit_mag <= dead_eps).astype(jnp.float32))
        total += n_units
    return dead, total


def _tree_norm(tree: Any):
    import jax
    import jax.numpy as jnp

    leaves = [
        jnp.asarray(l)
        for l in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
    ]
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def health_stats(
    grads: Any,
    updates: Any,
    params: Any,
    *,
    per_module: bool = False,
    dead_eps: float = 1e-8,
) -> Dict[str, Any]:
    """Per-top-level-module train-health statistics (jit-compatible).

    Returns a flat ``{name: scalar}`` dict that can ride a train step's
    existing output fetch:

    * ``grad_norm`` / ``update_norm`` / ``param_norm`` — global L2 norms
      (``grad_norm`` is exactly ``optax.global_norm(grads)``, so the sentinel
      finiteness check shares it instead of reducing the tree twice);
    * ``update_ratio`` — ``update_norm / param_norm`` (the "how fast are the
      weights moving" number; ~1e-3 is healthy, ~0 is frozen, ~1 is blowing
      up);
    * ``dead_frac`` — fraction of units (last-axis slices) whose max |grad|
      is <= ``dead_eps``;
    * ``module/<name>/<stat>`` — the same five per top-level module when
      ``per_module`` (``diagnostics=full``).

    ``grads``/``updates``/``params`` must share their top-level module
    structure (they do at every call site: the gradient tree mirrors the
    parameter tree, and ``optimizer.update`` returns updates in it too).
    """
    import jax.numpy as jnp

    eps = jnp.asarray(1e-12, jnp.float32)

    def stats_of(g, u, p) -> Dict[str, Any]:
        grad_norm = _tree_norm(g)
        update_norm = _tree_norm(u)
        param_norm = _tree_norm(p)
        dead, total = _unit_counts(g, dead_eps)
        return {
            "grad_norm": grad_norm,
            "update_norm": update_norm,
            "param_norm": param_norm,
            "update_ratio": update_norm / (param_norm + eps),
            "dead_frac": dead / jnp.asarray(max(1, total), jnp.float32),
        }

    out = dict(stats_of(grads, updates, params))
    if per_module:
        grad_modules = top_level_modules(grads)
        update_modules = top_level_modules(updates)
        param_modules = top_level_modules(params)
        for name in grad_modules:
            module = stats_of(
                grad_modules[name],
                update_modules.get(name, grad_modules[name]),
                param_modules.get(name, grad_modules[name]),
            )
            for stat, value in module.items():
                out[f"module/{name}/{stat}"] = value
    return out


def explained_variance(values: Any, returns: Any):
    """Value-function explained variance ``1 - Var(returns - values) /
    Var(returns)`` (jit-compatible; 0 when the return variance vanishes).

    1.0 = the critic predicts returns perfectly; 0 = no better than the
    mean; < 0 = actively worse.  A saturated/diverged value head shows up as
    this sliding toward (or below) zero long before the loss curve says so.
    """
    import jax.numpy as jnp

    values = jnp.asarray(values, jnp.float32).reshape(-1)
    returns = jnp.asarray(returns, jnp.float32).reshape(-1)
    var_returns = jnp.var(returns)
    ev = 1.0 - jnp.var(returns - values) / jnp.where(var_returns > 1e-12, var_returns, 1.0)
    return jnp.where(var_returns > 1e-12, ev, 0.0)


def mean_stats(stats_list: Sequence[Optional[Mapping[str, Any]]]) -> Dict[str, float]:
    """Key-wise mean over a sequence of fetched stats dicts (Dreamer's drain
    hands the per-gradient-step dicts of one log interval here).  ``None`` /
    empty entries are skipped; values coerce through ``float``."""
    sums: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for stats in stats_list:
        if not stats:
            continue
        for key, value in stats.items():
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            sums[key] = sums.get(key, 0.0) + v
            counts[key] = counts.get(key, 0) + 1
    return {k: sums[k] / counts[k] for k in sums}


# ---------------------------------------------------------------------------
# host-side anomaly detection
# ---------------------------------------------------------------------------

#: Gauge-key prefix for everything this module merges into the metric stream.
HEALTH_PREFIX = "Telemetry/health/"
#: Scalar-subset gauge keys (registered in schema.METRICS; per-module detail
#: keys are built dynamically and stay journal/TB-only).
_SCALAR_GAUGES = ("grad_norm", "update_norm", "param_norm", "update_ratio", "dead_frac", "value_ev")


class HealthMonitor:
    """Rolling-window learning-health anomaly detection behind the facade.

    Opened on rank 0 only (its outputs are the journal and the gauges); every
    hook is a cheap no-op until then.  Two feeds:

    * :meth:`on_stats` — per-train-dispatch stats fetched by the loops
      (update/weight ratio, dead fractions, value EV);
    * :meth:`observe_metrics` — the aggregated metric stream at each log
      boundary (entropy collapse, loss plateau).

    A detector must breach for ``confirm`` consecutive observations before
    its single fsync'd ``anomaly`` event (flood control: one per detector
    while the condition holds); the first clean observation journals
    ``anomaly_end``.  Thread-safe: the metrics server snapshots from its own
    thread.
    """

    #: how many recent observations each journaled anomaly window carries
    WINDOW_KEEP = 12

    def __init__(self, cfg: Optional[Mapping[str, Any]]):
        cfg = cfg or {}
        diag_cfg = cfg.get("diagnostics") or {}
        health_cfg = diag_cfg.get("health") or {}
        self.enabled = bool(health_cfg.get("enabled", True))
        self.per_module = bool(health_cfg.get("per_module", False))
        self.confirm = int(health_cfg.get("confirm", 3))
        if self.confirm < 1:
            raise ValueError(
                f"diagnostics.health.confirm must be >= 1, got {health_cfg.get('confirm')!r}"
            )
        det = health_cfg.get("detectors") or {}
        self.entropy_key = det.get("entropy_key", "Loss/entropy_loss")
        floor = det.get("entropy_floor")
        self.entropy_floor = None if floor is None else float(floor)
        ev_floor = det.get("value_ev_floor")
        self.value_ev_floor = None if ev_floor is None else float(ev_floor)
        low = det.get("update_ratio_low", 1e-8)
        high = det.get("update_ratio_high", 1.0)
        self.update_ratio_low = None if low is None else float(low)
        self.update_ratio_high = None if high is None else float(high)
        if (
            self.update_ratio_low is not None
            and self.update_ratio_high is not None
            and self.update_ratio_low >= self.update_ratio_high
        ):
            raise ValueError(
                "diagnostics.health.detectors.update_ratio_low must be < update_ratio_high, "
                f"got {low!r} >= {high!r}"
            )
        dead_max = det.get("dead_frac_max", 0.95)
        self.dead_frac_max = None if dead_max is None else float(dead_max)
        self.plateau_key = det.get("plateau_key")
        self.plateau_window = int(det.get("plateau_window", 20))
        if self.plateau_window < 2:
            raise ValueError(
                f"diagnostics.health.detectors.plateau_window must be >= 2, "
                f"got {det.get('plateau_window')!r}"
            )
        rtol = det.get("plateau_rtol", 1e-3)
        self.plateau_rtol = None if rtol is None else float(rtol)
        inject = health_cfg.get("inject_entropy_collapse_iter")
        self.inject_entropy_collapse_iter = None if inject is None else int(inject)
        if self.enabled and self.inject_entropy_collapse_iter is not None and self.entropy_floor is None:
            # the drill forces the watched metric to 0, but the detector only
            # observes it when a floor is armed — an injection that cannot
            # fire must fail loudly, not journal a fault_injection event that
            # falsely validates the alerting chain
            raise ValueError(
                "diagnostics.health.inject_entropy_collapse_iter is set but "
                "diagnostics.health.detectors.entropy_floor is null — the entropy-collapse "
                "detector is disarmed and the drill could never fire; set a floor "
                "(e.g. detectors.entropy_floor=0.05)"
            )

        self._lock = threading.Lock()
        self._journal_fn: Optional[Callable[..., None]] = None
        self._sync_fn: Optional[Callable[[], None]] = None
        self._opened = False
        self._latest: Dict[str, float] = {}
        # per-detector state, keyed (kind, subject)
        self._windows: Dict[Tuple[str, str], deque] = {}
        self._breaches: Dict[Tuple[str, str], int] = {}
        self._active: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._anomalies_total = 0
        self._observe_calls = 0
        self._injecting = False

    # -- lifecycle ---------------------------------------------------------
    def open(
        self,
        journal_fn: Optional[Callable[..., None]] = None,
        sync_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        if self._opened:
            return
        self._journal_fn = journal_fn
        self._sync_fn = sync_fn
        self._opened = True

    def _journal(self, event: str, **fields: Any) -> None:
        if self._journal_fn is not None:
            self._journal_fn(event, **fields)

    # -- detector core ------------------------------------------------------
    def _observe_value(
        self,
        kind: str,
        subject: str,
        value: float,
        breach: bool,
        step: Optional[int],
        required: Optional[int] = None,
        window: Optional[deque] = None,
        **payload: Any,
    ) -> None:
        """One observation of one watched series (caller holds the lock).

        Journals the flood-controlled ``anomaly`` (fsync'd, with the
        offending window) after ``required`` consecutive breaches (default:
        the configured ``confirm``), and ``anomaly_end`` on the first clean
        observation while active.  A caller that maintains its own window
        (the plateau detector, whose window IS the confirmation) passes it
        in; otherwise a per-key recent-values deque is kept here.
        """
        key = (kind, subject)
        if window is None:
            window = self._windows.setdefault(key, deque(maxlen=self.WINDOW_KEEP))
            window.append(round(float(value), 6))
        required = self.confirm if required is None else required
        if breach:
            self._breaches[key] = self._breaches.get(key, 0) + 1
            if key not in self._active and self._breaches[key] >= required:
                self._active[key] = {"since_step": step}
                self._anomalies_total += 1
                self._journal(
                    "anomaly",
                    kind=kind,
                    subject=subject,
                    step=step,
                    value=round(float(value), 6),
                    window=list(window),
                    confirm=required,
                    **payload,
                )
                if self._sync_fn is not None:
                    # the whole point is catching a run that dies wastefully:
                    # the record must survive a SIGKILL right after it fires
                    self._sync_fn()
        else:
            self._breaches[key] = 0
            if key in self._active:
                since = self._active.pop(key).get("since_step")
                self._journal(
                    "anomaly_end",
                    kind=kind,
                    subject=subject,
                    step=step,
                    since_step=since,
                    value=round(float(value), 6),
                )

    # -- feeds --------------------------------------------------------------
    def on_stats(self, step: Optional[int], stats: Mapping[str, Any]) -> None:
        """Digest one fetched train-step stats dict (from ``health_stats``)."""
        if not self._opened or not stats:
            return
        clean: Dict[str, float] = {}
        for key, value in stats.items():
            try:
                clean[str(key)] = float(value)
            except (TypeError, ValueError):
                continue
        if not clean:
            return
        with self._lock:
            self._latest.update(clean)
            ratio = clean.get("update_ratio")
            if ratio is not None and (
                self.update_ratio_low is not None or self.update_ratio_high is not None
            ):
                low_breach = self.update_ratio_low is not None and ratio < self.update_ratio_low
                high_breach = self.update_ratio_high is not None and ratio > self.update_ratio_high
                self._observe_value(
                    "update_ratio_band",
                    "update_ratio",
                    ratio,
                    low_breach or high_breach,
                    step,
                    low=self.update_ratio_low,
                    high=self.update_ratio_high,
                )
            if self.dead_frac_max is not None:
                for key, value in clean.items():
                    if key == "dead_frac":
                        subject = "dead_frac"
                    elif key.startswith("module/") and key.endswith("/dead_frac"):
                        subject = key
                    else:
                        continue
                    self._observe_value(
                        "dead_gradient",
                        subject,
                        value,
                        value >= self.dead_frac_max,
                        step,
                        max=self.dead_frac_max,
                    )
            ev = clean.get("value_ev")
            if ev is not None and self.value_ev_floor is not None:
                self._observe_value(
                    "value_ev_floor",
                    "value_ev",
                    ev,
                    ev < self.value_ev_floor,
                    step,
                    floor=self.value_ev_floor,
                )

    def observe_metrics(self, step: Optional[int], metrics: Mapping[str, Any]) -> None:
        """Digest one aggregated-metrics interval (called at every log
        boundary, after the gauges were merged)."""
        if not self._opened:
            return
        import numpy as np

        with self._lock:
            self._observe_calls += 1
            call = self._observe_calls
            inject = (
                self.inject_entropy_collapse_iter is not None
                and self.inject_entropy_collapse_iter <= call
                < self.inject_entropy_collapse_iter + self.confirm
            )
            if inject and not self._injecting:
                self._injecting = True
                self._journal(
                    "fault_injection",
                    iter_num=call,
                    kind="entropy_collapse",
                    intervals=self.confirm,
                )
            if self.entropy_key and self.entropy_floor is not None:
                value = metrics.get(self.entropy_key)
                if inject:
                    value = 0.0
                if isinstance(value, (int, float)) and np.isfinite(float(value)):
                    # magnitude floor: collapse drives both true-entropy and
                    # negative-entropy (Loss/entropy_loss) metrics toward 0
                    self._observe_value(
                        "entropy_collapse",
                        self.entropy_key,
                        float(value),
                        abs(float(value)) < abs(self.entropy_floor),
                        step,
                        floor=self.entropy_floor,
                    )
            if self.plateau_key and self.plateau_rtol is not None:
                value = metrics.get(self.plateau_key)
                if isinstance(value, (int, float)) and np.isfinite(float(value)):
                    key = ("loss_plateau", str(self.plateau_key))
                    window = self._windows.setdefault(key, deque(maxlen=self.plateau_window))
                    window.append(round(float(value), 6))
                    full = len(window) == self.plateau_window
                    scale = max(float(np.median(np.abs(np.asarray(window)))), 1e-12)
                    spread = (max(window) - min(window)) / scale if full else float("inf")
                    # the plateau window IS the confirmation window (breach =
                    # "the last plateau_window values moved < rtol"), so one
                    # breaching observation fires: required=1
                    self._observe_value(
                        "loss_plateau",
                        str(self.plateau_key),
                        float(value),
                        full and spread < self.plateau_rtol,
                        step,
                        required=1,
                        window=window,
                        rtol=self.plateau_rtol,
                        spread=round(spread, 8) if full else None,
                    )

    def open_anomaly_kinds(self) -> List[str]:
        """Sorted kinds of the currently-active anomalies (the decoupled
        promotion gate's "open sentinel anomaly" veto signal — cheap enough
        to consult once per trainer iteration)."""
        if not self._opened:
            return []
        with self._lock:
            return sorted({kind for kind, _subject in self._active})

    # -- gauges / snapshots --------------------------------------------------
    def interval_metrics(self) -> Dict[str, float]:
        """The ``Telemetry/health/*`` gauges merged into every metric
        interval: the latest stats (per-module detail included when the spec
        collects it) plus the live active-anomaly count."""
        if not self._opened:
            return {}
        with self._lock:
            if not self._latest and not self._anomalies_total:
                return {}
            out = {HEALTH_PREFIX + k: v for k, v in self._latest.items()}
            out[HEALTH_PREFIX + "anomalies"] = float(len(self._active))
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The fixed scalar subset for ``/metrics`` (per-module detail stays
        journal/TB-only: Prometheus series must come from the registered
        vocabulary — see ``schema.METRICS``)."""
        with self._lock:
            gauges: Dict[str, float] = {}
            for stat in _SCALAR_GAUGES:
                if stat in self._latest:
                    gauges[HEALTH_PREFIX + stat] = self._latest[stat]
            gauges[HEALTH_PREFIX + "anomalies"] = float(len(self._active))
            counters = {"health_anomalies_total": self._anomalies_total}
            active = ",".join(sorted(f"{kind}:{subject}" for kind, subject in self._active))
            info = {"health_active_anomalies": active or None}
        return {"gauges": gauges, "counters": counters, "info": info}

    def summary(self) -> Dict[str, Any]:
        """Run totals folded into the closing ``telemetry_summary`` event."""
        with self._lock:
            return {
                "health_anomalies": self._anomalies_total,
                "health_anomalies_open": len(self._active),
            }


# ---------------------------------------------------------------------------
# journal-side helpers (shared by report.py, tools/health_report.py and
# tools/health_diff.py — do NOT re-inline this logic)
# ---------------------------------------------------------------------------


def metric_series(
    events: List[Dict[str, Any]], name: str
) -> List[Tuple[Optional[float], float]]:
    """``[(step, value)]`` trajectory of one metric over a journal's
    ``metrics`` events (non-numeric values — the journal's "nan"/"inf"
    strings included — are skipped)."""
    out: List[Tuple[Optional[float], float]] = []
    for event in events:
        if event.get("event") != "metrics":
            continue
        value = (event.get("metrics") or {}).get(name)
        if isinstance(value, (int, float)):
            step = event.get("step")
            out.append((float(step) if isinstance(step, (int, float)) else None, float(value)))
    return out


def watched_metric_names(events: List[Dict[str, Any]], prefixes: Sequence[str]) -> List[str]:
    """Sorted union of metric names matching any watch prefix (an exact name
    is its own prefix) over a journal's metrics events."""
    names: set = set()
    for event in events:
        if event.get("event") != "metrics":
            continue
        for name, value in (event.get("metrics") or {}).items():
            if isinstance(value, (int, float)) and any(name.startswith(p) for p in prefixes):
                names.add(name)
    return sorted(names)


def active_anomalies(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Anomaly events without a matching ``anomaly_end`` (keyed kind+subject),
    in firing order — what the ``!! ANOMALY`` banner reports."""
    open_by_key: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for event in events:
        kind = event.get("event")
        if kind not in ("anomaly", "anomaly_end"):
            continue
        key = (str(event.get("kind")), str(event.get("subject")))
        if kind == "anomaly":
            open_by_key[key] = event
        else:
            open_by_key.pop(key, None)
    return sorted(open_by_key.values(), key=lambda e: e.get("t") or 0.0)
