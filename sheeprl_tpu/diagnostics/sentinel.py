"""NaN/divergence sentinel: in-graph finiteness guards + host-side detector.

Two layers, addressing the two documented failure modes:

* **In-graph** (jit-compatible, runs inside the algorithms' train steps): the
  losses and the global gradient norm are reduced to a single finiteness flag
  per optimizer step.  Under ``policy=skip_update`` the already-computed
  parameter/optimizer-state update is discarded via ``jnp.where`` selection —
  a poisoned batch then costs one wasted step instead of a corrupted run.
  The flag and the grad norm ride the step's metric vector back to the host,
  so ``warn``/``halt`` need no extra device fetch.
* **Host-side** (:class:`DivergenceDetector`): rolling-window checks on the
  aggregated metric stream at each log boundary — policy-entropy floor (the
  pixel-CartPole ent_coef=3e-4 collapse mode) and loss-explosion ratio versus
  the window median.  Findings are returned as structured ``divergence``
  events for the run journal; the detector never stops a run by itself.

The in-graph pieces are pure functions of :class:`SentinelSpec`, a hashable
trace-time constant, so ``make_train_step`` builders can read it from ``cfg``
without threading new arguments through ``shard_map``/``jit`` signatures.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Mapping, NamedTuple, Optional, Sequence

VALID_POLICIES = ("warn", "skip_update", "halt")


class SentinelHalt(RuntimeError):
    """Raised (host-side) when a non-finite update lands under ``policy=halt``."""


class SentinelSpec(NamedTuple):
    """Trace-time sentinel configuration for the jitted train steps."""

    enabled: bool = False
    policy: str = "warn"
    inject_nan_iter: Optional[int] = None

    @property
    def skip_update(self) -> bool:
        return self.enabled and self.policy == "skip_update"


def sentinel_spec(cfg: Mapping[str, Any]) -> SentinelSpec:
    """Extract the :class:`SentinelSpec` from a composed run config.

    Tolerates configs without a ``diagnostics`` section (bench.py and the HLO
    tests compose partial configs and call ``make_train_step`` directly):
    missing means disabled, which keeps those compiled graphs byte-identical.
    """
    diag = cfg.get("diagnostics") or {}
    sent = diag.get("sentinel") or {}
    enabled = bool(diag.get("enabled", False)) and bool(sent.get("enabled", False))
    policy = str(sent.get("policy", "warn"))
    if policy not in VALID_POLICIES:
        raise ValueError(f"diagnostics.sentinel.policy must be one of {VALID_POLICIES}, got {policy!r}")
    inject = sent.get("inject_nan_iter")
    return SentinelSpec(enabled=enabled, policy=policy, inject_nan_iter=None if inject is None else int(inject))


# --------------------------------------------------------------------------
# jit-compatible helpers (imported lazily-by-caller inside train steps)
# --------------------------------------------------------------------------


def finite_flag(*scalars):
    """``True`` iff every scalar in ``scalars`` is finite (jit-compatible).

    Checking the *global grad norm* instead of every gradient leaf is both
    cheaper and equivalent for this purpose: any NaN/Inf leaf makes the norm
    NaN/Inf.
    """
    import jax.numpy as jnp

    return jnp.all(jnp.isfinite(jnp.stack([jnp.asarray(s, jnp.float32).reshape(()) for s in scalars])))


def tree_all_finite(tree):
    """Finiteness flag over every floating leaf of a pytree (jit-compatible)."""
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(tree) if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    if not leaves:
        return jnp.asarray(True)
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(l)) for l in leaves]))


def select_finite(finite, new_tree, old_tree):
    """Per-leaf ``where(finite, new, old)`` — the skip_update selection.

    ``finite`` is a scalar bool; broadcasting keeps this one fused select per
    leaf, and NaNs in the rejected branch are inert under ``where``.
    """
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda n, o: jnp.where(finite, n, o), new_tree, old_tree)


def poison_tree(tree):
    """Replace every floating leaf with NaNs (fault injection for tests).

    Shapes/dtypes (and therefore compiled graphs) are unchanged; integer and
    bool leaves pass through so index/one-hot inputs stay valid.
    """
    import jax
    import jax.numpy as jnp

    def _poison(leaf):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.inexact):
            return jnp.full(arr.shape, jnp.nan, arr.dtype)
        return leaf

    return jax.tree_util.tree_map(_poison, tree)


# --------------------------------------------------------------------------
# Host-side rolling divergence detector
# --------------------------------------------------------------------------


class DivergenceDetector:
    """Rolling-window divergence checks over the aggregated metric stream.

    Fed once per log boundary (so windows are cheap and host-side only);
    returns structured event dicts for the journal:

    * ``entropy_collapse`` — ``entropy_key``'s *magnitude* falls below
      ``entropy_floor``.  Collapse drives the policy entropy toward 0, which
      is a shrinking magnitude both for true-entropy metrics and for
      PPO-style ``Loss/entropy_loss`` (negative entropy), so one floor works
      for either sign convention.
    * ``loss_explosion`` — a watched ``Loss/*`` metric jumps above
      ``loss_explosion_ratio`` x its rolling median magnitude.
    * ``nonfinite_metric`` — a watched metric arrives as NaN/Inf (aggregators
      normally drop NaNs before logging, so this mostly fires via the raw
      journal path).
    """

    def __init__(
        self,
        window: int = 20,
        min_points: int = 5,
        loss_explosion_ratio: float = 10.0,
        entropy_key: Optional[str] = None,
        entropy_floor: Optional[float] = None,
        watch_prefixes: Sequence[str] = ("Loss/",),
    ):
        if window < 2:
            raise ValueError(f"divergence window must be >= 2, got {window}")
        self._window = int(window)
        self._min_points = max(2, int(min_points))
        self._ratio = float(loss_explosion_ratio) if loss_explosion_ratio else 0.0
        self._entropy_key = entropy_key
        self._entropy_floor = None if entropy_floor is None else float(entropy_floor)
        self._watch_prefixes = tuple(watch_prefixes)
        self._history: Dict[str, deque] = {}

    def _watched(self, name: str) -> bool:
        return any(name.startswith(p) for p in self._watch_prefixes)

    def observe(self, step: int, metrics: Mapping[str, Any]) -> List[Dict[str, Any]]:
        import numpy as np

        events: List[Dict[str, Any]] = []
        for name, value in metrics.items():
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            if name == self._entropy_key and self._entropy_floor is not None and np.isfinite(v):
                if abs(v) < abs(self._entropy_floor):
                    events.append(
                        {
                            "kind": "entropy_collapse",
                            "metric": name,
                            "value": v,
                            "floor": self._entropy_floor,
                            "step": step,
                        }
                    )
            if not self._watched(name):
                continue
            if not np.isfinite(v):
                events.append({"kind": "nonfinite_metric", "metric": name, "value": v, "step": step})
                continue
            hist = self._history.setdefault(name, deque(maxlen=self._window))
            if self._ratio and len(hist) >= self._min_points:
                baseline = float(np.median(np.abs(np.asarray(hist))))
                if baseline > 1e-8 and abs(v) > self._ratio * baseline:
                    events.append(
                        {
                            "kind": "loss_explosion",
                            "metric": name,
                            "value": v,
                            "baseline_median": baseline,
                            "ratio": abs(v) / baseline,
                            "step": step,
                        }
                    )
            hist.append(v)
        return events
