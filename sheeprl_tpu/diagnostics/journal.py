"""Crash-safe run journal: a write-ahead JSONL event/metric log.

The repo's own history motivates this file: reward logs were lost badly enough
to need a recovery toolchain (``tools/recover_rewards.py``,
``REWARD_RECOVERY_GUIDE.md``), and the collapsed pixel-CartPole run could only
be diagnosed post-hoc from TensorBoard event archaeology.  The journal is the
prevention side of that story: every aggregated metric, checkpoint event,
divergence event and step counter is appended as one JSON object per line and
flushed (+fsync) as it is written, so a SIGKILL at any instant leaves at most
one truncated *trailing* line — which :func:`read_journal` skips — and the
run's history up to the last log interval survives verbatim.

Writer protocol (one event per line):

``{"t": <unix time>, "event": "<type>", ...}``

The event-kind vocabulary is declared centrally in
:data:`sheeprl_tpu.diagnostics.schema.EVENT_KINDS` (one description per
kind); the JRN pass of ``tools/sheeprl_lint.py`` statically verifies that
every ``write("<kind>", ...)`` call site in the tree uses a registered kind
and that the ``howto/diagnostics.md`` event table matches the registry.
Rank gating lives in the facade: under ``jax.distributed`` only the global
rank-0 host owns a writer.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence

JOURNAL_NAME = "journal.jsonl"


def _sanitize(value: Any) -> Any:
    """Make ``value`` strict-JSON serializable.

    Non-finite floats become the strings ``"nan"`` / ``"inf"`` / ``"-inf"``
    (``json.dumps`` would otherwise emit bare ``NaN`` tokens that strict
    parsers reject); numpy scalars/arrays collapse to Python scalars/lists.
    """
    if isinstance(value, dict):
        return {str(k): _sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    if isinstance(value, bool) or value is None or isinstance(value, (int, str)):
        return value
    if isinstance(value, float):
        if math.isnan(value):
            return "nan"
        if math.isinf(value):
            return "inf" if value > 0 else "-inf"
        return value
    # numpy scalars / 0-d arrays / jax host scalars
    item = getattr(value, "item", None)
    if item is not None:
        try:
            return _sanitize(item())
        except Exception:
            pass
    tolist = getattr(value, "tolist", None)
    if tolist is not None:
        try:
            return _sanitize(tolist())
        except Exception:
            pass
    return str(value)


class RunJournal:
    """Append-only JSONL writer with per-event flush and fsync.

    ``fsync_every`` counts journal *events*: the facade writes one ``metrics``
    event per log interval, so the default of 1 is an fsync per log interval —
    the durability the ISSUE asks for — at a rate (one per
    ``metric.log_every`` policy steps) where fsync cost is irrelevant.
    """

    def __init__(self, path: str, fsync_every: int = 1):
        self.path = str(path)
        self._fsync_every = max(0, int(fsync_every))
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        self._fp = open(self.path, "a", encoding="utf-8")
        self._count = 0
        self._closed = False
        # the loop thread is no longer the only writer: the stall watchdog
        # and the metrics-server HTTP threads journal concurrently, and an
        # interleaved fp.write would corrupt the line framing
        self._lock = threading.Lock()
        # wall-clock of the newest write: the /metrics endpoint exposes
        # now - last_write_t as sheeprl_journal_lag_seconds (stall detector)
        self.last_write_t: Optional[float] = None

    def write(self, event: str, **fields: Any) -> None:
        record: Dict[str, Any] = {"t": round(time.time(), 3), "event": str(event)}
        record.update(_sanitize(fields))
        line = json.dumps(record, separators=(",", ":")) + "\n"
        with self._lock:
            if self._closed:
                return
            self.last_write_t = time.time()
            self._fp.write(line)
            self._fp.flush()
            self._count += 1
            if self._fsync_every and self._count % self._fsync_every == 0:
                try:
                    os.fsync(self._fp.fileno())
                except OSError:  # pragma: no cover - exotic filesystems
                    pass

    def sync(self) -> None:
        """Force buffered events to disk regardless of the fsync cadence —
        the OOM-forensics and stall paths call this so the post-mortem record
        survives the process dying immediately afterwards."""
        with self._lock:
            if self._closed:
                return
            try:
                self._fp.flush()
                os.fsync(self._fp.fileno())
            except (OSError, ValueError):  # pragma: no cover
                pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._fp.flush()
                os.fsync(self._fp.fileno())
            except (OSError, ValueError):  # pragma: no cover
                pass
            self._fp.close()


def iter_journal(path: str) -> Iterator[Dict[str, Any]]:
    """Yield events from a journal, tolerating a crash-truncated tail.

    A SIGKILL can only leave a partial *last* line (writes are line-buffered
    and flushed whole); a decode error there is silently skipped.  A decode
    error mid-file means external corruption — that line is skipped too, so
    one bad sector never makes the rest of the history unreadable.
    """
    with open(path, encoding="utf-8") as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(event, dict):
                yield event


def read_journal(path: str) -> List[Dict[str, Any]]:
    return list(iter_journal(path))


def find_journal(run_path: str) -> Optional[str]:
    """Locate a journal under a run directory (or pass a file through).

    Accepts the journal file itself, a ``version_N`` dir, or any ancestor run
    dir — the newest ``journal.jsonl`` below wins, matching how
    ``recover_reward_logs.py`` walks ``logs/runs/``.
    """
    if os.path.isfile(run_path):
        return run_path
    candidates = []
    for root, _, files in os.walk(run_path):
        if JOURNAL_NAME in files:
            candidates.append(os.path.join(root, JOURNAL_NAME))
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)


def collect_journals(paths: Sequence[str]) -> List[str]:
    """Expand files/run dirs into ALL journal files below them (sorted,
    de-duplicated) — unlike :func:`find_journal`, every segment of a resumed
    run is kept: ``tools/goodput_report.py`` groups the ``version_N``
    siblings into one logical run, and ``tools/trace_report.py`` reads them
    for the run-state overlay."""
    out: List[str] = []
    for path in paths:
        # normalized so the same journal reached via different spellings
        # (explicit file arg vs. a dir walk) de-duplicates to one entry
        if os.path.isfile(path):
            out.append(os.path.abspath(path))
        elif os.path.isdir(path):
            for root, _, files in os.walk(path):
                if JOURNAL_NAME in files:
                    out.append(os.path.abspath(os.path.join(root, JOURNAL_NAME)))
    seen, unique = set(), []
    for path in sorted(out):
        if path not in seen:
            seen.add(path)
            unique.append(path)
    return unique
