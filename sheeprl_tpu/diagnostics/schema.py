"""Central registry of the diagnostics subsystem's wire formats.

Every journal event kind any module may write and every Prometheus metric
name the ``/metrics`` endpoint may expose is declared HERE, once, with a
one-line description.  Three consumers keep the registry honest:

* the runtime — :mod:`~sheeprl_tpu.diagnostics.journal`,
  :mod:`~sheeprl_tpu.diagnostics.memory` and
  :mod:`~sheeprl_tpu.diagnostics.metrics_server` import their event/metric
  vocabularies from this module instead of re-declaring them;
* the static analyzer — the JRN pass of ``tools/sheeprl_lint.py`` parses this
  file (AST only, no import) and fails when any ``journal.write("<kind>")``
  call site in the tree uses a kind missing from :data:`EVENT_KINDS`, or when
  a gauge/counter literal in the diagnostics package does not resolve to a
  :data:`METRICS` entry prefixed ``sheeprl_``;
* the docs — the event table in ``howto/diagnostics.md`` is verified against
  :data:`EVENT_KINDS` (same JRN pass), so adding an event kind here without
  documenting it is a lint failure, not silent drift.

To add a journal event kind: add it to :data:`EVENT_KINDS`, emit it, and add
a row to the ``howto/diagnostics.md`` table.  To add a ``/metrics`` name: add
the full exported name (``sheeprl_*``) to :data:`METRICS`.  The lint tells
you which of the three places you forgot.
"""

from __future__ import annotations

from typing import Dict, Tuple

#: Exported Prometheus names all start with this (the ``emit`` helper in
#: :mod:`~sheeprl_tpu.diagnostics.metrics_server` prefixes it).
METRIC_PREFIX = "sheeprl_"

#: Every journal event kind -> one-line description (the howto table's text).
EVENT_KINDS: Dict[str, str] = {
    "run_start": "config hash, algo/env/seed, run identity, sentinel policy",
    "metrics": "every aggregated metric interval, keyed by the policy-step counter",
    "checkpoint": "step + checkpoint path",
    "divergence": "structured sentinel/detector findings",
    "fault_injection": "a test-only fault fired (NaN poison, shape change, transfer/OOM drill)",
    "recompile": "watchdog: a new dispatch signature, with the per-leaf shape/dtype diff",
    "recompile_storm": "watchdog: recompile rate crossed the storm threshold",
    "telemetry_cost": "compiled-step cost_analysis FLOPs for one instrumented signature",
    "telemetry_fallback": "AOT compile/dispatch failed; the step reverted to native jit dispatch",
    "metrics_server": "the /metrics endpoint address (or its bind failure)",
    "compilation_cache": "JAX on-disk compilation cache enabled (directory recorded)",
    "aot_cache_hit": "persistent AOT executable cache: a serialized executable was loaded instead of compiling (fn, entry path, FLOPs)",
    "aot_cache_miss": "persistent AOT executable cache: no usable entry — reason absent/corrupt/fingerprint_mismatch/store_failed — so a fresh compile ran",
    "telemetry_summary": "closing perf totals (recompiles, compile time, FLOPs, phase seconds)",
    "memory_breakdown": "one-shot static footprint decomposition at first train dispatch",
    "sharding_audit": "per-leaf bytes/sharding table of the first train dispatch",
    "fsdp_shard_map": "FSDP partition-rule layout of the train state: axis size, min_shard_bytes, per-tree sharded/replicated leaf counts and global vs per-device bytes",
    "donation_miss": "declared donations whose buffers were still alive after dispatch",
    "host_transfer": "a transfer-guard trip (device<->host sync) with provenance",
    "oom": "RESOURCE_EXHAUSTED forensics: full memory snapshot, fsync'd before re-raise",
    "memory_summary": "closing memory totals (peaks, guard trips, donation misses)",
    "state_change": "run-state machine transition (steady states at first entry only; stall transitions always)",
    "stall": "watchdog: no progress for stall_threshold_s — all-thread stacks, last state, idle seconds (fsync'd)",
    "stall_end": "the stalled run made progress again (seconds stalled, restored state)",
    "profile_capture": "auto (on stall) or on-demand (/profile) jax.profiler capture: status ok/busy/failed + directory",
    "anomaly": "learning-health detector fired after `confirm` consecutive breaches — kind, subject, offending window (fsync'd)",
    "anomaly_end": "the anomalous learning-health condition cleared (kind, subject, step it started at)",
    "serve_start": "the policy server came up: algo, served checkpoint/step, bind address, batch buckets, watched dir",
    "ckpt_promote": "hot-reload promoted a new checkpoint (step, path, params version) — atomic swap, no recompile",
    "ckpt_reject": "hot-reload refused a checkpoint: health-gate anomalies, shape mismatch, or missing journal",
    "session_evict": "serving session layer: the LRU session lost its state-slab slot to a new session (session, slot, model, resident count vs capacity)",
    "slo_breach": "serving SLO: the rolling burn rate stayed > 1.0 for `confirm` consecutive requests — model, burn, target_ms, objective, window (fsync'd)",
    "slo_breach_end": "the serving SLO burn rate recovered to <= 1.0 (model, burn, seconds the breach lasted)",
    "slow_request": "serving forensics: one request exceeded slo.slow_trace_ms — request id, model, full per-phase breakdown, batch width, queue depth at enqueue, session-eviction status (fsync'd)",
    "request_log_rotate": "serving request log: one shard of /act traffic rotated to disk (model, stream, rows, bytes, shard path) — or dropped=true when the writer queue was full",
    "ckpt_begin": "a checkpoint write started (path, step, blocking flag, seconds queued behind the async writer)",
    "ckpt_end": "a checkpoint write finished: bytes, write ms, manifest verified — or status=failed with the error",
    "ckpt_skipped": "resume selection rejected a checkpoint (corrupt / truncated / unreadable / incomplete_group) with the reason",
    "params_reject": "decoupled promotion gate fenced a trainer update off the player: reason, step, staleness vs budget (escalate=true on the budget-exhausting rejection, fsync'd)",
    "rollback": "quarantined train-step failure absorbed: trainer params+opt_state restored from the last-good snapshot — error, restored iteration, retries left (fsync'd)",
    "dataset_export": "replay experience exported as dataset shards (rows/bytes/shards written, cumulative totals, dataset path)",
    "dataset_open": "offline training opened a dataset: verified streams/segments/shards/rows/bytes and how many shards were skipped",
    "dataset_shard_skipped": "dataset open rejected a torn/corrupt shard (no_manifest / size_mismatch / digest_mismatch) with the reason",
    "preempted": "graceful preemption: emergency snapshot landed at a loop boundary; the process exits with code 75 (fsync'd)",
    "restart": "supervisor respawned the run after a non-clean exit: attempt, rc, backoff, measured downtime, resume source",
    "run_end": "completed / halted / aborted / preempted — absent after a kill",
}

#: Journal event kinds emitted by the memory monitor (handler routing in the
#: facade and the ``tools/memory_report.py`` views key off this subset).
MEMORY_EVENTS: Tuple[str, ...] = (
    "memory_breakdown",
    "sharding_audit",
    "donation_miss",
    "host_transfer",
    "oom",
)

#: Every metric name the /metrics endpoint may export -> description.
#: Names are the FULL exported spelling (``sheeprl_`` prefix included); the
#: snapshot-dict keys that produce them are mapped through
#: :func:`sheeprl_tpu.diagnostics.metrics_server._metric_name`.
METRICS: Dict[str, str] = {
    # fixed series emitted by metrics_server.render_prometheus
    "sheeprl_up": "1 while the training process serves the endpoint",
    "sheeprl_run_info": "run identity as labels (value is always 1)",
    "sheeprl_policy_steps_total": "policy steps taken (env frames / action_repeat)",
    "sheeprl_phase_seconds_total": "cumulative wall-clock per host phase (label: phase)",
    "sheeprl_journal_lag_seconds": "seconds since the last journal write",
    # telemetry counters (Telemetry.snapshot()["counters"])
    "sheeprl_recompiles_total": "watchdog: new dispatch signatures seen",
    "sheeprl_recompile_storms_total": "watchdog: storm threshold crossings",
    "sheeprl_backend_compiles_total": "jax.monitoring backend compile events",
    "sheeprl_compile_seconds_total": "cumulative backend compile wall-clock",
    "sheeprl_sentinel_events_total": "journaled divergence/sentinel findings",
    "sheeprl_train_flops_total": "cumulative FLOPs dispatched through kind=train steps",
    "sheeprl_env_steps_total": "cumulative environment steps taken by the player",
    "sheeprl_dataset_rows_read_total": "offline mode: transitions streamed from the dataset loader",
    # memory counters (MemoryMonitor.snapshot()["counters"])
    "sheeprl_host_transfers_total": "transfer-guard trips journaled",
    "sheeprl_donation_miss_leaves_total": "leaves that missed a declared donation",
    "sheeprl_oom_events_total": "RESOURCE_EXHAUSTED events journaled",
    # goodput counters (GoodputMonitor.snapshot()["counters"])
    "sheeprl_stalls_total": "stall-watchdog firings (no progress for stall_threshold_s)",
    "sheeprl_stalled_seconds_total": "cumulative seconds spent in the stalled state",
    "sheeprl_profile_captures_total": "successful jax.profiler captures (auto on stall + /profile)",
    # learning-health counters (HealthMonitor.snapshot()["counters"])
    "sheeprl_health_anomalies_total": "anomaly events journaled by the learning-health detectors",
    # resilience counters (ResilienceMonitor.snapshot()["counters"])
    "sheeprl_ckpts_written_total": "checkpoints written (async or blocking) with a verified manifest sidecar",
    "sheeprl_ckpt_failures_total": "checkpoint writes that failed (journaled as ckpt_end status=failed)",
    "sheeprl_ckpt_write_seconds_total": "cumulative serialize+fsync wall-clock spent writing checkpoints",
    "sheeprl_restarts_total": "kill/resume cycles the supervisor performed before this process (SHEEPRL_SUPERVISOR_RESTARTS)",
    "sheeprl_params_rejected_total": "trainer updates the decoupled promotion gate fenced off the player (params_reject events)",
    "sheeprl_rollbacks_total": "quarantined train-step failures absorbed by restoring the last-good snapshot (rollback events)",
    # interval gauges (Telemetry/... keys, prefix-stripped and sanitized)
    "sheeprl_mfu": "model FLOPs utilization vs the device-kind peak",
    "sheeprl_tflops_per_sec": "achieved TFLOP/s over the last interval",
    "sheeprl_sps": "policy steps per second over the last interval",
    "sheeprl_env_steps_per_sec": "environment steps per second over the last interval",
    "sheeprl_fetch_amortization": "env steps amortized by each blocking action fetch",
    "sheeprl_dataset_read_sps": "offline mode: dataset transitions streamed per second over the last interval",
    "sheeprl_dataset_epoch": "offline mode: the loader's pass counter over the dataset (deterministic per-epoch shuffle)",
    "sheeprl_recompiles": "recompiles within the last interval",
    "sheeprl_compile_count": "backend compiles within the last interval",
    "sheeprl_compile_time_s": "backend compile seconds within the last interval",
    "sheeprl_phase_pct_train": "interval wall-clock share: train dispatch+fetch",
    "sheeprl_phase_pct_env": "interval wall-clock share: env stepping",
    "sheeprl_phase_pct_fetch": "interval wall-clock share: metric/buffer fetch",
    "sheeprl_phase_pct_other": "interval wall-clock share: other instrumented spans",
    "sheeprl_phase_pct_idle": "interval wall-clock share: un-instrumented host time",
    # resilience gauges (checkpoint freshness; run_monitor --url keys its
    # !! NO-RECENT-CKPT banner off these)
    "sheeprl_ckpt_last_step": "policy step of the newest verified checkpoint written by this run",
    "sheeprl_ckpt_age_seconds": "seconds since the newest verified checkpoint landed on disk",
    "sheeprl_ckpt_interval_seconds": "seconds between the last two checkpoint writes (the observed cadence)",
    "sheeprl_param_staleness": "decoupled fencing: consecutive trainer updates the player has been held back from (0 = acting on fresh params)",
    "sheeprl_param_staleness_budget": "decoupled fencing: the configured max_staleness budget the staleness gauge escalates against",
    # goodput gauges (run lifecycle layer, prefix-stripped)
    "sheeprl_run_state": "run-state machine index into goodput.STATES (5 = stalled)",
    "sheeprl_goodput": "cumulative productive share since open: train-span seconds / wall seconds",
    "sheeprl_time_to_first_step": "seconds from diagnostics open to the first completed train dispatch",
    # learning-health gauges (Telemetry/health/*, prefix-stripped; the
    # per-module detail keys stay journal/TB-only — /metrics exports exactly
    # this scalar subset)
    "sheeprl_health_grad_norm": "latest global gradient L2 norm from the in-graph health stats",
    "sheeprl_health_update_norm": "latest global parameter-update L2 norm",
    "sheeprl_health_param_norm": "latest global parameter L2 norm",
    "sheeprl_health_update_ratio": "latest update-to-weight ratio (update_norm / param_norm)",
    "sheeprl_health_dead_frac": "latest fraction of units whose gradients are ~zero",
    "sheeprl_health_value_ev": "latest value-function explained variance (ppo/a2c)",
    "sheeprl_health_anomalies": "learning-health anomalies currently active",
    # memory gauges (Telemetry/hbm_* etc., prefix-stripped)
    "sheeprl_fsdp_axis_size": "extent of the FSDP ('model') mesh axis this run shards params over (absent on pure-DP runs)",
    "sheeprl_params_bytes_per_device": "param bytes one device holds under the FSDP partition rule (vs the replicated global size)",
    "sheeprl_hbm_bytes_in_use": "per-device HBM bytes in use (max over devices)",
    "sheeprl_hbm_peak_bytes": "per-device HBM peak bytes (max over devices)",
    "sheeprl_hbm_largest_alloc_bytes": "largest single HBM allocation",
    "sheeprl_host_rss_bytes": "host process resident set size",
    "sheeprl_replay_host_bytes": "replay buffer bytes resident in host RAM",
    "sheeprl_replay_disk_bytes": "replay buffer bytes memmapped on disk",
    "sheeprl_replay_device_bytes": "replay buffer bytes resident in HBM",
    "sheeprl_replay_dataset_disk": "bytes of exported dataset shards attributed to the tracked replay buffer",
    # serving tier (sheeprl_tpu/serving/server.py snapshot; the serve
    # /metrics endpoint reuses render_prometheus, so the same naming rules
    # apply — tools/run_monitor.py --url keys its serving panel off these)
    "sheeprl_serve_requests_total": "serving: /act requests accepted into the batcher",
    "sheeprl_serve_dispatches_total": "serving: batched device dispatches (requests amortize into these)",
    "sheeprl_serve_request_errors_total": "serving: requests failed (queue full, timeout, dispatch error)",
    "sheeprl_serve_ckpt_promotions_total": "serving: checkpoints hot-promoted by the watcher",
    "sheeprl_serve_ckpt_rejections_total": "serving: checkpoints refused (health gate / shape mismatch)",
    "sheeprl_serve_batch_width_total": "serving: dispatches per padded bucket width (label: width)",
    "sheeprl_serve_latency_p50_ms": "serving: median request latency (enqueue to response)",
    "sheeprl_serve_latency_p99_ms": "serving: p99 request latency",
    "sheeprl_serve_requests_per_sec": "serving: request throughput over the recent completion window",
    "sheeprl_serve_queue_depth": "serving: requests waiting for a dispatch slot",
    "sheeprl_serve_batch_width_mean": "serving: mean valid rows per dispatch (amortization factor)",
    "sheeprl_serve_ckpt_step": "serving: policy step of the currently served checkpoint",
    "sheeprl_serve_last_promote_rejected": "serving: 1 while the newest checkpoint candidate was rejected",
    # stateful multi-model serving (session layer + model registry + request
    # log; per-model series carry a {model="..."} label, the unlabeled sample
    # is the cross-model aggregate)
    "sheeprl_serve_shed_total": "serving: requests refused 503 at the door because the queue was full (load shedding; responses carry Retry-After)",
    "sheeprl_serve_models": "serving: resident models on this server (the registry size)",
    "sheeprl_serve_request_log_rows_total": "serving: /act rows appended to the offline request-log dataset",
    "sheeprl_serve_request_log_shards_total": "serving: request-log shards rotated to disk (journaled request_log_rotate)",
    "sheeprl_sessions_active": "serving sessions: client sessions currently resident in the state slab",
    "sheeprl_sessions_capacity": "serving sessions: state-slab capacity (serving.sessions.capacity)",
    "sheeprl_sessions_created_total": "serving sessions: sessions allocated a slab slot (first sight or post-eviction re-entry)",
    "sheeprl_sessions_evictions_total": "serving sessions: LRU evictions journaled as session_evict",
    "sheeprl_sessions_overflow_total": "serving sessions: new sessions that rode the scratch slot because every slot was pinned by their own batch",
    # request-level tracing, latency breakdown + SLOs (ISSUE 19): per-phase
    # histograms with fixed serving.slo.buckets_ms boundaries, burn-rate
    # gauge, shed-wait accounting and slow-request forensics counters
    "sheeprl_serve_latency_ms_bucket": "serving: per-phase request-latency histogram buckets (labels: phase, le, optional model; boundaries from serving.slo.buckets_ms)",
    "sheeprl_serve_latency_ms_sum": "serving: cumulative milliseconds observed per phase (histogram _sum)",
    "sheeprl_serve_latency_ms_count": "serving: observations per phase (histogram _count)",
    "sheeprl_serve_queue_ms_p50": "serving: median queue-wait (enqueue to batch-formation start) over the rolling window",
    "sheeprl_serve_queue_ms_p99": "serving: p99 queue-wait",
    "sheeprl_serve_batch_form_ms_p50": "serving: median batch-formation wait (co-rider window) over the rolling window",
    "sheeprl_serve_batch_form_ms_p99": "serving: p99 batch-formation wait",
    "sheeprl_serve_dispatch_ms_p50": "serving: median AOT dispatch time (slab assembly + session checkout + device step)",
    "sheeprl_serve_dispatch_ms_p99": "serving: p99 AOT dispatch time",
    "sheeprl_serve_scatter_ms_p50": "serving: median result fan-out time (dispatch return to every waiter woken)",
    "sheeprl_serve_scatter_ms_p99": "serving: p99 result fan-out time",
    "sheeprl_serve_slo_burn": "serving: rolling SLO burn rate — bad_fraction / (1 - objective); > 1.0 spends error budget faster than the objective allows",
    "sheeprl_serve_shed_wait_ms": "serving: mean milliseconds a shed request spent queued/contended before its 503 (overload analysis without survivorship bias)",
    "sheeprl_serve_slow_requests_total": "serving: requests that exceeded slo.slow_trace_ms and journaled slow_request forensics",
    "sheeprl_serve_slo_breaches_total": "serving: confirmed SLO breaches journaled as slo_breach",
}
