"""Run lifecycle & goodput observability: run-state machine + stall watchdog.

PRs 1/3/4 instrumented *what the run computes* (NaN sentinel, MFU/phase
telemetry, HBM); this module observes *whether the run is alive and making
progress* — the measurement half of ROADMAP item 4.  On preemptible TPU pools
wall-clock is the resource you pay for, and **goodput** (productive train
time / wall time) is the number that says whether the pipeline work lands in
production.  Three mechanisms, all riding hooks the loops already call:

* **Run-state machine** — ``starting → compiling → training / env_wait /
  checkpointing / stalled → ended``, driven by telemetry's compile/dispatch
  notifications, the facade's ``diag.span`` enters, and the per-interval
  metric flushes.  Transitions are journaled as ``state_change`` events with
  flood control (each steady state at *first entry* only; stall transitions
  always), and the live state rides every metric interval as the numeric
  ``Telemetry/run_state`` gauge (index into :data:`STATES`) next to
  ``Telemetry/goodput`` (cumulative-since-open; numerator is telemetry's
  exact train-span seconds — omitted, never a false 0.0, when telemetry is
  off) and ``Telemetry/time_to_first_step``.

* **Heartbeat stall watchdog** — a daemon thread wakes every ``heartbeat_s``;
  no progress signal (span enter, dispatch, or interval flush) for
  ``stall_threshold_s`` journals exactly ONE fsync'd ``stall`` event carrying
  forensics (all-thread stacks via ``faulthandler``, the last known state,
  idle seconds), optionally auto-captures a short ``jax.profiler`` trace
  (``profile_capture`` event; failure is never fatal), and journals
  ``stall_end`` on the next progress signal.  The
  ``diagnostics.goodput.watchdog.inject_stall_iter`` fault knob sleeps inside
  the Nth train dispatch to drill the whole chain end-to-end.

* **Segment accounting** — ``tools/goodput_report.py`` groups a resumed run's
  ``version_N`` checkpoint-dir segments into one logical run (killed-segment
  detection, time-to-recover, productive time recovered from the last
  journaled goodput gauge); the journal-side helpers it shares with the live
  status lines (:func:`stalled_seconds`, :func:`journal_run_state`,
  :func:`segment_stats`) live here.

Locking contract: journal writes happen OUTSIDE the monitor's own lock,
except the stall path — ``stall``/``stall_end`` are written while holding it
so ``stall`` always precedes ``stall_end`` on disk (safe: the journal's own
write lock is a leaf lock).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

#: The run-state vocabulary, in gauge order: ``Telemetry/run_state`` exports
#: the index into this tuple (5 = stalled), so dashboards can alert on it.
STATES: Tuple[str, ...] = (
    "starting",
    "compiling",
    "training",
    "env_wait",
    "checkpointing",
    "stalled",
    "ended",
)
STATE_INDEX: Dict[str, int] = {name: i for i, name in enumerate(STATES)}

#: Facade span names that map onto a run state; unmapped spans (rollout,
#: buffer-sample, custom) count as progress without changing the state.
_SPAN_STATES: Dict[str, str] = {
    "train": "training",
    "env_wait": "env_wait",
    "checkpoint": "checkpointing",
}


def _positive_or_none(value: Any, knob: str) -> Optional[float]:
    """Validate a ``>0-or-null`` watchdog knob (``Event.wait(<=0)`` degenerates
    into a busy-spin, so zero/negative must fail loudly — mirrored in
    ``cli.check_configs`` so the CLI fails before the run dir exists)."""
    if value is None:
        return None
    number = float(value)
    if number <= 0:
        raise ValueError(
            f"diagnostics.goodput.watchdog.{knob} must be > 0 or null "
            f"(null disables the watchdog), got {value!r}"
        )
    return number


class GoodputMonitor:
    """Rank-0 run-state machine + stall watchdog behind the facade.

    Opened by ``Diagnostics.open`` on rank 0 only; every hook is a cheap
    no-op until then, so telemetry and the facade call them unconditionally.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, cfg: Optional[Mapping[str, Any]], clock: Callable[[], float] = time.monotonic):
        cfg = cfg or {}
        diag_cfg = cfg.get("diagnostics") or {}
        goodput_cfg = diag_cfg.get("goodput") or {}
        self.enabled = bool(goodput_cfg.get("enabled", True))
        wd_cfg = goodput_cfg.get("watchdog") or {}
        self.watchdog_enabled = bool(wd_cfg.get("enabled", True))
        self.heartbeat_s = _positive_or_none(wd_cfg.get("heartbeat_s", 5.0), "heartbeat_s")
        self.stall_threshold_s = _positive_or_none(
            wd_cfg.get("stall_threshold_s", 120.0), "stall_threshold_s"
        )
        inject = wd_cfg.get("inject_stall_iter")
        self.inject_stall_iter = None if inject is None else int(inject)
        # while the state machine says `compiling` the threshold is scaled by
        # this factor (clamped >= 1): a first XLA compile legitimately runs
        # minutes with zero progress signals, and a spurious stall there would
        # dump forensics (and, with the profile pillar on, start a capture)
        # into every cold start — a truly hung compile still trips at
        # threshold x grace
        self.compile_grace = max(1.0, float(wd_cfg.get("compile_grace", 5.0) or 1.0))
        profile_cfg = goodput_cfg.get("profile") or {}
        # matches the YAML default: the profile pillar is OPT-IN (a capture
        # window overlapping the recovering dispatch can wedge the backend
        # profiler), including for direct-entrypoint callers with partial cfgs
        self.profile_enabled = bool(profile_cfg.get("enabled", False))
        # null = the default, NOT zero — check_configs explicitly allows None
        # and the ctor must validate identically
        max_ms = profile_cfg.get("max_ms")
        self.profile_max_ms = 2000.0 if max_ms is None else float(max_ms)
        if self.enabled and self.profile_enabled and self.profile_max_ms < 10:
            # validated only while both are enabled: the remedy the error
            # suggests (disabling the profile pillar) must itself compose
            raise ValueError(
                f"diagnostics.goodput.profile.max_ms must be >= 10 (the capture floor), "
                f"got {profile_cfg.get('max_ms')!r}; set diagnostics.goodput.profile.enabled=False "
                "to disable stall profiling instead"
            )
        self._auto_profiles = int(profile_cfg.get("auto_captures", 1) or 0)

        self._clock = clock
        self._lock = threading.Lock()
        self._profile_lock = threading.Lock()
        self._journal_fn: Optional[Callable[..., None]] = None
        self._sync_fn: Optional[Callable[[], None]] = None
        self._telemetry = None
        self._log_dir: Optional[str] = None
        self._opened = False
        self._closed = False

        self._state: str = "starting"
        self._state_entered_t: Optional[float] = None
        self._state_seconds: Dict[str, float] = {}
        # flood control: steady states journal a state_change at FIRST entry
        # only ("starting" is implicit in run_start, "ended" in run_end)
        self._journaled_states = {"starting", "ended"}
        self._last_progress: Optional[float] = None
        self._open_clock: Optional[float] = None
        self._train_dispatches = 0
        self._time_to_first_step: Optional[float] = None

        self._stalled = False
        self._prestall_state: Optional[str] = None
        self._stall_started_t: Optional[float] = None
        self._stalls_total = 0
        self._stalled_s_total = 0.0
        self._profile_captures = 0

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def open(
        self,
        journal_fn: Optional[Callable[..., None]] = None,
        sync_fn: Optional[Callable[[], None]] = None,
        telemetry: Any = None,
        log_dir: Optional[str] = None,
    ) -> None:
        if self._opened:
            return
        # publish under the monitor lock: the watchdog starts below and reads
        # all of these; the lock (not thread-start ordering) is what makes
        # open() safe to race with an early first heartbeat
        with self._lock:
            self._journal_fn = journal_fn
            self._sync_fn = sync_fn
            self._telemetry = telemetry
            self._log_dir = str(log_dir) if log_dir else None
            now = self._clock()
            self._open_clock = now
            self._state_entered_t = now
            self._last_progress = now
            self._opened = True
        if self.watchdog_enabled and self.heartbeat_s is not None and self.stall_threshold_s is not None:
            self._thread = threading.Thread(
                target=self._watchdog_loop, name="sheeprl-stall-watchdog", daemon=True
            )
            self._thread.start()

    def close(self) -> None:
        """Stop the watchdog and fold the live state tail into the totals.

        Writes NOTHING to the journal (``run_end`` covers the ended
        transition — the facade's close event sequence is pinned by tests);
        an open stall is folded into the stalled-seconds total under the
        lock so ``summary()`` stays honest.
        """
        if not self._opened or self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._lock:
            now = self._clock()
            if self._stalled:
                self._stalled = False
                if self._stall_started_t is not None:
                    self._stalled_s_total += max(0.0, now - self._stall_started_t)
            self._set_state_locked("ended", now)

    # -- hooks (telemetry + facade; all no-ops until opened) ----------------
    def note_compile_start(self, name: str) -> None:
        """A never-seen dispatch signature is about to compile."""
        if not self._opened:
            return
        self._emit(self._note_progress("compiling"))

    def note_dispatch(self, name: str, kind: str) -> None:
        """An instrumented dispatch completed (called by telemetry after its
        own accounting, outside any lock — the stall injection sleeps here)."""
        if not self._opened:
            return
        if kind != "train":
            self._emit(self._note_progress(None))
            return
        with self._lock:
            self._train_dispatches += 1
            n = self._train_dispatches
            if self._time_to_first_step is None and self._open_clock is not None:
                self._time_to_first_step = max(0.0, self._clock() - self._open_clock)
        self._emit(self._note_progress("training"))
        if (
            self.inject_stall_iter is not None
            and n == self.inject_stall_iter
            and self.stall_threshold_s is not None
            and self.heartbeat_s is not None
        ):
            # fault drill: hold the loop thread idle long enough for the
            # watchdog to fire, then recover deterministically — exactly one
            # stall + stall_end per run
            sleep_s = self.stall_threshold_s + 4.0 * self.heartbeat_s
            self._journal("fault_injection", iter_num=n, kind="stall", sleep_s=round(sleep_s, 3))
            time.sleep(sleep_s)
            self._emit(self._note_progress("training"))

    def note_span(self, name: str) -> None:
        """Facade span enter: progress, plus a state change for mapped names
        (train / env_wait / checkpoint)."""
        if not self._opened:
            return
        self._emit(self._note_progress(_SPAN_STATES.get(name)))

    # -- state machine core -------------------------------------------------
    def _note_progress(self, new_state: Optional[str]) -> Optional[Dict[str, Any]]:
        """Record a progress signal; returns a ``state_change`` payload to be
        journaled OUTSIDE the lock (or None).  Stall recovery journals
        ``stall_end`` (and its state_change, when due) while HOLDING the lock
        so it can never land on disk before the watchdog's ``stall``."""
        with self._lock:
            now = self._clock()
            self._last_progress = now
            if self._stalled:
                self._stalled = False
                stalled_for = 0.0
                if self._stall_started_t is not None:
                    stalled_for = max(0.0, now - self._stall_started_t)
                self._stalled_s_total += stalled_for
                self._stall_started_t = None
                # a site that does not set its own state restores the one the
                # stall interrupted — every recovery path leaves `stalled`
                target = new_state or self._prestall_state or "training"
                self._prestall_state = None
                payload = self._set_state_locked(target, now)
                if payload is not None:
                    self._journal("state_change", **payload)
                self._journal("stall_end", state=target, stalled_s=round(stalled_for, 3))
                return None
            if new_state is not None:
                return self._set_state_locked(new_state, now)
        return None

    def _set_state_locked(self, state: str, now: float) -> Optional[Dict[str, Any]]:
        """Transition (caller holds the lock); returns the journal payload
        when flood control says this transition is journal-worthy."""
        prev = self._state
        if prev == state:
            return None
        if self._state_entered_t is not None:
            self._state_seconds[prev] = self._state_seconds.get(prev, 0.0) + max(
                0.0, now - self._state_entered_t
            )
        self._state = state
        self._state_entered_t = now
        if state == "stalled":
            return {"state": state, "prev": prev}
        first_entry = state not in self._journaled_states
        self._journaled_states.add(state)
        return {"state": state, "prev": prev} if first_entry else None

    def _emit(self, payload: Optional[Dict[str, Any]]) -> None:
        if payload is not None:
            self._journal("state_change", **payload)

    def _journal(self, event: str, **fields: Any) -> None:
        if self._journal_fn is not None:
            self._journal_fn(event, **fields)

    # -- watchdog ------------------------------------------------------------
    def _watchdog_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            with self._lock:
                if self._stalled or self._last_progress is None:
                    continue
                baseline = self._last_progress
                idle = self._clock() - baseline
                threshold = self._stall_threshold_locked()
            if idle >= threshold:
                # the abort baseline is the progress reading the idle math
                # used: progress landing between this check and the stall
                # lock must still abort the stall
                self._mark_stalled(idle, threshold_s=threshold, progress_seen=baseline)

    def _stall_threshold_locked(self) -> float:
        """Effective stall threshold for the CURRENT position (caller holds
        the lock): scaled by ``compile_grace`` while compiling — and until
        the first train dispatch completes, which covers the agent-build/env
        setup window AND the telemetry-off configuration (no dispatch
        notifications there means `compiling` is unreachable and
        ``_train_dispatches`` stays 0, so the watchdog permanently runs at
        the conservative threshold x grace instead of false-flagging every
        long first compile)."""
        grace = (
            self.compile_grace
            if (self._state == "compiling" or self._train_dispatches == 0)
            else 1.0
        )
        return self.stall_threshold_s * grace

    def _mark_stalled(
        self,
        idle_s: float,
        threshold_s: Optional[float] = None,
        progress_seen: Optional[float] = None,
    ) -> None:
        """Journal exactly one fsync'd ``stall`` with forensics.

        ``threshold_s`` is the EFFECTIVE threshold that tripped (the watchdog
        passes the compile-grace-scaled value so the forensics never look
        like a late firing); defaults to the base threshold for direct calls.
        ``progress_seen`` is the ``_last_progress`` reading the caller's idle
        computation used — any progress after THAT aborts the stall.

        Stack gathering happens UNFLAGGED and lock-free (it takes tens of
        ms); the lock is then re-taken and the stall aborted if progress
        landed meanwhile.  ``state_change``+``stall`` are written while
        HOLDING the lock — the one exception to the journal-outside-the-lock
        rule — so ``stall`` always precedes ``stall_end`` on disk.
        """
        if progress_seen is None:
            with self._lock:
                progress_seen = self._last_progress
        stacks = self._thread_stacks()
        with self._lock:
            if self._stalled or self._last_progress != progress_seen:
                return  # progress (or another stall) won the race
            now = self._clock()
            self._stalled = True
            self._stalls_total += 1
            self._prestall_state = self._state
            # stalled time is DETECTION -> recovery on every surface (live
            # counter, state_seconds, journal stall->stall_end bounds); the
            # idle lead-in before detection is the stall event's idle_s
            self._stall_started_t = now
            payload = self._set_state_locked("stalled", now)
            if payload is not None:
                self._journal("state_change", **payload)
            self._journal(
                "stall",
                idle_s=round(float(idle_s), 3),
                threshold_s=threshold_s if threshold_s is not None else self.stall_threshold_s,
                last_state=self._prestall_state,
                stacks=stacks,
            )
            if self._sync_fn is not None:
                self._sync_fn()  # the record must survive a SIGKILL right now
        if not self.profile_enabled:
            return
        with self._lock:
            if self._auto_profiles <= 0:
                return
            self._auto_profiles -= 1

        def _auto_capture() -> None:
            result = self.capture_profile()
            if (result or {}).get("status") == "busy":
                with self._lock:
                    self._auto_profiles += 1  # refund: nothing was captured

        # its own daemon thread: a capture that wedges in the backend
        # profiler (seen when the recovering dispatch overlaps the
        # capture window) must cost the run one thread, not the watchdog
        # or a hang in close()
        threading.Thread(
            target=_auto_capture, name="sheeprl-stall-profile", daemon=True
        ).start()

    def _thread_stacks(self, limit: int = 12000) -> str:
        """All-thread stacks via ``faulthandler`` (needs a real fd).  Tail
        truncation is correct: faulthandler prints the current (watchdog)
        thread FIRST and the main thread LAST — verified empirically, so the
        stuck loop thread survives the cut."""
        import faulthandler
        import tempfile

        try:
            with tempfile.TemporaryFile(mode="w+") as fp:
                faulthandler.dump_traceback(file=fp, all_threads=True)
                fp.seek(0)
                text = fp.read()
        except Exception as err:  # pragma: no cover - exotic platforms
            return f"<stack capture failed: {err!r}>"
        return text[-limit:]

    # -- profiler capture (auto on stall + the /profile endpoint) ------------
    def capture_profile(self, ms: Optional[float] = None) -> Dict[str, Any]:
        """Capture a short ``jax.profiler`` trace under the run dir.

        Returns (and journals as ``profile_capture``) a status dict — always
        a dict, never raises: ``ok`` with the output dir, ``busy`` when a
        capture is already running (including ``metric.profiler``'s whole-run
        trace holding the profiler), or ``failed`` with the error.  ``ms``
        defaults to ``profile.max_ms`` and is clamped into [10, max_ms]
        (``ms=0`` clamps to the 10 ms floor, not the default).
        """
        if not self._opened or not self.profile_enabled:
            return {"status": "disabled"}
        duration_ms = float(ms) if ms is not None else self.profile_max_ms
        duration_ms = min(max(10.0, duration_ms), self.profile_max_ms)
        if not self._profile_lock.acquire(blocking=False):
            result: Dict[str, Any] = {"status": "busy"}
            self._journal("profile_capture", **result)
            return result
        try:
            import jax

            out_dir = os.path.join(self._log_dir or ".", "goodput_profile")
            os.makedirs(out_dir, exist_ok=True)
            try:
                # e.g. metric.profiler's whole-run capture already owns the
                # profiler (start_trace raises) — never fatal, and the
                # cleanup below must NOT run: a stop_trace here would
                # finalize the FOREIGN session and truncate the user's
                # whole-run profile
                jax.profiler.start_trace(out_dir)
            except Exception as err:
                result = {"status": "failed", "error": repr(err)[:200]}
            else:
                try:
                    time.sleep(duration_ms / 1000.0)
                    jax.profiler.stop_trace()
                    with self._lock:
                        self._profile_captures += 1
                    result = {"status": "ok", "dir": out_dir, "ms": round(duration_ms, 1)}
                except Exception as err:
                    try:
                        jax.profiler.stop_trace()  # OUR session is active here
                    except Exception:
                        pass
                    result = {"status": "failed", "error": repr(err)[:200]}
        finally:
            self._profile_lock.release()
        self._journal("profile_capture", **result)
        return result

    # -- gauges / snapshots --------------------------------------------------
    def _train_seconds(self) -> Optional[float]:
        """Goodput's numerator: telemetry's exact train-span seconds, or None
        when no telemetry is attached (the gauge is then OMITTED — a false
        0.0 would read as 'zero productive time')."""
        telemetry = self._telemetry
        if telemetry is None:
            return None
        try:
            return float(telemetry.train_seconds())
        except Exception:  # pragma: no cover - foreign telemetry stand-ins
            return None

    def _lifecycle_gauges(self) -> Dict[str, float]:
        """The gauge triple shared by :meth:`interval_metrics` (journal/TB)
        and :meth:`snapshot` (/metrics) — ONE site owns the omission rules
        (goodput/ttfs only with telemetry attached, never a false 0.0)."""
        with self._lock:
            out: Dict[str, float] = {"Telemetry/run_state": float(STATE_INDEX[self._state])}
            ttfs = self._time_to_first_step
            open_clock = self._open_clock
        train_s = self._train_seconds()
        if train_s is not None:
            if open_clock is not None:
                elapsed = self._clock() - open_clock
                if elapsed > 0:
                    out["Telemetry/goodput"] = train_s / elapsed
            if ttfs is not None:
                out["Telemetry/time_to_first_step"] = round(ttfs, 3)
        return out

    def interval_metrics(self) -> Dict[str, float]:
        """Per-interval gauges merged into the metric stream by the facade;
        the flush itself is a progress signal (prevents spurious stalls while
        a run tears down between the last dispatch and close)."""
        if not self._opened:
            return {}
        self._emit(self._note_progress(None))
        return self._lifecycle_gauges()

    def snapshot(self) -> Dict[str, Any]:
        gauges = self._lifecycle_gauges()
        with self._lock:
            counters = {
                "stalls_total": self._stalls_total,
                "stalled_seconds_total": round(self._stalled_s_total, 3),
                "profile_captures_total": self._profile_captures,
            }
            info = {"run_state": self._state}
        return {"gauges": gauges, "counters": counters, "info": info}

    def summary(self) -> Dict[str, Any]:
        """Run totals merged into the closing ``telemetry_summary`` event
        (call after :meth:`close` so the live state tail is folded in)."""
        with self._lock:
            out: Dict[str, Any] = {
                "state_seconds": {k: round(v, 3) for k, v in sorted(self._state_seconds.items())},
                "stalls": self._stalls_total,
                "stalled_seconds": round(self._stalled_s_total, 3),
                "profile_captures": self._profile_captures,
            }
            if self._time_to_first_step is not None:
                out["time_to_first_step_s"] = round(self._time_to_first_step, 3)
            open_clock = self._open_clock
            end_clock = self._state_entered_t if self._state == "ended" else self._clock()
        train_s = self._train_seconds()
        if train_s is not None and open_clock is not None and end_clock is not None:
            elapsed = max(0.0, end_clock - open_clock)
            if elapsed > 0:
                out["goodput"] = round(train_s / elapsed, 4)
        return out


# ---------------------------------------------------------------------------
# journal-side accounting (shared by report.py status lines, goodput_report
# and the trace_report run-state overlay — do NOT re-inline this math)


def stalled_seconds(events: List[Dict[str, Any]]) -> float:
    """Seconds stalled according to a journal event list: closed stalls sum
    their ``stall → stall_end`` bounds; an unclosed stall (killed while
    stalled) contributes ``stall →`` *last journal event* seconds — the best
    journal-only estimate, since the actual death time is unknowable
    post-hoc."""
    total = 0.0
    open_t: Optional[float] = None
    last_t: Optional[float] = None
    for event in events:
        t = event.get("t")
        if not isinstance(t, (int, float)):
            continue
        last_t = t if last_t is None else max(last_t, t)
        kind = event.get("event")
        if kind == "stall":
            open_t = t
        elif kind == "stall_end" and open_t is not None:
            total += max(0.0, t - open_t)
            open_t = None
    if open_t is not None and last_t is not None:
        total += max(0.0, last_t - open_t)
    return total


def journal_run_state(events: List[Dict[str, Any]]) -> Optional[Tuple[float, str]]:
    """Freshest known run state ``(t, state)`` from a journal.

    Flood control journals steady ``state_change`` events only at FIRST
    entry, so the per-interval ``Telemetry/run_state`` gauge must be read
    too — the newest of gauge / state_change / stall / stall_end / run_end
    wins."""
    best: Optional[Tuple[float, str]] = None
    for event in events:
        t = event.get("t")
        if not isinstance(t, (int, float)):
            continue
        kind = event.get("event")
        state: Optional[str] = None
        if kind == "state_change":
            state = event.get("state")
        elif kind == "stall":
            state = "stalled"
        elif kind == "stall_end":
            state = event.get("state") or "training"
        elif kind == "run_end":
            state = "ended"
        elif kind == "metrics":
            gauge = (event.get("metrics") or {}).get("Telemetry/run_state")
            if isinstance(gauge, (int, float)) and 0 <= int(gauge) < len(STATES):
                state = STATES[int(gauge)]
        if state is not None and (best is None or t >= best[0]):
            best = (t, str(state))
    return best


def segment_stats(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-segment accounting over one journal's event list.

    Productive (train) seconds come from the closing ``telemetry_summary``
    when the segment shut down cleanly; a killed segment recovers them from
    its last journaled cumulative ``Telemetry/goodput`` gauge
    (``gauge * seconds-since-run_start`` — the gauge is cumulative-since-open
    by contract)."""
    ts = [e.get("t") for e in events if isinstance(e.get("t"), (int, float))]
    start_t = min(ts) if ts else None
    end_t = max(ts) if ts else None
    run_end = next((e for e in reversed(events) if e.get("event") == "run_end"), None)
    summary = next((e for e in reversed(events) if e.get("event") == "telemetry_summary"), None)
    metrics_events = [e for e in events if e.get("event") == "metrics"]

    train_s: Optional[float] = None
    source: Optional[str] = None
    ttfs: Optional[float] = None
    if summary is not None:
        phase = summary.get("phase_seconds") or {}
        if isinstance(phase.get("train"), (int, float)):
            train_s = float(phase["train"])
            source = "summary"
        if isinstance(summary.get("time_to_first_step_s"), (int, float)):
            ttfs = float(summary["time_to_first_step_s"])
    if train_s is None and start_t is not None:
        for event in reversed(metrics_events):
            gauge = (event.get("metrics") or {}).get("Telemetry/goodput")
            if isinstance(gauge, (int, float)) and isinstance(event.get("t"), (int, float)):
                train_s = float(gauge) * max(0.0, event["t"] - start_t)
                source = "gauge"
                break
    if ttfs is None:
        for event in reversed(metrics_events):
            value = (event.get("metrics") or {}).get("Telemetry/time_to_first_step")
            if isinstance(value, (int, float)):
                ttfs = float(value)
                break

    last_step = None
    for event in reversed(metrics_events):
        if event.get("step") is not None:
            last_step = event["step"]
            break

    wall_s = max(0.0, (end_t or 0.0) - (start_t or 0.0)) if ts else 0.0
    return {
        "start_t": start_t,
        "end_t": end_t,
        "wall_s": round(wall_s, 3),
        "status": run_end.get("status") if run_end is not None else None,
        "train_s": round(train_s, 3) if train_s is not None else None,
        "train_source": source,
        "goodput": round(train_s / wall_s, 4) if train_s is not None and wall_s > 0 else None,
        "stalls": sum(1 for e in events if e.get("event") == "stall"),
        "stalled_s": round(stalled_seconds(events), 3),
        # only successful captures count (matches the live counter)
        "profile_captures": sum(
            1 for e in events if e.get("event") == "profile_capture" and e.get("status") == "ok"
        ),
        "time_to_first_step_s": ttfs,
        "last_step": last_step,
        "state_seconds": (summary or {}).get("state_seconds"),
    }
