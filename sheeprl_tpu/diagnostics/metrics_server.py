"""Rank-0 live metrics endpoint: ``/metrics`` (Prometheus text) + ``/healthz``
(+ on-demand ``/profile`` jax.profiler captures when the goodput layer is on).

A stdlib ``ThreadingHTTPServer`` on a daemon thread — no new dependencies —
serving the telemetry snapshot so external scrapers (Prometheus, or the
``tools/run_monitor.py`` terminal dashboard in ``--url`` mode) can watch a
live training run without touching its files.  Opt-in
(``diagnostics.telemetry.http.enabled=True``); ``port: 0`` binds an ephemeral
port, which the facade journals (``metrics_server`` event) and prints.

The server never blocks training: handlers only read a lock-protected
snapshot dict produced by :meth:`Telemetry.snapshot`, and shutdown is a
bounded ``server.shutdown()`` + thread join inside ``Diagnostics.close``.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

from sheeprl_tpu.diagnostics.schema import METRIC_PREFIX

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(value: Any) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _metric_name(key: str) -> str:
    """``Telemetry/phase_pct/train`` -> ``phase_pct_train`` etc."""
    name = key.split("/", 1)[1] if key.startswith("Telemetry/") else key
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    name = "".join(out)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _format_le(le: Any) -> str:
    if isinstance(le, str):
        return le
    return f"{float(le):g}"


def latency_histogram_lines(hist: Mapping[str, Any], model: Optional[str] = None) -> list:
    """Series lines (no ``# TYPE`` header — the caller owns the one-per-family
    rule) for a per-phase latency histogram snapshot shaped like
    ``PolicyService.snapshot()["latency_hist"]``:
    ``{phase: {"buckets": [(le, cum_count), ...], "sum": ms, "count": n}}``.

    Renders the standard Prometheus histogram triplet
    ``sheeprl_serve_latency_ms_bucket{phase,le}`` / ``_sum`` / ``_count``,
    with a ``model`` label prepended when serving multiple residents."""
    lines = []
    model_label = f'model="{_escape_label(model)}",' if model else ""
    for phase in sorted(hist):
        entry = hist[phase] or {}
        phase_label = f'phase="{_escape_label(phase)}"'
        for le, count in entry.get("buckets") or []:
            lines.append(
                f"sheeprl_serve_latency_ms_bucket"
                f'{{{model_label}le="{_format_le(le)}",{phase_label}}} {float(count):g}'
            )
        lines.append(
            f"sheeprl_serve_latency_ms_sum{{{model_label}{phase_label}}} "
            f"{float(entry.get('sum') or 0.0):g}"
        )
        lines.append(
            f"sheeprl_serve_latency_ms_count{{{model_label}{phase_label}}} "
            f"{float(entry.get('count') or 0):g}"
        )
    return lines


def render_prometheus(snapshot: Mapping[str, Any]) -> str:
    """Prometheus text exposition (0.0.4) of a telemetry snapshot.

    Gauges come from the latest closed accounting interval; ``*_total``
    counters are cumulative over the run.  ``sheeprl_run_info`` carries the
    run identity as labels (value is always 1), the standard info-metric
    idiom.
    """
    lines = []

    def emit(name: str, mtype: str, value: Any, help_text: str = "", labels: Optional[Dict] = None):
        full = METRIC_PREFIX + name
        if help_text:
            lines.append(f"# HELP {full} {help_text}")
        lines.append(f"# TYPE {full} {mtype}")
        label_s = ""
        if labels:
            inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items()))
            label_s = "{" + inner + "}"
        try:
            num = float(value)
        except (TypeError, ValueError):
            num = 0.0
        lines.append(f"{full}{label_s} {num:g}")

    info = snapshot.get("info") or {}
    if info:
        full = "sheeprl_run_info"
        lines.append(f"# HELP {full} Run identity (labels carry the data; value is 1).")
        lines.append(f"# TYPE {full} gauge")
        inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in sorted(info.items()) if v is not None)
        lines.append(f"{full}{{{inner}}} 1")

    emit("up", "gauge", 1, "1 while the training process serves this endpoint.")
    steps = snapshot.get("policy_steps")
    if steps is not None:
        emit("policy_steps_total", "counter", steps, "Policy steps taken (env frames / action_repeat).")

    for key, value in sorted((snapshot.get("gauges") or {}).items()):
        if value is None:
            continue
        emit(_metric_name(key), "gauge", value)

    for key, value in sorted((snapshot.get("counters") or {}).items()):
        emit(key, "counter", value)

    phase_seconds = snapshot.get("phase_seconds_total") or {}
    if phase_seconds:
        # one TYPE line for the whole label family — a second TYPE line for
        # the same metric name is a Prometheus parse error
        lines.append("# TYPE sheeprl_phase_seconds_total counter")
        for phase, secs in sorted(phase_seconds.items()):
            try:
                num = float(secs)
            except (TypeError, ValueError):
                num = 0.0
            lines.append(f'sheeprl_phase_seconds_total{{phase="{_escape_label(phase)}"}} {num:g}')

    lag = snapshot.get("journal_lag_seconds")
    if lag is not None:
        emit(
            "journal_lag_seconds",
            "gauge",
            lag,
            "Seconds since the last journal write (high = run stalled or not logging).",
        )
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Background HTTP server bound to ``host:port`` (0 = ephemeral).

    ``profile_fn`` (optional, from the goodput layer) serves on-demand
    ``jax.profiler`` captures at ``GET /profile[?ms=N]`` — the handler thread
    blocks for the capture window, never the training loop; the journal
    records every capture as a ``profile_capture`` event.
    """

    def __init__(
        self,
        snapshot_fn: Callable[[], Dict[str, Any]],
        host: str = "127.0.0.1",
        port: int = 0,
        profile_fn: Optional[Callable[[Optional[float]], Dict[str, Any]]] = None,
    ):
        self._snapshot_fn = snapshot_fn
        self._profile_fn = profile_fn
        self._host = host
        self._port = int(port)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> Tuple[str, int]:
        snapshot_fn = self._snapshot_fn
        profile_fn = self._profile_fn

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr spam
                pass

            def do_GET(self) -> None:  # noqa: N802 - stdlib API
                path, _, query = self.path.partition("?")
                try:
                    if path == "/metrics":
                        body = render_prometheus(snapshot_fn()).encode()
                        self.send_response(200)
                        self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                    elif path == "/profile" and profile_fn is not None:
                        from urllib.parse import parse_qs

                        ms: Optional[float] = None
                        for value in parse_qs(query).get("ms", []):
                            try:
                                ms = float(value)
                            except ValueError:
                                pass
                        result = profile_fn(ms)
                        body = json.dumps(result).encode()
                        # busy = retryable contention, not a client error
                        self.send_response(200 if result.get("status") != "failed" else 500)
                        self.send_header("Content-Type", "application/json")
                    elif path == "/healthz":
                        snap = snapshot_fn()
                        body = json.dumps(
                            {
                                "status": "ok",
                                "t": round(time.time(), 3),
                                "policy_steps": snap.get("policy_steps"),
                                "journal_lag_seconds": snap.get("journal_lag_seconds"),
                            }
                        ).encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                    else:
                        body = b"not found\n"
                        self.send_response(404)
                        self.send_header("Content-Type", "text/plain")
                except Exception as err:  # pragma: no cover - snapshot races
                    body = f"snapshot error: {err!r}\n".encode()
                    self.send_response(500)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sheeprl-metrics-server", daemon=True
        )
        self._thread.start()
        return self.address

    @property
    def address(self) -> Tuple[str, int]:
        assert self._server is not None, "MetricsServer not started"
        host, port = self._server.server_address[:2]
        return str(host), int(port)

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
