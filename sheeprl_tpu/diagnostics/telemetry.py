"""Performance telemetry: recompilation watchdog + MFU/goodput accounting.

The run-health subsystem (journal/sentinel/tracing, ISSUE 1) answers "is the
run *healthy*?"; this module answers "is the run *fast*?" — continuously, from
inside the run itself, instead of from offline ``bench.py`` snapshots
(PERF.md's numbers).  Three mechanisms, all behind the ``Diagnostics`` facade:

* **Recompilation watchdog** — the training loops wrap their jitted train /
  rollout steps with :meth:`Telemetry.instrument`.  Every dispatch computes
  the argument *signature* (pytree structure + per-leaf shape/dtype/weak-type);
  a signature never seen before is exactly the condition under which
  ``jax.jit`` compiles, so each new one is journaled as a ``recompile`` event
  carrying a leaf-level diff against the previous signature.  A global
  ``jax.monitoring`` listener independently counts every backend compile in
  the process (including un-instrumented helpers), and where monitoring is
  unavailable the wrapper falls back to probing the jitted function's
  ``_cache_size()`` around the dispatch.  Too many recompiles inside a sliding
  window journals a ``recompile_storm`` warning — the silent perf killer this
  watchdog exists for.

* **MFU / goodput accounting** — for ``kind="train"`` instrumented steps the
  first dispatch goes through the AOT path (``fn.lower(*args).compile()``):
  the exact compiled executable's ``cost_analysis()`` FLOPs are captured once
  at first compile *and* the executable is kept for dispatch, so instrumenting
  costs zero extra compiles.  Per log interval the dispatched train FLOPs over
  wall-clock give ``Telemetry/tflops_per_sec`` and — against the device-kind
  peak table (or ``telemetry.mfu.peak_tflops_per_device``) —
  ``Telemetry/mfu``; the policy-step counter gives ``Telemetry/sps``.

* **Persistent AOT executable cache** — with
  ``diagnostics.compilation_cache_dir`` set, every executable the AOT path
  compiles is also serialized to disk
  (``jax.experimental.serialize_executable``) keyed by (fn name, dispatch
  signature, config hash) and stamped with a jax/jaxlib/platform
  fingerprint.  A restarted process loads the executable instead of
  recompiling — production restarts and recompile storms cost seconds, not
  minutes — journaling ``aot_cache_hit`` per loaded signature;
  ``aot_cache_miss`` records why a fresh compile ran (``absent`` /
  ``corrupt`` / ``fingerprint_mismatch`` / ``store_failed``), and a corrupt
  or stale entry always falls back to a fresh compile that overwrites it.
  This complements JAX's own on-disk compilation cache (enabled from the
  same directory at CLI startup): that one caches *compilation*, this one
  caches the loaded executable, skipping even the lowering/cache-probe work
  on the hot restart path and surviving backends where the XLA cache is
  unavailable.

* **Phase attribution** — the facade's existing ``span`` hooks (rollout /
  env_step_async / env_wait / buffer-sample / train / checkpoint) feed a
  nesting-aware self-time accumulator (a child span's time is subtracted from
  its parent), so each interval also reports where the wall-clock went:
  ``Telemetry/phase_pct/{train,env,fetch,other,idle}``.

Emission rides the rank-0 logger proxy: ``JournalingLogger`` asks the facade
to augment each aggregated-metrics interval with the ``Telemetry/*`` gauges
before the TensorBoard/W&B backend and the journal see it, so every algorithm
inherits live perf telemetry without loop changes.
"""

from __future__ import annotations

import os
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

TELEMETRY_PREFIX = "Telemetry/"

# Peak dense-matmul FLOP/s per chip by device kind (same table as bench.py's
# `_chip_peak`, kept self-contained so telemetry never imports the bench).
# Unknown kinds (CPU, forced-host platforms) resolve to None: MFU is then
# only reported when `telemetry.mfu.peak_tflops_per_device` is set — an
# unknown denominator would make the gauge silently wrong, not conservative.
_PEAKS: Dict[str, Dict[str, float]] = {
    "v5e": {"bf16": 197e12, "f32": 98.5e12},
    "v4": {"bf16": 275e12, "f32": 137.5e12},
    "v5p": {"bf16": 459e12, "f32": 229.5e12},
}


def resolve_peak_flops(device_kind: str, precision: str) -> Optional[float]:
    """Per-device peak FLOP/s for a device kind + fabric precision, or None
    when the kind is unrecognized (no guessing: see `_PEAKS` note)."""
    kind = (device_kind or "").lower()
    table = None
    if "v5p" in kind:
        table = _PEAKS["v5p"]
    elif "v4" in kind:
        table = _PEAKS["v4"]
    elif any(t in kind for t in ("v5 lite", "v5e", "v5lite")):
        table = _PEAKS["v5e"]
    if table is None:
        return None
    return table["bf16"] if ("bf16" in precision or "16" in precision) else table["f32"]


# ---------------------------------------------------------------------------
# signatures


def tree_signature(args: Tuple[Any, ...], kwargs: Mapping[str, Any]) -> Tuple[str, Tuple]:
    """Hashable dispatch signature of a call: pytree structure + per-leaf
    (shape, dtype, weak_type).  Non-array leaves (Python scalars that become
    jit constants / static args) contribute their type and repr, so a static
    argument flip also registers as a new signature — which is exactly when
    jit recompiles."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, dict(kwargs)))
    sig: List[Tuple] = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype), bool(getattr(leaf, "weak_type", False))))
        else:
            sig.append(("pyleaf", type(leaf).__name__, repr(leaf)[:48]))
    return (str(treedef), tuple(sig))


def signature_diff(
    old: Optional[Tuple[str, Tuple]], new: Tuple[str, Tuple], paths: List[str]
) -> List[str]:
    """Human-readable leaf-level diff between two signatures (what the
    ``recompile`` journal event carries)."""
    if old is None:
        return ["first compile"]
    changes: List[str] = []
    if old[0] != new[0]:
        changes.append("pytree structure changed")
    old_leaves, new_leaves = old[1], new[1]
    n = max(len(old_leaves), len(new_leaves))
    for i in range(n):
        o = old_leaves[i] if i < len(old_leaves) else None
        nw = new_leaves[i] if i < len(new_leaves) else None
        if o == nw:
            continue
        label = paths[i] if i < len(paths) else f"leaf[{i}]"
        changes.append(f"{label}: {_fmt_leaf(o)} -> {_fmt_leaf(nw)}")
        if len(changes) >= 16:  # a storm of changed leaves needs no full list
            changes.append(f"... ({n - i - 1} more leaves)")
            break
    return changes or ["signature changed"]


def _fmt_leaf(leaf_sig: Optional[Tuple]) -> str:
    if leaf_sig is None:
        return "<absent>"
    if leaf_sig[0] == "pyleaf":
        return f"{leaf_sig[1]}({leaf_sig[2]})"
    shape, dtype, weak = leaf_sig
    return f"{dtype}{list(shape)}" + ("~" if weak else "")


def _leaf_paths(args: Tuple[Any, ...], kwargs: Mapping[str, Any]) -> List[str]:
    import jax

    try:
        flat, _ = jax.tree_util.tree_flatten_with_path((args, dict(kwargs)))
        return [jax.tree_util.keystr(path) for path, _ in flat]
    except Exception:  # pragma: no cover - keystr availability
        return []


# ---------------------------------------------------------------------------
# global compile monitor (jax.monitoring)

_monitor_lock = threading.Lock()
_monitor_state = {"installed": False, "available": None}
_active_collectors: List["Telemetry"] = []


def _on_event_duration(name: str, secs: float, **kw: Any) -> None:
    if "backend_compile" not in name:
        return
    for collector in list(_active_collectors):
        collector._note_backend_compile(float(secs))


def monitoring_available() -> bool:
    """Install the process-wide ``jax.monitoring`` compile listener (once) and
    report whether the events API exists in this jax."""
    with _monitor_lock:
        if _monitor_state["installed"]:
            return bool(_monitor_state["available"])
        _monitor_state["installed"] = True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
            _monitor_state["available"] = True
        except Exception:
            _monitor_state["available"] = False
        return bool(_monitor_state["available"])


def _attach_collector(telemetry: "Telemetry") -> None:
    with _monitor_lock:
        if telemetry not in _active_collectors:
            _active_collectors.append(telemetry)


def _detach_collector(telemetry: "Telemetry") -> None:
    with _monitor_lock:
        if telemetry in _active_collectors:
            _active_collectors.remove(telemetry)


# ---------------------------------------------------------------------------
# instrumented dispatch


class _Instrumented:
    """Wrapper around one jitted callable: signature watch + cost capture.

    ``kind="train"`` goes through the AOT path (lower → compile → keep the
    executable): the FLOPs come from the *exact* executable that runs, and no
    second backend compile ever happens.  Executables are cached per
    signature, mirroring jit's own cache, so bouncing between two shapes
    (e.g. the shape-change fault injection) compiles each once, like jit.
    Any failure in the AOT path — lowering, compiling, or a dispatch
    rejection — permanently falls back to the native jit call and is
    journaled, so telemetry can never take training down.
    """

    def __init__(
        self,
        telemetry: "Telemetry",
        name: str,
        fn: Callable,
        kind: str,
        donate_argnums: Tuple[int, ...] = (),
        cost_note: Optional[str] = None,
    ):
        self._telemetry = telemetry
        self._fn = fn
        self.name = name
        self.kind = kind
        # what the call site DECLARED it donates — the memory monitor verifies
        # the buffers were actually consumed at first dispatch
        self.donate_argnums = tuple(donate_argnums or ())
        # caller-supplied caveat on the cost_analysis FLOPs (e.g. unrolled
        # scans inflate them) — journaled with every telemetry_cost event so
        # MFU is never silently over-reported on such graphs
        self.cost_note = cost_note
        self._use_aot = kind == "train" and telemetry.cost_analysis_enabled
        self._signature: Optional[Tuple[str, Tuple]] = None
        self._seen: set = set()
        self._compiled: Dict[Tuple[str, Tuple], Any] = {}
        # FLOPs are per signature: e.g. SAC's scan-over-gradient-steps train
        # step legitimately runs at several batch-count signatures (pretrain
        # burst vs steady state) with proportionally different FLOPs
        self._flops_by_sig: Dict[Tuple[str, Tuple], float] = {}
        self._cache_size_probe = getattr(fn, "_cache_size", None)
        self._last_cache_size = 0

    def __call__(self, *args: Any, **kwargs: Any):
        tele = self._telemetry
        sig = tree_signature(args, kwargs)
        # mirror jit's cache semantics: only a NEVER-seen signature compiles;
        # bouncing back to a previous signature is a cache hit, not a recompile
        new_sig = sig not in self._seen
        if new_sig:
            if self._seen:
                tele._watchdog_observe(self, sig, args, kwargs)
            self._seen.add(sig)
            if tele._goodput is not None:
                # a never-seen signature is exactly the condition under which
                # jit compiles: flip the run state to `compiling` before the
                # (potentially minutes-long) compile starts
                tele._goodput.note_compile_start(self.name)
        if self._use_aot:
            compiled = self._compiled.get(sig)
            if compiled is None:
                compiled = self._aot_compile(sig, args, kwargs)
            if compiled is not None:
                self._signature = sig
                try:
                    out = self._invoke(compiled, args, kwargs)
                except Exception as err:
                    if getattr(err, "_sheeprl_diag_handled", False):
                        # the memory monitor already journaled this (blocked
                        # host transfer / OOM forensics): it is a run problem,
                        # not an AOT-path problem — do NOT fall back
                        raise
                    # sharding/committed-ness corner the AOT call rejects:
                    # permanently revert to the native dispatch path
                    self._use_aot = False
                    self._compiled.clear()
                    tele._journal(
                        "telemetry_fallback",
                        fn=self.name,
                        stage="aot_dispatch",
                        error=repr(err)[:200],
                    )
                    out = self._invoke(self._fn, args, kwargs, retry=True)
                tele._record_call(self)
                return out
        self._signature = sig
        out = self._invoke(self._fn, args, kwargs)
        if new_sig and self._cache_size_probe is not None:
            # compile-cache-size probe (the no-jax.monitoring fallback): a
            # grown cache confirms the signature change was a real compile —
            # counted only when the monitoring listener can't (no double count)
            try:
                size = int(self._cache_size_probe())
                if size > self._last_cache_size:
                    self._last_cache_size = size
                    if not getattr(tele, "_monitoring_ok", False):
                        tele._note_backend_compile(0.0)
            except Exception:  # pragma: no cover - private API drift
                self._cache_size_probe = None
        tele._record_call(self)
        return out

    def _invoke(self, fn: Callable, args: Tuple[Any, ...], kwargs: Mapping[str, Any], retry: bool = False):
        """The actual dispatch, routed through the memory monitor's guarded
        scope (transfer guard / audits / OOM forensics) when one is attached.
        ``retry`` marks the AOT-fallback re-dispatch of the same logical step
        (the monitor must not count it twice)."""
        mem = self._telemetry._memory
        if mem is None:
            return fn(*args, **kwargs)
        return mem.guarded_call(self, lambda: fn(*args, **kwargs), args, kwargs, count_call=not retry)

    def _fresh_compile(self, args, kwargs):
        """The one place a new executable is built — the warm-restart tests
        monkeypatch/count this to prove a cached restart compiles nothing."""
        return self._fn.lower(*args, **kwargs).compile()

    def _aot_compile(self, sig, args, kwargs):
        tele = self._telemetry
        cache_path = fingerprint = None
        if tele.aot_cache_dir:
            cache_path = aot_cache_path(tele.aot_cache_dir, self.name, sig, tele._aot_cache_salt)
            fingerprint = aot_cache_fingerprint()
            hit, miss_reason = _aot_cache_read(cache_path, fingerprint)
            if hit is not None:
                compiled, flops = hit
                if flops:
                    self._flops_by_sig[sig] = flops
                self._compiled[sig] = compiled
                if tele._memory is not None:
                    tele._memory.note_executable(self.name, compiled)
                hit_fields = dict(fn=self.name, path=cache_path, flops_per_call=flops)
                if self.cost_note:
                    # the warm restart never journals a telemetry_cost event,
                    # so the FLOPs-inflation caveat must ride the hit itself —
                    # the loaded FLOPs feed Telemetry/mfu exactly like fresh
                    # ones would
                    hit_fields["note"] = self.cost_note
                tele._journal("aot_cache_hit", **hit_fields)
                return compiled
            tele._journal(
                "aot_cache_miss", fn=self.name, stage="load", reason=miss_reason, path=cache_path
            )
        try:
            t0 = time.perf_counter()
            compiled = self._fresh_compile(args, kwargs)
            compile_s = time.perf_counter() - t0
            flops = _cost_flops(compiled)
            if flops:
                self._flops_by_sig[sig] = flops
                cost_fields = dict(
                    fn=self.name, flops_per_call=flops, compile_s=round(compile_s, 3)
                )
                if self.cost_note:
                    cost_fields["note"] = self.cost_note
                tele._journal("telemetry_cost", **cost_fields)
            self._compiled[sig] = compiled
            if tele._memory is not None:
                # the executable's memory_analysis (activation temps etc.)
                # feeds the memory_breakdown event — zero extra compiles
                tele._memory.note_executable(self.name, compiled)
            if cache_path is not None:
                store_err = _aot_cache_write(cache_path, fingerprint, compiled, flops)
                if store_err is not None:
                    # backends without executable serialization: the run is
                    # unaffected, but the next restart will compile again —
                    # journal it so "why was the restart cold?" has an answer
                    tele._journal(
                        "aot_cache_miss",
                        fn=self.name,
                        stage="store",
                        reason=f"store_failed: {store_err}",
                        path=cache_path,
                    )
            return compiled
        except Exception as err:
            self._use_aot = False
            self._compiled.clear()
            tele._journal(
                "telemetry_fallback", fn=self.name, stage="aot_compile", error=repr(err)[:200]
            )
            return None

    @property
    def flops_per_call(self) -> Optional[float]:
        """FLOPs of the signature dispatched last (None until captured)."""
        if self._signature is not None and self._signature in self._flops_by_sig:
            return self._flops_by_sig[self._signature]
        # fallback for signatures whose AOT capture failed: any known one
        return next(iter(self._flops_by_sig.values()), None)


def _cost_flops(compiled: Any) -> Optional[float]:
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0)) or None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# persistent AOT executable cache (diagnostics.compilation_cache_dir)

#: Bumped when the on-disk entry layout changes; part of the fingerprint so
#: old entries invalidate cleanly instead of failing to unpickle.
AOT_CACHE_FORMAT = 1


def _code_fingerprint() -> str:
    """Version component of the cache fingerprint: package version plus — in
    a git checkout — the HEAD revision (read from ``.git`` directly, no
    subprocess).  The executable cache skips lowering entirely, so unlike
    JAX's own compilation cache it can never notice a source edit via the
    HLO hash; this component invalidates on version bumps and commits
    instead.  (Uncommitted source edits in a dev checkout still hit stale
    entries — clear the cache dir when iterating on graph code.)"""
    try:
        import sheeprl_tpu

        version = str(getattr(sheeprl_tpu, "__version__", "?"))
        root = os.path.dirname(os.path.dirname(os.path.abspath(sheeprl_tpu.__file__)))
        head_path = os.path.join(root, ".git", "HEAD")
        rev = ""
        if os.path.exists(head_path):
            with open(head_path) as fh:
                head = fh.read().strip()
            if head.startswith("ref:"):
                ref_path = os.path.join(root, ".git", *head.split(" ", 1)[1].split("/"))
                if os.path.exists(ref_path):
                    with open(ref_path) as fh:
                        rev = fh.read().strip()[:12]
            else:
                rev = head[:12]
        return f"{version}@{rev}" if rev else version
    except Exception:  # pragma: no cover - never block the cache on this
        return "?"


def aot_cache_fingerprint() -> str:
    """Environment stamp an executable is only valid under: code version
    (package version + git HEAD when available), jax + jaxlib versions,
    backend platform, device kind and device count (a serialized executable
    is compiled FOR a specific code revision, runtime and topology)."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "?")
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = "?"
    try:
        devices = jax.devices()
        kind = devices[0].device_kind if devices else ""
        count = len(devices)
    except Exception:  # pragma: no cover - pre-init probes
        kind, count = "", 0
    return "|".join(
        [
            f"fmt{AOT_CACHE_FORMAT}",
            _code_fingerprint(),
            jax.__version__,
            str(jaxlib_version),
            jax.default_backend(),
            str(kind),
            str(count),
        ]
    )


def aot_cache_path(cache_dir: str, name: str, sig: Tuple[str, Tuple], salt: str) -> str:
    """Entry file for one (fn, dispatch signature, config) triple.  The
    fingerprint is deliberately NOT part of the key: a jax upgrade then reads
    the old entry and journals ``fingerprint_mismatch`` (observable
    invalidation) instead of silently orphaning files."""
    import hashlib

    digest = hashlib.sha256(repr((name, sig, salt)).encode()).hexdigest()[:32]
    safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in name)[:48]
    return os.path.join(str(cache_dir), f"{safe}-{digest}.aotx")


def _aot_cache_read(path: str, fingerprint: str):
    """(compiled, flops) from one cache entry, or (None, reason) on any miss.
    Every failure mode — missing file, truncated/corrupt pickle, wrong
    fingerprint, deserialize rejection — is a *reason string*, never an
    exception: the caller always has the fresh-compile fallback."""
    import pickle

    if not os.path.exists(path):
        return None, "absent"
    try:
        with open(path, "rb") as fh:
            entry = pickle.load(fh)
        if not isinstance(entry, dict):
            return None, "corrupt"
    except Exception:
        return None, "corrupt"
    if entry.get("fingerprint") != fingerprint:
        return None, "fingerprint_mismatch"
    try:
        from jax.experimental import serialize_executable

        compiled = serialize_executable.deserialize_and_load(
            entry["payload"], entry["in_tree"], entry["out_tree"]
        )
        return (compiled, entry.get("flops")), None
    except Exception:
        return None, "corrupt"


def _aot_cache_write(path: str, fingerprint: str, compiled: Any, flops: Optional[float]) -> Optional[str]:
    """Serialize ``compiled`` to ``path`` (atomic tmp+rename so a crashed
    writer can only ever leave a *missing* entry, not a half one).  Returns an
    error string on failure (backends without executable serialization),
    None on success."""
    import pickle

    try:
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        entry = {
            "fingerprint": fingerprint,
            "flops": flops,
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            pickle.dump(entry, fh)
        os.replace(tmp, path)
        return None
    except Exception as err:
        return repr(err)[:200]


# ---------------------------------------------------------------------------
# telemetry core


class Telemetry:
    """Per-run perf accounting: watchdog state, FLOPs/phase/step counters and
    the interval math behind the ``Telemetry/*`` gauges.

    Thread-safe (spans may close on whatever thread runs the loop; the
    metrics server snapshots from its own thread).  ``clock`` is injectable
    for deterministic tests.
    """

    def __init__(self, cfg: Optional[Mapping[str, Any]], clock: Callable[[], float] = time.perf_counter):
        cfg = cfg or {}
        diag_cfg = (cfg.get("diagnostics") or {}) if cfg else {}
        tele_cfg = diag_cfg.get("telemetry") or {}
        self.enabled = bool(tele_cfg.get("enabled", True))
        wd_cfg = tele_cfg.get("watchdog") or {}
        self.watchdog_enabled = bool(wd_cfg.get("enabled", True))
        # clamped: threshold 0 would turn EVERY recompile into a storm
        self.storm_threshold = max(1, int(wd_cfg.get("storm_threshold", 5)))
        self.storm_window_s = float(wd_cfg.get("storm_window_s", 60.0))
        inject = wd_cfg.get("inject_shape_change_iter")
        self.inject_shape_change_iter = None if inject is None else int(inject)
        mfu_cfg = tele_cfg.get("mfu") or {}
        self.mfu_enabled = bool(mfu_cfg.get("enabled", True))
        self.cost_analysis_enabled = self.mfu_enabled and bool(mfu_cfg.get("cost_analysis", True))
        self._peak_override = mfu_cfg.get("peak_tflops_per_device")
        http_cfg = tele_cfg.get("http") or {}
        self.http_enabled = bool(http_cfg.get("enabled", False))
        self.http_host = str(http_cfg.get("host", "127.0.0.1"))
        self.http_port = int(http_cfg.get("port", 0))
        # persistent AOT executable cache: same directory as JAX's on-disk
        # compilation cache (diagnostics.compilation_cache_dir — both are
        # restart accelerators and both are off when it is null).  The salt
        # folds the config identity into every cache key: two runs with
        # identical dispatch signatures but different graphs (e.g.
        # scan_unroll / rssm_chunks flips) must never share an executable.
        self.aot_cache_dir = str(diag_cfg.get("compilation_cache_dir") or "") or None
        self._aot_cache_salt = ""
        if self.aot_cache_dir:
            try:
                from sheeprl_tpu.diagnostics import config_hash
                from sheeprl_tpu.utils.utils import dotdict

                # hash only the GRAPH-shaping config sections: restarts and
                # resumes legitimately differ in run identity (run_name,
                # checkpoint.resume_from, seed, logging) and must still hit;
                # anything that changes the compiled graph without changing
                # the dispatch signature (scan_unroll, rssm_chunk_burn_in,
                # horizon, sentinel/health toggles, precision) must MISS.
                # Sections are deep-converted to plain dicts first: the CLI
                # hands dotdict sections, which yaml.safe_dump rejects.
                graph_cfg = {}
                for k in ("algo", "env", "fabric", "distribution", "diagnostics", "buffer"):
                    v = (cfg or {}).get(k)
                    if v is None:
                        continue
                    graph_cfg[k] = dotdict(v).as_dict() if isinstance(v, dict) else v
                self._aot_cache_salt = config_hash(graph_cfg)
            except Exception as err:
                # an un-hashable config must DISABLE the cache, not fall back
                # to an empty salt: an empty salt would let two different
                # graphs with identical dispatch signatures share an
                # executable
                self.aot_cache_dir = None
                warnings.warn(
                    "diagnostics.compilation_cache_dir is set but the config could not "
                    f"be hashed for the AOT executable cache key ({err!r}); the "
                    "executable cache is DISABLED for this run (JAX's own on-disk "
                    "compilation cache is unaffected).",
                    RuntimeWarning,
                )

        self._precision = str((cfg.get("fabric") or {}).get("precision", "32-true")) if cfg else "32-true"
        self._clock = clock
        # the facade attaches the MemoryMonitor here so instrumented
        # dispatches pick up the transfer guard / audits / OOM forensics
        self._memory = None
        # ... and the (rank-0, opened) GoodputMonitor so compiles/dispatches
        # drive the run-state machine and feed the stall watchdog
        self._goodput = None
        self._lock = threading.Lock()
        self._journal_fn: Optional[Callable[..., None]] = None
        self._span_stack = threading.local()

        # phase self-times (seconds): cumulative + current interval
        self._phase_total: Dict[str, float] = {}
        self._phase_interval: Dict[str, float] = {}
        # instrumented-call accounting
        self._instrumented: Dict[str, _Instrumented] = {}
        self._calls_total: Dict[str, int] = {}
        self._calls_interval: Dict[str, int] = {}
        self._train_flops_interval = 0.0
        self._train_flops_total = 0.0
        # env throughput: vector env steps (note_env_steps) over wall-clock,
        # and how many of them each blocking rollout fetch amortizes (one
        # kind="rollout" dispatch == one obs->action->fetch round trip)
        self._env_steps_interval = 0
        self._env_steps_total = 0
        self._rollout_calls_interval = 0
        # offline dataset feed: rows streamed from the loader (the env-free
        # mode's throughput axis) and the loader's epoch counter
        self._dataset_rows_interval = 0
        self._dataset_rows_total = 0
        self._dataset_epoch: Optional[float] = None
        # watchdog
        self._recompiles_total = 0
        self._recompile_times: deque = deque()
        self._storms_total = 0
        # global compile monitor
        self._backend_compiles = 0
        self._backend_compile_s = 0.0
        # sentinel mirror (the /metrics counter)
        self._sentinel_events = 0
        self._monitoring_ok = False
        # interval bookkeeping
        self._tick_t: Optional[float] = None
        self._tick_step: Optional[float] = None
        self._peak_flops_total: Optional[float] = None
        self._device_count = 1
        self._latest: Dict[str, float] = {}
        self._info: Dict[str, Any] = {}

    # -- lifecycle ---------------------------------------------------------
    def open(self, journal_fn: Optional[Callable[..., None]] = None, info: Optional[Mapping[str, Any]] = None) -> None:
        self._journal_fn = journal_fn
        self._info = dict(info or {})
        self._tick_t = self._clock()
        self._monitoring_ok = monitoring_available()
        _attach_collector(self)
        self._resolve_peak()

    def close(self) -> None:
        _detach_collector(self)

    def _resolve_peak(self) -> None:
        try:
            import jax

            devices = jax.devices()
            self._device_count = max(1, len(devices))
            kind = devices[0].device_kind if devices else ""
        except Exception:  # pragma: no cover - pre-init probes
            kind = ""
        if self._peak_override is not None:
            per_device = float(self._peak_override) * 1e12
        else:
            per_device = resolve_peak_flops(kind, self._precision)
        if per_device:
            self._peak_flops_total = per_device * self._device_count
        self._info.setdefault("device_kind", kind)

    def _journal(self, event: str, **fields: Any) -> None:
        if self._journal_fn is not None:
            self._journal_fn(event, **fields)

    # -- instrumentation ---------------------------------------------------
    def instrument(
        self,
        name: str,
        fn: Callable,
        kind: str = "train",
        donate_argnums: Tuple[int, ...] = (),
        cost_note: Optional[str] = None,
    ) -> Callable:
        if not self.enabled:
            return fn
        wrapped = _Instrumented(
            self, name, fn, kind, donate_argnums=donate_argnums, cost_note=cost_note
        )
        self._instrumented[name] = wrapped
        return wrapped

    def _record_call(self, inst: _Instrumented) -> None:
        with self._lock:
            self._calls_total[inst.name] = self._calls_total.get(inst.name, 0) + 1
            self._calls_interval[inst.name] = self._calls_interval.get(inst.name, 0) + 1
            if inst.kind == "train" and inst.flops_per_call:
                self._train_flops_interval += inst.flops_per_call
                self._train_flops_total += inst.flops_per_call
            if inst.kind == "rollout":
                self._rollout_calls_interval += 1
        if self._goodput is not None:
            # outside the lock on purpose: the stall fault injection sleeps
            # in this notification, and the watchdog thread must be able to
            # take its own lock (and read counters here) meanwhile
            self._goodput.note_dispatch(inst.name, inst.kind)

    def note_env_steps(self, n: int) -> None:
        """Count ``n`` environment steps (loops call it once per vector step
        with ``num_envs``) — feeds ``Telemetry/env_steps_per_sec`` and the
        fetch-amortization gauge."""
        with self._lock:
            self._env_steps_interval += int(n)
            self._env_steps_total += int(n)

    def note_fetch(self, n: int = 1) -> None:
        """Count a blocking obs→action fetch that did NOT go through an
        instrumented ``kind="rollout"`` dispatch (the Dreamer player fetches
        its action values directly)."""
        with self._lock:
            self._rollout_calls_interval += int(n)

    def note_dataset_rows(self, n: int) -> None:
        """Count ``n`` transitions streamed from an offline dataset loader —
        feeds ``Telemetry/dataset_read_sps`` (howto/offline_rl.md)."""
        with self._lock:
            self._dataset_rows_interval += int(n)
            self._dataset_rows_total += int(n)

    def note_dataset_epoch(self, epoch: float) -> None:
        """Record the offline loader's epoch counter — the
        ``Telemetry/dataset_epoch`` gauge."""
        with self._lock:
            self._dataset_epoch = float(epoch)

    def _watchdog_observe(self, inst: _Instrumented, sig, args, kwargs) -> None:
        """One *new* dispatch signature on an already-compiled fn == one
        recompile (the caller filters the expected first compile)."""
        if not self.watchdog_enabled:
            return
        diff = signature_diff(inst._signature, sig, _leaf_paths(args, kwargs))
        now = self._clock()
        with self._lock:
            self._recompiles_total += 1
            total = self._recompiles_total
            self._recompile_times.append(now)
            while self._recompile_times and now - self._recompile_times[0] > self.storm_window_s:
                self._recompile_times.popleft()
            storm = len(self._recompile_times) >= self.storm_threshold
            if storm:
                self._storms_total += 1
                self._recompile_times.clear()  # cooldown: re-arm the window
        self._journal("recompile", fn=inst.name, count=total, diff=diff)
        if storm:
            self._journal(
                "recompile_storm",
                recompiles_in_window=self.storm_threshold,
                window_s=self.storm_window_s,
                total=total,
            )
            warnings.warn(
                f"Recompile storm: >= {self.storm_threshold} recompiles within "
                f"{self.storm_window_s:g}s (total {total}). Something is feeding the "
                "jitted steps varying shapes/dtypes — check the `recompile` journal "
                "events for the leaf diff.",
                RuntimeWarning,
            )

    def _note_backend_compile(self, secs: float) -> None:
        with self._lock:
            self._backend_compiles += 1
            self._backend_compile_s += secs

    def count_sentinel_event(self, n: int = 1) -> None:
        with self._lock:
            self._sentinel_events += int(n)

    def train_seconds(self) -> float:
        """Cumulative self-time of the ``train`` spans — the exact numerator
        of the goodput gauge (includes any compile that ran inside a train
        span; the state machine's ``state_seconds`` splits `compiling` out)."""
        with self._lock:
            return self._phase_total.get("train", 0.0)

    # -- phase spans -------------------------------------------------------
    def span(self, name: str):
        """Standalone span context manager (the facade routes its ``span``
        through ``span_enter``/``span_exit`` directly; bench.py uses this to
        produce the same phase accounting without a facade)."""
        from contextlib import contextmanager

        @contextmanager
        def _span():
            token = self.span_enter(name)
            try:
                yield
            finally:
                self.span_exit(token)

        return _span()

    def span_enter(self, name: str) -> List:
        stack = getattr(self._span_stack, "stack", None)
        if stack is None:
            stack = self._span_stack.stack = []
        rec = [name, self._clock(), 0.0]  # [name, t0, child seconds]
        stack.append(rec)
        return rec

    def span_exit(self, rec: List) -> None:
        stack = getattr(self._span_stack, "stack", None)
        dur = self._clock() - rec[1]
        if stack and stack[-1] is rec:
            stack.pop()
        if stack:
            stack[-1][2] += dur
        self_time = max(0.0, dur - rec[2])
        with self._lock:
            name = rec[0]
            self._phase_total[name] = self._phase_total.get(name, 0.0) + self_time
            self._phase_interval[name] = self._phase_interval.get(name, 0.0) + self_time

    # -- interval math -----------------------------------------------------
    # The phase -> bucket map behind Telemetry/phase_pct/*: `env` is host
    # work spent driving the envs/policy (rollout bookkeeping + async issue),
    # `fetch` is blocking waits on env results and batch staging, `train` is
    # the train-step dispatch+fetch, everything else (checkpoint, custom
    # spans) lands in `other`, and `idle` is wall-clock no span accounted for.
    _PHASE_BUCKETS = {
        "rollout": "env",
        "env_step_async": "env",
        "env_wait": "fetch",
        "buffer-sample": "fetch",
        "train": "train",
    }

    def interval_metrics(self, step: Optional[float]) -> Dict[str, float]:
        """Close the current accounting interval and return its Telemetry/*
        gauges (called by the facade once per aggregated-metrics interval)."""
        if not self.enabled:
            return {}
        now = self._clock()
        out: Dict[str, float] = {}
        with self._lock:
            dt = (now - self._tick_t) if self._tick_t is not None else 0.0
            if dt > 0:
                if step is not None and self._tick_step is not None and step >= self._tick_step:
                    out[TELEMETRY_PREFIX + "sps"] = (float(step) - self._tick_step) / dt
                if self._train_flops_interval > 0 and self.mfu_enabled:
                    flops_per_s = self._train_flops_interval / dt
                    out[TELEMETRY_PREFIX + "tflops_per_sec"] = flops_per_s / 1e12
                    if self._peak_flops_total:
                        out[TELEMETRY_PREFIX + "mfu"] = flops_per_s / self._peak_flops_total
                if self._env_steps_interval > 0:
                    out[TELEMETRY_PREFIX + "env_steps_per_sec"] = self._env_steps_interval / dt
                    if self._rollout_calls_interval > 0:
                        # env steps per blocking obs->action fetch: num_envs
                        # when the player batches all envs behind one d2h
                        out[TELEMETRY_PREFIX + "fetch_amortization"] = (
                            self._env_steps_interval / self._rollout_calls_interval
                        )
                if self._dataset_rows_interval > 0:
                    out[TELEMETRY_PREFIX + "dataset_read_sps"] = self._dataset_rows_interval / dt
                if self._phase_interval:
                    buckets: Dict[str, float] = {}
                    for name, secs in self._phase_interval.items():
                        bucket = self._PHASE_BUCKETS.get(name, "other")
                        buckets[bucket] = buckets.get(bucket, 0.0) + secs
                    accounted = sum(buckets.values())
                    buckets["idle"] = max(0.0, dt - accounted)
                    for bucket, secs in sorted(buckets.items()):
                        out[TELEMETRY_PREFIX + f"phase_pct/{bucket}"] = 100.0 * secs / dt
            if self._dataset_epoch is not None:
                out[TELEMETRY_PREFIX + "dataset_epoch"] = self._dataset_epoch
            out[TELEMETRY_PREFIX + "recompiles"] = float(self._recompiles_total)
            out[TELEMETRY_PREFIX + "compile_count"] = float(self._backend_compiles)
            out[TELEMETRY_PREFIX + "compile_time_s"] = round(self._backend_compile_s, 3)
            # reset the interval accumulators
            self._phase_interval = {}
            self._calls_interval = {}
            self._train_flops_interval = 0.0
            self._env_steps_interval = 0
            self._rollout_calls_interval = 0
            self._dataset_rows_interval = 0
            self._tick_t = now
            if step is not None:
                self._tick_step = float(step)
            self._latest = dict(out)
        return out

    # -- snapshots (metrics server / run summary) --------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "info": dict(self._info),
                "gauges": dict(self._latest),
                "counters": {
                    "recompiles_total": self._recompiles_total,
                    "recompile_storms_total": self._storms_total,
                    "backend_compiles_total": self._backend_compiles,
                    "compile_seconds_total": round(self._backend_compile_s, 3),
                    "sentinel_events_total": self._sentinel_events,
                    "train_flops_total": self._train_flops_total,
                    "env_steps_total": self._env_steps_total,
                    "dataset_rows_read_total": self._dataset_rows_total,
                },
                "policy_steps": self._tick_step,
                "phase_seconds_total": dict(self._phase_total),
                "calls_total": dict(self._calls_total),
                "flops_per_call": {
                    name: inst.flops_per_call
                    for name, inst in self._instrumented.items()
                    if inst.flops_per_call
                },
            }

    def summary(self) -> Dict[str, Any]:
        """Cumulative run totals for the closing ``telemetry_summary`` event."""
        snap = self.snapshot()
        return {
            "recompiles": snap["counters"]["recompiles_total"],
            "recompile_storms": snap["counters"]["recompile_storms_total"],
            "backend_compiles": snap["counters"]["backend_compiles_total"],
            "compile_time_s": snap["counters"]["compile_seconds_total"],
            "train_flops_total": snap["counters"]["train_flops_total"],
            "phase_seconds": {k: round(v, 3) for k, v in snap["phase_seconds_total"].items()},
            "instrumented_calls": snap["calls_total"],
        }
