"""Pallas TPU kernel: fused LayerNorm-GRU cell.

The RSSM's hot op (SURVEY §7.10's Pallas candidate) is the recurrent cell
stepped T times under ``lax.scan``: ``concat(h, x) @ W`` (one MXU matmul)
followed by LayerNorm over the joint ``3H`` projection and the gate
elementwise chain (reference models.py:331-410; our flax cell
``sheeprl_tpu/models/blocks.py:LayerNormGRUCell``).  This kernel runs the
whole step in one ``pallas_call``: the weight matrix stays resident in VMEM
across the batch grid, and the LN + sigmoid/tanh gate math happens on the VPU
without round-tripping the ``[B, 3H]`` projection through HBM.

Semantics are bit-compatible with the flax cell (gate order reset|cand|update,
``cand = tanh(reset * cand)``, ``update = sigmoid(update - 1)``), pinned by
``tests/test_ops/test_pallas_gru.py`` against the flax cell and the golden GRU
fixture.  Gradients: ``jax.custom_vjp`` whose backward recomputes the step
with plain jnp ops (rematerialization) and reuses XLA's autodiff — the
backward is a standard fused XLA graph, the forward (the op run T times per
scan in both dynamic learning and imagination) is the Pallas kernel.

Eligibility (checked by ``fused_gru_supported``): TPU backend (or
``interpret=True`` for CPU tests), ``3H`` a lane multiple (all DV3 size
presets satisfy this), and the weight block fitting VMEM.  Ineligible shapes
fall back to the flax path.

Measured on a v5-lite chip (H=512, B=1024, 64-step scan): the XLA-compiled
flax cell runs the scan in ~147 ms vs ~230 ms through this kernel — XLA's own
matmul+LN+gate fusion is already sufficient at RSSM shapes (consistent with
SURVEY §2.8's "Pallas only where XLA fusion is insufficient"), so the fused
path ships **off by default** (``algo.world_model.recurrent_model.
fused_kernel``) as a verified building block for shapes where the balance
tips (e.g. much larger H where W residency dominates).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_LANE = 128
_SUBLANE = 8
# keep W + one batch tile comfortably inside ~16 MB of VMEM
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_BATCH_BLOCK = 256


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def fused_gru_supported(joint_dim: int, hidden_size: int, use_bias: bool = True) -> bool:
    """Shape/platform eligibility for the fused kernel."""
    del use_bias
    if (3 * hidden_size) % _LANE != 0:
        return False
    d_pad = _round_up(joint_dim, _LANE)
    w_bytes = d_pad * 3 * hidden_size * 4
    tile_bytes = _BATCH_BLOCK * (d_pad + 6 * hidden_size) * 4
    return w_bytes + tile_bytes <= _VMEM_BUDGET_BYTES


def _gru_kernel(joint_ref, w_ref, b_ref, g_ref, beta_ref, h_ref, out_ref, *, eps: float):
    """One batch tile: projection (MXU, native input dtype with fp32
    accumulation) + LayerNorm + gates (VPU, fp32)."""
    a = jnp.dot(joint_ref[:], w_ref[:], preferred_element_type=jnp.float32) + b_ref[:].astype(
        jnp.float32
    )
    # LayerNorm over the 3H projection
    mean = jnp.mean(a, axis=-1, keepdims=True)
    centered = a - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    n = centered * jax.lax.rsqrt(var + eps)
    n = n * g_ref[:].astype(jnp.float32) + beta_ref[:].astype(jnp.float32)
    hidden = out_ref.shape[-1]
    reset = jax.nn.sigmoid(n[:, :hidden])
    cand = jnp.tanh(reset * n[:, hidden : 2 * hidden])
    update = jax.nn.sigmoid(n[:, 2 * hidden :] - 1.0)
    h = h_ref[:].astype(jnp.float32)
    out_ref[:] = (update * cand + (1.0 - update) * h).astype(out_ref.dtype)


def _gru_pallas(joint: jax.Array, w: jax.Array, b: jax.Array, g: jax.Array, beta: jax.Array,
                h: jax.Array, *, eps: float, interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, joint_dim = joint.shape
    hidden = h.shape[-1]
    three_h = 3 * hidden

    # pad the contraction dim to lanes (zero rows of W contribute nothing) and
    # the batch dim to the tile grid
    d_pad = _round_up(joint_dim, _LANE)
    bm = min(_BATCH_BLOCK, _round_up(batch, _SUBLANE))
    b_pad = _round_up(batch, bm)
    if d_pad != joint_dim:
        joint = jnp.pad(joint, ((0, 0), (0, d_pad - joint_dim)))
        w = jnp.pad(w, ((0, d_pad - joint_dim), (0, 0)))
    if b_pad != batch:
        joint = jnp.pad(joint, ((0, b_pad - batch), (0, 0)))
        h = jnp.pad(h, ((0, b_pad - batch), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_gru_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((b_pad, hidden), h.dtype),
        grid=(b_pad // bm,),
        in_specs=[
            pl.BlockSpec((bm, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d_pad, three_h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, three_h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, three_h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, three_h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(joint, w, b.reshape(1, -1), g.reshape(1, -1), beta.reshape(1, -1), h)
    return out[:batch]


def _gru_reference(joint, w, b, g, beta, h, eps):
    """Plain-jnp step, numerically identical to the kernel — used for the
    custom-VJP backward (remat) and as the fallback path."""
    a = jnp.dot(joint, w, preferred_element_type=jnp.float32) + b.astype(jnp.float32)
    mean = jnp.mean(a, axis=-1, keepdims=True)
    centered = a - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    n = centered * jax.lax.rsqrt(var + eps)
    n = n * g.astype(jnp.float32) + beta.astype(jnp.float32)
    hidden = h.shape[-1]
    reset = jax.nn.sigmoid(n[:, :hidden])
    cand = jnp.tanh(reset * n[:, hidden : 2 * hidden])
    update = jax.nn.sigmoid(n[:, 2 * hidden :] - 1.0)
    return (update * cand + (1.0 - update) * h.astype(jnp.float32)).astype(h.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def fused_layernorm_gru(joint, w, b, g, beta, h, eps: float = 1e-3, interpret: bool = False):
    """``new_h = GRU(LN(joint @ w + b; g, beta), h)`` as one Pallas kernel."""
    return _gru_pallas(joint, w, b, g, beta, h, eps=eps, interpret=interpret)


def _fused_fwd(joint, w, b, g, beta, h, eps, interpret):
    out = _gru_pallas(joint, w, b, g, beta, h, eps=eps, interpret=interpret)
    return out, (joint, w, b, g, beta, h)


def _fused_bwd(eps, interpret, residuals, cotangent):
    del interpret
    joint, w, b, g, beta, h = residuals
    _, vjp = jax.vjp(lambda *args: _gru_reference(*args, eps), joint, w, b, g, beta, h)
    return vjp(cotangent)


fused_layernorm_gru.defvjp(_fused_fwd, _fused_bwd)
