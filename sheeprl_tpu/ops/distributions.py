"""JAX distribution library.

Replaces the reference's torch.distributions subclasses
(/root/reference/sheeprl/utils/distribution.py:25-416) with lightweight pure
classes over ``jax.Array``.  Every object here is safe to construct *inside* a
jitted function: construction does no host work, sampling takes an explicit
PRNG key, and gradients flow through ``rsample``-style reparameterization or
straight-through estimators built on ``stop_gradient``.

Conventions:
- ``sample(key)`` draws without gradient; ``rsample(key)`` reparameterizes.
- ``log_prob(x)`` sums over declared event dims (like torch's Independent).
- mixed precision: samples/modes keep the dtype of the parameters they were
  built from (so bf16 stays bf16 through the RSSM hot path), while
  ``log_prob``/``entropy``/KL and the value-reading heads (two-hot ``mean``)
  compute in fp32 — the loss boundary is where bf16 error compounds.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.ops.numerics import safeatanh, safetanh, symexp, symlog


def _sum_last_dims(x: jax.Array, dims: int) -> jax.Array:
    if dims == 0:
        return x
    return jnp.sum(x, axis=tuple(range(-dims, 0)))


def _f32(x: jax.Array) -> jax.Array:
    return x.astype(jnp.float32) if jnp.issubdtype(x.dtype, jnp.floating) else x


class Normal:
    """Diagonal normal with optional event dims (Independent(Normal, dims))."""

    def __init__(self, loc: jax.Array, scale: jax.Array, event_dims: int = 0):
        self.loc = loc
        self.scale = scale
        self.event_dims = event_dims

    @property
    def mean(self) -> jax.Array:
        return self.loc

    @property
    def mode(self) -> jax.Array:
        return self.loc

    @property
    def stddev(self) -> jax.Array:
        return self.scale

    def rsample(self, key: jax.Array) -> jax.Array:
        eps = jax.random.normal(key, self.loc.shape, dtype=self.loc.dtype)
        return self.loc + self.scale * eps

    sample = rsample

    def log_prob(self, value: jax.Array) -> jax.Array:
        loc, scale, value = _f32(self.loc), _f32(self.scale), _f32(value)
        var = scale**2
        lp = -((value - loc) ** 2) / (2 * var) - jnp.log(scale) - 0.5 * math.log(2 * math.pi)
        return _sum_last_dims(lp, self.event_dims)

    def entropy(self) -> jax.Array:
        ent = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(_f32(self.scale))
        return _sum_last_dims(ent, self.event_dims)


class TanhNormal:
    """Squashed diagonal Gaussian (SAC actor).  The log-prob uses the
    tanh change-of-variables with the numerically-safe atanh of the reference
    (utils/utils.py:303-316, algos/sac/agent.py squashed log-prob)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, event_dims: int = 1, eps: float = 1e-6):
        self.base = Normal(loc, scale, event_dims=0)
        self.event_dims = event_dims
        self.eps = eps

    @property
    def mean(self) -> jax.Array:
        return jnp.tanh(self.base.loc)

    mode = mean

    def rsample_and_log_prob(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = self.base.rsample(key)
        y = safetanh(x, self.eps)
        lp = self.base.log_prob(x) - jnp.log1p(-(_f32(y) ** 2) + self.eps)
        return y, _sum_last_dims(lp, self.event_dims)

    def rsample(self, key: jax.Array) -> jax.Array:
        return self.rsample_and_log_prob(key)[0]

    sample = rsample

    def log_prob(self, value: jax.Array) -> jax.Array:
        value = _f32(value)
        x = safeatanh(value, self.eps)
        lp = self.base.log_prob(x) - jnp.log1p(-(value**2) + self.eps)
        return _sum_last_dims(lp, self.event_dims)


class TruncatedNormal:
    """Truncated normal on [a, b] with reparameterized sampling
    (reference distribution.py:25-149, DreamerV1/V2 continuous actor)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, a: float = -1.0, b: float = 1.0, event_dims: int = 1):
        self.loc = loc
        self.scale = scale
        self.a = a
        self.b = b
        self.event_dims = event_dims
        self._alpha = (a - loc) / scale
        self._beta = (b - loc) / scale

    @staticmethod
    def _big_phi(x: jax.Array) -> jax.Array:
        return 0.5 * (1 + jax.lax.erf(x / math.sqrt(2)))

    @staticmethod
    def _inv_big_phi(x: jax.Array) -> jax.Array:
        return math.sqrt(2) * jax.lax.erf_inv(2 * x - 1)

    @property
    def _Z(self) -> jax.Array:
        return jnp.clip(self._big_phi(self._beta) - self._big_phi(self._alpha), 1e-8, None)

    @property
    def mean(self) -> jax.Array:
        phi = lambda x: jnp.exp(-0.5 * x**2) / math.sqrt(2 * math.pi)
        return self.loc + self.scale * (phi(self._alpha) - phi(self._beta)) / self._Z

    @property
    def mode(self) -> jax.Array:
        return jnp.clip(self.loc, self.a, self.b)

    def rsample(self, key: jax.Array) -> jax.Array:
        u = jax.random.uniform(key, self.loc.shape, dtype=self.loc.dtype, minval=1e-6, maxval=1 - 1e-6)
        cdf_a = self._big_phi(self._alpha)
        x = self._inv_big_phi(cdf_a + u * self._Z)
        out = self.loc + self.scale * x
        # keep gradients through loc/scale but clamp the value into the support
        eps = 1e-6
        return jnp.clip(out, self.a + eps, self.b - eps)

    sample = rsample

    def log_prob(self, value: jax.Array) -> jax.Array:
        loc, scale, value = _f32(self.loc), _f32(self.scale), _f32(value)
        z = (value - loc) / scale
        lp = -0.5 * z**2 - 0.5 * math.log(2 * math.pi) - jnp.log(scale) - jnp.log(_f32(self._Z))
        return _sum_last_dims(lp, self.event_dims)

    def entropy(self) -> jax.Array:
        # differential entropy of the truncated normal
        phi = lambda x: jnp.exp(-0.5 * x**2) / math.sqrt(2 * math.pi)
        Z = self._Z
        term = (self._alpha * phi(self._alpha) - self._beta * phi(self._beta)) / (2 * Z)
        ent = 0.5 * math.log(2 * math.pi * math.e) + jnp.log(self.scale * Z) + term
        return _sum_last_dims(ent, self.event_dims)


class Categorical:
    """Categorical over the last axis of ``logits``."""

    def __init__(self, logits: jax.Array):
        logits = _f32(logits)
        self.logits = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def mode(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)

    def sample(self, key: jax.Array) -> jax.Array:
        return jax.random.categorical(key, self.logits, axis=-1)

    def log_prob(self, value: jax.Array) -> jax.Array:
        value = value.astype(jnp.int32)
        return jnp.take_along_axis(self.logits, value[..., None], axis=-1)[..., 0]

    def entropy(self) -> jax.Array:
        p = self.probs
        return -jnp.sum(p * self.logits, axis=-1)


class OneHotCategorical:
    """One-hot categorical, optionally with straight-through gradients
    (reference distribution.py:281-406 ``OneHotCategorical[StraightThrough]ValidateArgs``).

    ``event_dims`` follows torch's Independent: log_prob/entropy sum over that
    many trailing *batch* dims after the categorical reduction.
    """

    def __init__(self, logits: jax.Array, event_dims: int = 0):
        # normalize in fp32 (logsumexp in bf16 is lossy); samples are cast
        # back to the construction dtype so bf16 RSSM latents stay bf16
        self.dtype = logits.dtype
        logits = _f32(logits)
        self.logits = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)
        self.event_dims = event_dims

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def mode(self) -> jax.Array:
        idx = jnp.argmax(self.logits, axis=-1)
        return jax.nn.one_hot(idx, self.logits.shape[-1], dtype=self.dtype)

    @property
    def mean(self) -> jax.Array:
        return self.probs

    def sample(self, key: jax.Array) -> jax.Array:
        idx = jax.random.categorical(key, self.logits, axis=-1)
        return jax.nn.one_hot(idx, self.logits.shape[-1], dtype=self.dtype)

    def rsample(self, key: jax.Array) -> jax.Array:
        """Straight-through gradient sample: forward = hard one-hot,
        backward = softmax probabilities (stop_gradient trick)."""
        hard = self.sample(key)
        probs = self.probs.astype(self.dtype)
        return hard + probs - jax.lax.stop_gradient(probs)

    def straight_through(self, hard: jax.Array) -> jax.Array:
        probs = self.probs.astype(self.dtype)
        return hard + probs - jax.lax.stop_gradient(probs)

    def log_prob(self, value: jax.Array) -> jax.Array:
        lp = jnp.sum(value * self.logits, axis=-1)
        return _sum_last_dims(lp, self.event_dims)

    def entropy(self) -> jax.Array:
        p = self.probs
        ent = -jnp.sum(p * self.logits, axis=-1)
        return _sum_last_dims(ent, self.event_dims)


def kl_categorical(p_logits: jax.Array, q_logits: jax.Array, event_dims: int = 0) -> jax.Array:
    """KL(p || q) between categoricals over the last axis, summing ``event_dims``
    trailing batch dims (torch ``kl_divergence(Independent(OneHotCat...)...)``,
    used by DreamerV2/V3 KL balancing, reference algos/dreamer_v3/loss.py:70-83)."""
    p_logits, q_logits = _f32(p_logits), _f32(q_logits)
    p_logits = p_logits - jax.nn.logsumexp(p_logits, axis=-1, keepdims=True)
    q_logits = q_logits - jax.nn.logsumexp(q_logits, axis=-1, keepdims=True)
    p = jax.nn.softmax(p_logits, axis=-1)
    kl = jnp.sum(p * (p_logits - q_logits), axis=-1)
    return _sum_last_dims(kl, event_dims)


class Bernoulli:
    """Bernoulli with a defined mode (reference ``BernoulliSafeMode``,
    distribution.py:409-416)."""

    def __init__(self, logits: jax.Array, event_dims: int = 0):
        self.logits = logits
        self.event_dims = event_dims

    @property
    def probs(self) -> jax.Array:
        return jax.nn.sigmoid(self.logits)

    @property
    def mode(self) -> jax.Array:
        return (self.probs > 0.5).astype(self.logits.dtype)

    @property
    def mean(self) -> jax.Array:
        return self.probs

    def sample(self, key: jax.Array) -> jax.Array:
        return jax.random.bernoulli(key, self.probs).astype(self.logits.dtype)

    def log_prob(self, value: jax.Array) -> jax.Array:
        # -softplus(-l) for value 1, -softplus(l) for value 0 (numerically stable BCE)
        logits, value = _f32(self.logits), _f32(value)
        lp = -jax.nn.softplus(-logits) * value - jax.nn.softplus(logits) * (1 - value)
        return _sum_last_dims(lp, self.event_dims)


class SymlogDistribution:
    """Symlog-MSE pseudo-distribution for vector reconstruction
    (reference distribution.py:152-193)."""

    def __init__(self, mode: jax.Array, dims: int, dist: str = "mse", agg: str = "sum", tol: float = 1e-8):
        self._mode = mode
        self._dims = dims
        self._dist = dist
        self._agg = agg
        self._tol = tol

    @property
    def mode(self) -> jax.Array:
        return symexp(self._mode)

    @property
    def mean(self) -> jax.Array:
        return symexp(self._mode)

    def log_prob(self, value: jax.Array) -> jax.Array:
        assert self._mode.shape == value.shape, (self._mode.shape, value.shape)
        mode, value = _f32(self._mode), _f32(value)
        if self._dist == "mse":
            distance = (mode - symlog(value)) ** 2
        elif self._dist == "abs":
            distance = jnp.abs(mode - symlog(value))
        else:
            raise NotImplementedError(self._dist)
        distance = jnp.where(distance < self._tol, 0.0, distance)
        axes = tuple(range(-self._dims, 0))
        loss = jnp.mean(distance, axis=axes) if self._agg == "mean" else jnp.sum(distance, axis=axes)
        return -loss


class MSEDistribution:
    """Plain MSE pseudo-distribution (DV3 image decoder head,
    reference distribution.py:196-221)."""

    def __init__(self, mode: jax.Array, dims: int, agg: str = "sum"):
        self._mode = mode
        self._dims = dims
        self._agg = agg

    @property
    def mode(self) -> jax.Array:
        return self._mode

    @property
    def mean(self) -> jax.Array:
        return self._mode

    def log_prob(self, value: jax.Array) -> jax.Array:
        assert self._mode.shape == value.shape, (self._mode.shape, value.shape)
        mode, value = _f32(self._mode), _f32(value)
        distance = (mode - value) ** 2
        axes = tuple(range(-self._dims, 0))
        loss = jnp.mean(distance, axis=axes) if self._agg == "mean" else jnp.sum(distance, axis=axes)
        return -loss


class TwoHotEncodingDistribution:
    """255-bin two-hot symlog distribution over scalars (DV3 reward head and
    critic, reference distribution.py:224-278)."""

    def __init__(
        self,
        logits: jax.Array,
        dims: int = 0,
        low: int = -20,
        high: int = 20,
        transfwd: Callable[[jax.Array], jax.Array] = symlog,
        transbwd: Callable[[jax.Array], jax.Array] = symexp,
    ):
        # value heads read out through this: always fp32 (two-hot bucket
        # interpolation over 255 bins is exactly the kind of math bf16 ruins)
        self.logits = _f32(logits)
        self.dims = dims
        self.low = low
        self.high = high
        self.transfwd = transfwd
        self.transbwd = transbwd
        self.bins = jnp.linspace(low, high, logits.shape[-1], dtype=logits.dtype)

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def _reduce_axes(self) -> tuple:
        # reference dims=(-1,) for dims=1: reduce the bins axis (which replaces
        # the scalar (..., 1) event axis) plus any extra trailing event dims
        return tuple(range(-max(self.dims, 1), 0))

    @property
    def mean(self) -> jax.Array:
        return self.transbwd(jnp.sum(self.probs * self.bins, axis=self._reduce_axes, keepdims=True))

    @property
    def mode(self) -> jax.Array:
        return self.mean

    def log_prob(self, x: jax.Array) -> jax.Array:
        x = self.transfwd(x)
        nbins = self.bins.shape[0]
        below = jnp.sum((self.bins <= x).astype(jnp.int32), axis=-1, keepdims=True) - 1
        above = below + 1
        above = jnp.clip(above, 0, nbins - 1)
        below = jnp.clip(below, 0, nbins - 1)
        equal = below == above
        dist_to_below = jnp.where(equal, 1.0, jnp.abs(self.bins[below] - x))
        dist_to_above = jnp.where(equal, 1.0, jnp.abs(self.bins[above] - x))
        total = dist_to_below + dist_to_above
        weight_below = dist_to_above / total
        weight_above = dist_to_below / total
        target = (
            jax.nn.one_hot(below, nbins, dtype=self.logits.dtype) * weight_below[..., None]
            + jax.nn.one_hot(above, nbins, dtype=self.logits.dtype) * weight_above[..., None]
        )[..., 0, :]
        log_pred = self.logits - jax.nn.logsumexp(self.logits, axis=-1, keepdims=True)
        return jnp.sum(target * log_pred, axis=self._reduce_axes)


class MultiCategorical:
    """Product of independent categoricals (MultiDiscrete action spaces)."""

    def __init__(self, logits_list):
        self.dists = [OneHotCategorical(lg) for lg in logits_list]

    def sample(self, key: jax.Array):
        keys = jax.random.split(key, len(self.dists))
        return [d.sample(k) for d, k in zip(self.dists, keys)]

    def log_prob(self, values) -> jax.Array:
        return sum(d.log_prob(v) for d, v in zip(self.dists, values))

    def entropy(self) -> jax.Array:
        return sum(d.entropy() for d in self.dists)
