"""Pure JAX numerics shared across algorithms.

TPU-first re-design of the reference's scattered torch helpers:
- symlog/symexp/two-hot: /root/reference/sheeprl/utils/utils.py:148-207
- GAE:                    /root/reference/sheeprl/utils/utils.py:63-103
- lambda-values:          /root/reference/sheeprl/algos/dreamer_v3/utils.py:66-77

The reference computes GAE and lambda-returns with Python ``for`` loops over
time on the device; here both are ``jax.lax.scan`` bodies so they fuse into the
enclosing jitted training step (one XLA graph, no host round-trips).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def symlog(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1)


def safetanh(x: jax.Array, eps: float) -> jax.Array:
    lim = 1.0 - eps
    return jnp.clip(jnp.tanh(x), -lim, lim)


def safeatanh(y: jax.Array, eps: float) -> jax.Array:
    lim = 1.0 - eps
    return jnp.arctanh(jnp.clip(y, -lim, lim))


def two_hot_encoder(x: jax.Array, support_range: int = 300, num_buckets: Optional[int] = None) -> jax.Array:
    """Two-hot encode a scalar tensor of shape ``(..., 1)`` onto an odd-sized
    linear support ``[-support_range, support_range]``.

    Matches the semantics of reference utils/utils.py:157-188 (torch bucketize +
    scatter_add) without scatter: on TPU a one-hot matmul-friendly formulation
    vectorizes better than scatter_add.
    """
    if num_buckets is None:
        num_buckets = support_range * 2 + 1
    if num_buckets % 2 == 0:
        raise ValueError("support_size must be odd")
    x = jnp.clip(x, -support_range, support_range)
    buckets = jnp.linspace(-support_range, support_range, num_buckets, dtype=x.dtype)
    bucket_size = (buckets[1] - buckets[0]) if num_buckets > 1 else jnp.asarray(1.0, x.dtype)
    # right index: first bucket strictly greater (torch.bucketize default 'right=False'
    # returns the insertion point keeping sorted order, i.e. count of buckets < x,
    # with ties mapping to the left edge's index).
    right_idxs = jnp.searchsorted(buckets, x, side="left")
    left_idxs = jnp.clip(right_idxs - 1, 0, num_buckets - 1)
    right_idxs_c = jnp.clip(right_idxs, 0, num_buckets - 1)
    left_value = jnp.abs(buckets[right_idxs_c] - x) / bucket_size
    right_value = 1.0 - left_value
    left_oh = jax.nn.one_hot(left_idxs[..., 0], num_buckets, dtype=x.dtype)
    right_oh = jax.nn.one_hot(right_idxs[..., 0], num_buckets, dtype=x.dtype)
    return left_oh * left_value + right_oh * right_value


def two_hot_decoder(x: jax.Array, support_range: int) -> jax.Array:
    """Decode a two-hot vector back to a scalar (reference utils/utils.py:191-207)."""
    num_buckets = x.shape[-1]
    if num_buckets % 2 == 0:
        raise ValueError("support_size must be odd")
    support = jnp.linspace(-support_range, support_range, num_buckets, dtype=x.dtype)
    return jnp.sum(x * support, axis=-1, keepdims=True)


def uniform_mix(logits: jax.Array, unimix: float = 0.01) -> jax.Array:
    """Mix ``unimix`` uniform probability into categorical logits over the last
    axis (DreamerV3's 1% unimix, reference algos/dreamer_v3/agent.py:437-449)."""
    if unimix <= 0.0:
        return logits
    probs = jax.nn.softmax(logits, axis=-1)
    uniform = jnp.ones_like(probs) / probs.shape[-1]
    probs = (1.0 - unimix) * probs + unimix * uniform
    return jnp.log(probs)


def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    num_steps: int,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation over leading time axis ``[T, ...]``.

    Behaviorally equivalent to the reference's reversed Python loop
    (utils/utils.py:63-103) but expressed as a reverse ``lax.scan`` so it
    compiles into the training-step graph.
    """
    del num_steps  # shape-derived under jit; kept for API parity
    not_dones = 1.0 - dones.astype(values.dtype)
    rewards = rewards.astype(values.dtype)

    # At step t: delta_t = r_t + gamma * nonterminal_t * V_{t+1} - V_t where
    # nonterminal_t and V_{t+1} come from (not_dones[t], values[t+1]) except at
    # the last step which uses (not_dones[-1], next_value).
    next_values = jnp.concatenate([values[1:], next_value[None]], axis=0)
    next_nonterminal = jnp.concatenate([not_dones[:-1], not_dones[-1:]], axis=0)
    deltas = rewards + gamma * next_values * next_nonterminal - values

    def body(lastgaelam, inp):
        delta, nonterminal = inp
        adv = delta + gamma * gae_lambda * nonterminal * lastgaelam
        return adv, adv

    _, advantages = jax.lax.scan(body, jnp.zeros_like(deltas[0]), (deltas, next_nonterminal), reverse=True)
    returns = advantages + values
    return returns, advantages


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(lambda) returns for imagined trajectories ``[H, ...]``
    (reference algos/dreamer_v3/utils.py:66-77) as a reverse scan."""
    interm = rewards + continues * values * (1 - lmbda)

    def body(nxt, inp):
        interm_t, cont_t = inp
        val = interm_t + cont_t * lmbda * nxt
        return val, val

    _, lambda_values = jax.lax.scan(body, values[-1], (interm, continues), reverse=True)
    return lambda_values
