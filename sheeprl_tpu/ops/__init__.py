from sheeprl_tpu.ops.numerics import (
    gae,
    compute_lambda_values,
    safeatanh,
    safetanh,
    symexp,
    symlog,
    two_hot_decoder,
    two_hot_encoder,
    uniform_mix,
)

__all__ = [
    "gae",
    "compute_lambda_values",
    "safeatanh",
    "safetanh",
    "symexp",
    "symlog",
    "two_hot_decoder",
    "two_hot_encoder",
    "uniform_mix",
]
