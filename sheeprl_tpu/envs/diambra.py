"""DIAMBRA Arena adapter (behavioral equivalent of
`/root/reference/sheeprl/envs/diambra.py:22-145`).

DIAMBRA arcade envs return a Dict observation mixing Box frames with
Discrete/MultiDiscrete scalars; buffers store everything as arrays, so the
scalar sub-spaces are re-expressed as int32 Boxes and every observation value
is reshaped to its declared shape.  One player only; frame sizing is forced
through the engine (or the wrapper stack when `increase_performance=False`).
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Tuple, Union

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_DIAMBRA_AVAILABLE

if not _IS_DIAMBRA_AVAILABLE:
    raise ModuleNotFoundError("No module named 'diambra'")

import diambra.arena  # noqa: E402

_ACTION_SPACES = {"DISCRETE", "MULTI_DISCRETE"}
# engine settings / wrapper options the adapter owns and callers may not override
_RESERVED_SETTINGS = ("frame_shape", "n_players")
_RESERVED_WRAPPERS = ("frame_shape", "stack_frames", "dilation", "flatten")


def boxify_space(space: gym.Space) -> spaces.Box:
    """Express a Discrete/MultiDiscrete sub-space as an int32 Box (Box passes
    through) so replay buffers can store it as a dense array."""
    if isinstance(space, spaces.Box):
        return space
    if isinstance(space, spaces.Discrete):
        return spaces.Box(0, int(space.n) - 1, (1,), np.int32)
    if isinstance(space, spaces.MultiDiscrete):
        nvec = np.asarray(space.nvec)
        return spaces.Box(np.zeros_like(nvec), nvec - 1, (len(nvec),), np.int32)
    raise RuntimeError(f"Unsupported DIAMBRA observation sub-space: {type(space)}")


class DiambraWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(
        self,
        id: str,
        action_space: str = "DISCRETE",
        screen_size: Union[int, Tuple[int, int]] = 64,
        grayscale: bool = False,
        repeat_action: int = 1,
        rank: int = 0,
        diambra_settings: Optional[Dict[str, Any]] = None,
        diambra_wrappers: Optional[Dict[str, Any]] = None,
        render_mode: str = "rgb_array",
        log_level: int = 0,
        increase_performance: bool = True,
    ) -> None:
        if action_space not in _ACTION_SPACES:
            raise ValueError(f"'action_space' must be one of {sorted(_ACTION_SPACES)}, got {action_space}")
        if isinstance(screen_size, int):
            screen_size = (screen_size, screen_size)
        settings_in = dict(diambra_settings or {})
        wrappers_in = dict(diambra_wrappers or {})
        for k in _RESERVED_SETTINGS:
            if settings_in.pop(k, None) is not None:
                warnings.warn(f"The DIAMBRA {k} setting is managed by the wrapper and was ignored")
        for k in _RESERVED_WRAPPERS:
            if wrappers_in.pop(k, None) is not None:
                warnings.warn(f"The DIAMBRA {k} wrapper option is managed by the wrapper and was ignored")
        role = settings_in.pop("role", None)
        if role is not None and role not in {"P1", "P2"}:
            raise ValueError(f"'role' must be 'P1', 'P2' or None, got {role}")

        settings = diambra.arena.EnvironmentSettings(
            **settings_in,
            game_id=id,
            action_space=getattr(diambra.arena.SpaceTypes, action_space),
            n_players=1,
            role=getattr(diambra.arena.Roles, role) if role is not None else None,
            render_mode=render_mode,
        )
        if repeat_action > 1:
            # sticky actions need the engine to run at its base step ratio
            if getattr(settings, "step_ratio", 1) > 1:
                warnings.warn(f"step_ratio forced to 1 because repeat_action={repeat_action}")
            settings.step_ratio = 1
        wrappers = diambra.arena.WrappersSettings(**wrappers_in, flatten=True, repeat_action=repeat_action)
        frame_shape = tuple(screen_size) + (int(grayscale),)
        if increase_performance:
            settings.frame_shape = frame_shape  # resize inside the engine
        else:
            wrappers.frame_shape = frame_shape  # resize in python

        self._env = diambra.arena.make(
            id, settings, wrappers, rank=rank, render_mode=render_mode, log_level=log_level
        )
        self._discrete_actions = action_space == "DISCRETE"
        self.action_space = self._env.action_space
        self.observation_space = spaces.Dict(
            {k: boxify_space(v) for k, v in self._env.observation_space.spaces.items()}
        )
        self.render_mode = render_mode

    def _as_arrays(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            k: np.asarray(v).reshape(self.observation_space[k].shape) for k, v in obs.items()
        }

    def step(self, action: Any) -> Tuple[Dict[str, np.ndarray], float, bool, bool, Dict[str, Any]]:
        if self._discrete_actions and isinstance(action, np.ndarray):
            action = int(action.squeeze())
        obs, reward, terminated, truncated, info = self._env.step(action)
        info["env_domain"] = "DIAMBRA"
        # a finished game ends the episode even when the round continues
        terminated = terminated or bool(info.get("env_done", False))
        return self._as_arrays(obs), float(reward), terminated, truncated, info

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        obs, info = self._env.reset(seed=seed, options=options)
        info["env_domain"] = "DIAMBRA"
        return self._as_arrays(obs), info

    def render(self) -> Optional[np.ndarray]:
        return self._env.render()

    def close(self) -> None:
        self._env.close()
