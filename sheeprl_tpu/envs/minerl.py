"""MineRL 0.4.4 adapter (behavioral equivalent of
`/root/reference/sheeprl/envs/minerl.py:48-322`).

Flattens MineRL's dict action space into one Discrete menu (one entry per
binary command, camera quadrant, and enum value), exposes a Dict observation
with the POV frame (CHW), life stats, dense inventory vectors and optionally
compass/equipment, and applies the shared sticky-attack/jump + pitch-clamp
state machines from `sheeprl_tpu.envs._minecraft`.

Tasks are the custom specs in `sheeprl_tpu.envs.minerl_envs` (navigate /
obtain-diamond / obtain-iron-pickaxe), selected by id.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.envs._minecraft import PitchTracker, StickyActions, count_items
from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("No module named 'minerl'")

import minerl.herobraine.hero.spaces as minerl_spaces  # noqa: E402
from minerl.herobraine.hero import mc  # noqa: E402

from sheeprl_tpu.envs.minerl_envs.specs import (  # noqa: E402
    CustomNavigate,
    CustomObtainDiamond,
    CustomObtainIronPickaxe,
)

TASKS = {
    "custom_navigate": CustomNavigate,
    "custom_obtain_diamond": CustomObtainDiamond,
    "custom_obtain_iron_pickaxe": CustomObtainIronPickaxe,
}

N_ALL_ITEMS = len(mc.ALL_ITEMS)
ITEM_NAME_TO_ID = {name: i for i, name in enumerate(mc.ALL_ITEMS)}
CAMERA_DELTAS = (
    np.array([-15.0, 0.0]),  # pitch down
    np.array([15.0, 0.0]),  # pitch up
    np.array([0.0, -15.0]),  # yaw left
    np.array([0.0, 15.0]),  # yaw right
)
_MOVEMENT_COMBOS = {"jump", "sneak", "sprint"}  # these also press forward


def _noop_action(action_space) -> Dict[str, Any]:
    """The all-zeros / all-'none' MineRL action dict."""
    noop: Dict[str, Any] = {}
    for name, space in action_space.spaces.items():
        if isinstance(space, minerl_spaces.Enum):
            noop[name] = "none"
        elif name == "camera":
            noop[name] = (0.0, 0.0)
        else:
            noop[name] = 0
    return noop


def build_action_menu(action_space) -> List[Dict[str, Any]]:
    """Enumerate the discrete action menu: entry 0 is no-op, then one entry
    per binary command (jump/sneak/sprint also press forward), four camera
    quadrant moves, and one entry per non-'none' enum value
    (reference minerl.py:117-138)."""
    menu: List[Dict[str, Any]] = [{}]
    for name, space in action_space.spaces.items():
        if isinstance(space, minerl_spaces.Enum):
            for value in sorted(set(space.values.tolist()) - {"none"}):
                menu.append({name: value})
        elif name == "camera":
            menu.extend({name: delta} for delta in CAMERA_DELTAS)
        else:
            entry: Dict[str, Any] = {name: 1}
            if name in _MOVEMENT_COMBOS:
                entry["forward"] = 1
            menu.append(entry)
    return menu


class MineRLWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: int = 30,
        sticky_jump: int = 10,
        break_speed_multiplier: int = 100,
        multihot_inventory: bool = True,
        **kwargs: Any,
    ):
        if "navigate" not in id.lower():
            kwargs.pop("extreme", None)
        spec = TASKS[id.lower()](break_speed=break_speed_multiplier, **kwargs)
        self._env = spec.make()
        self._sticky = StickyActions(
            attack_for=0 if break_speed_multiplier > 1 else sticky_attack, jump_for=sticky_jump
        )
        self._pitch = PitchTracker(limits=(float(pitch_limits[0]), float(pitch_limits[1])))
        self._menu = build_action_menu(self._env.action_space)
        self._noop = _noop_action(self._env.action_space)
        self.action_space = spaces.Discrete(len(self._menu))

        # inventory vocabulary: every Minecraft item (multihot) or just the
        # task's obtainable items
        if multihot_inventory:
            self._item_to_id = ITEM_NAME_TO_ID
            self._n_items = N_ALL_ITEMS
        else:
            task_items = list(self._env.observation_space["inventory"].spaces.keys())
            self._item_to_id = {name: i for i, name in enumerate(task_items)}
            self._n_items = len(task_items)
        self._max_inventory = np.zeros(self._n_items, np.float32)

        obs_spaces: Dict[str, spaces.Space] = {
            "rgb": spaces.Box(0, 255, (3, height, width), np.uint8),
            "life_stats": spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
            "inventory": spaces.Box(0.0, np.inf, (self._n_items,), np.float32),
            "max_inventory": spaces.Box(0.0, np.inf, (self._n_items,), np.float32),
        }
        if "compass" in self._env.observation_space.spaces:
            obs_spaces["compass"] = spaces.Box(-180.0, 180.0, (1,), np.float32)
        self._has_equipment = "equipped_items" in self._env.observation_space.spaces
        if self._has_equipment:
            if multihot_inventory:
                self._equip_to_id = ITEM_NAME_TO_ID
                self._n_equip = N_ALL_ITEMS
            else:
                equip_values = self._env.observation_space["equipped_items"]["mainhand"]["type"].values.tolist()
                self._equip_to_id = {name: i for i, name in enumerate(equip_values)}
                self._n_equip = len(equip_values)
            obs_spaces["equipment"] = spaces.Box(0.0, 1.0, (self._n_equip,), np.int32)
        self.observation_space = spaces.Dict(obs_spaces)
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    # ---- conversions ------------------------------------------------------------

    def _convert_action(self, action) -> Dict[str, Any]:
        cmd = dict(self._noop)
        cmd.update(self._menu[int(np.asarray(action).item())])
        attack, jump = self._sticky.update(attack=bool(cmd["attack"]), jump=bool(cmd["jump"]))
        cmd["attack"], cmd["jump"] = int(attack), int(jump)
        if jump:
            cmd["forward"] = 1  # sticky jump keeps moving forward
        d_pitch, d_yaw = self._pitch.apply(*np.asarray(cmd["camera"], np.float64))
        cmd["camera"] = np.array([d_pitch, d_yaw])
        return cmd

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        inventory = count_items(
            obs["inventory"].keys(), obs["inventory"].values(), self._item_to_id, self._n_items
        )
        self._max_inventory = np.maximum(inventory, self._max_inventory)
        out: Dict[str, np.ndarray] = {
            "rgb": obs["pov"].copy().transpose(2, 0, 1),
            "life_stats": np.array(
                [obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["air"]],
                np.float32,
            ),
            "inventory": inventory,
            "max_inventory": self._max_inventory.copy(),
        }
        if "compass" in self.observation_space.spaces:
            out["compass"] = np.asarray(obs["compass"]["angle"], np.float32).reshape(-1)
        if self._has_equipment:
            onehot = np.zeros(self._n_equip, np.int32)
            equipped = str(obs["equipped_items"]["mainhand"]["type"])
            onehot[self._equip_to_id.get(equipped, self._equip_to_id["air"])] = 1
            out["equipment"] = onehot
        return out

    # ---- gym API ----------------------------------------------------------------

    def step(self, action) -> Tuple[Dict[str, np.ndarray], float, bool, bool, Dict[str, Any]]:
        obs, reward, done, info = self._env.step(self._convert_action(action))
        return self._convert_obs(obs), float(reward), bool(done), False, info

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        obs = self._env.reset()
        self._sticky.reset()
        self._pitch.reset()
        self._max_inventory = np.zeros(self._n_items, np.float32)
        return self._convert_obs(obs), {}

    def render(self) -> Optional[np.ndarray]:
        return self._env.render(self.render_mode)

    def close(self) -> None:
        self._env.close()
