"""Environment wrappers.

Behavioral equivalents of /root/reference/sheeprl/envs/wrappers.py:13-342,
written against gymnasium >= 1.0 (the reference targets 0.29; several gym
wrappers it leans on were renamed/removed, so the dict-obs normalization and
pixel pipeline are implemented natively here and in ``env.py``).
"""

from __future__ import annotations

import copy
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import gymnasium as gym
import numpy as np


class MaskVelocityWrapper(gym.ObservationWrapper):
    """Mask velocity terms in classic-control observations to make the MDP
    partially observable (reference wrappers.py:13-45)."""

    velocity_indices = {
        "CartPole-v0": np.array([1, 3]),
        "CartPole-v1": np.array([1, 3]),
        "MountainCar-v0": np.array([1]),
        "MountainCarContinuous-v0": np.array([1]),
        "Pendulum-v1": np.array([2]),
        "LunarLander-v2": np.array([2, 3, 5]),
        "LunarLanderContinuous-v2": np.array([2, 3, 5]),
        "LunarLander-v3": np.array([2, 3, 5]),
    }

    def __init__(self, env: gym.Env):
        super().__init__(env)
        assert env.unwrapped.spec is not None
        env_id: str = env.unwrapped.spec.id
        self.mask = np.ones_like(env.observation_space.sample())
        try:
            self.mask[self.velocity_indices[env_id]] = 0.0
        except KeyError as e:
            raise NotImplementedError(f"Velocity masking not implemented for {env_id}") from e

    def observation(self, observation: np.ndarray) -> np.ndarray:
        return observation * self.mask


class ActionRepeat(gym.Wrapper):
    """Repeat each action ``amount`` times, summing rewards
    (reference wrappers.py:48-73)."""

    def __init__(self, env: gym.Env, amount: int = 1):
        super().__init__(env)
        if amount <= 0:
            raise ValueError("`amount` should be a positive integer")
        self._amount = amount

    @property
    def action_repeat(self) -> int:
        return self._amount

    def step(self, action):
        done = truncated = False
        total_reward, current_step = 0.0, 0
        obs, info = None, {}
        while current_step < self._amount and not (done or truncated):
            obs, reward, done, truncated, info = self.env.step(action)
            total_reward += reward
            current_step += 1
        return obs, total_reward, done, truncated, info


class RestartOnException(gym.Wrapper):
    """Env-level fault tolerance: recreate a crashed env and resignal
    (reference wrappers.py:74-125, used by Dreamer's long-running sims)."""

    def __init__(
        self,
        env_fn: Callable[..., gym.Env],
        exceptions: Union[type, Tuple[type, ...]] = (Exception,),
        window: float = 300,
        maxfails: int = 2,
        wait: float = 20,
    ):
        if not isinstance(exceptions, (tuple, list)):
            exceptions = (exceptions,)
        self._env_fn = env_fn
        self._exceptions = tuple(exceptions)
        self._window = window
        self._maxfails = maxfails
        self._wait = wait
        self._last = time.time()
        self._fails = 0
        super().__init__(self._env_fn())

    def _register_failure(self, origin: str, err: Exception) -> None:
        if time.time() > self._last + self._window:
            self._last = time.time()
            self._fails = 1
        else:
            self._fails += 1
        if self._fails > self._maxfails:
            raise RuntimeError(f"The env crashed too many times: {self._fails}") from err
        gym.logger.warn(f"{origin} - Restarting env after crash with {type(err).__name__}: {err}")
        time.sleep(self._wait)

    def step(self, action):
        try:
            return self.env.step(action)
        except self._exceptions as e:
            self._register_failure("STEP", e)
            self.env = self._env_fn()
            new_obs, info = self.env.reset()
            info.update({"restart_on_exception": True})
            return new_obs, 0.0, False, False, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        try:
            return self.env.reset(seed=seed, options=options)
        except self._exceptions as e:
            self._register_failure("RESET", e)
            self.env = self._env_fn()
            new_obs, info = self.env.reset(seed=seed, options=options)
            info.update({"restart_on_exception": True})
            return new_obs, info


class FrameStack(gym.Wrapper):
    """Expose a rolling window over each pixel key: the observation becomes
    ``[num_stack, ...]`` holding every ``dilation``-th of the most recent
    ``num_stack * dilation`` frames, newest last (behavioral parity with
    reference wrappers.py:128-183).

    Each tracked key owns a preallocated ring buffer; a step costs one copy of
    the newest frame plus one modular gather — no per-step deque churn or
    re-stacking of the whole window.
    """

    def __init__(self, env: gym.Env, num_stack: int, cnn_keys: Sequence[str], dilation: int = 1) -> None:
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"num_stack must be a positive integer, got {num_stack}")
        if dilation <= 0:
            raise ValueError(f"dilation must be a positive integer, got {dilation}")
        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise RuntimeError(
                f"FrameStack needs a gym.spaces.Dict observation space, got {type(env.observation_space)}"
            )
        self._num_stack = num_stack
        self._dilation = dilation
        self._window = num_stack * dilation
        wanted = set(cnn_keys or ())
        tracked = [
            k for k, space in env.observation_space.spaces.items() if k in wanted and len(space.shape) == 3
        ]
        if not tracked:
            raise RuntimeError(f"None of the cnn keys {sorted(wanted)} name a 3-D observation to stack")
        self.observation_space = copy.deepcopy(env.observation_space)
        self._ring: Dict[str, np.ndarray] = {}
        for k in tracked:
            space = env.observation_space[k]
            self.observation_space[k] = gym.spaces.Box(
                np.broadcast_to(space.low, (num_stack, *space.shape)).copy(),
                np.broadcast_to(space.high, (num_stack, *space.shape)).copy(),
                (num_stack, *space.shape),
                space.dtype,
            )
            self._ring[k] = np.zeros((self._window, *space.shape), dtype=space.dtype)
        self._frames_seen = 0

    def _stacked(self, key: str) -> np.ndarray:
        # ages (newest = 0) of the exposed frames are 0, d, ..., (S-1)*d;
        # the frame with age a lives in slot (frames_seen - 1 - a) % window
        newest = self._frames_seen - 1
        slots = (newest - self._dilation * np.arange(self._num_stack - 1, -1, -1)) % self._window
        return self._ring[key][slots]

    def step(self, action):
        obs, reward, done, truncated, infos = self.env.step(action)
        slot = self._frames_seen % self._window
        self._frames_seen += 1
        # DIAMBRA fight boundaries (round/stage/game done without the episode
        # ending) restart play from a fresh scene: reflood the window with the
        # new scene's first frame so the stack never straddles the boundary
        # (reference wrappers.py:160-171).
        reflood = (
            infos.get("env_domain") == "DIAMBRA"
            and {"round_done", "stage_done", "game_done"} <= infos.keys()
            and (infos["round_done"] or infos["stage_done"] or infos["game_done"])
            and not (done or truncated)
        )
        for k, ring in self._ring.items():
            if reflood:
                ring[:] = obs[k][None]
            else:
                ring[slot] = obs[k]
            obs[k] = self._stacked(k)
        return obs, reward, done, truncated, infos

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None, **kwargs):
        obs, infos = self.env.reset(seed=seed, **kwargs)
        self._frames_seen = self._window
        for k, ring in self._ring.items():
            ring[:] = obs[k][None]
            obs[k] = self._stacked(k)
        return obs, infos


class RewardAsObservationWrapper(gym.Wrapper):
    """Expose the last reward under a ``reward`` observation key
    (reference wrappers.py:186-240)."""

    def __init__(self, env: gym.Env) -> None:
        super().__init__(env)
        reward_range = getattr(self.env, "reward_range", None) or (-np.inf, np.inf)
        reward_space = gym.spaces.Box(*reward_range, (1,), np.float32)
        if isinstance(self.env.observation_space, gym.spaces.Dict):
            self.observation_space = gym.spaces.Dict(
                {"reward": reward_space, **dict(self.env.observation_space.items())}
            )
        else:
            self.observation_space = gym.spaces.Dict({"obs": self.env.observation_space, "reward": reward_space})

    def _convert_obs(self, obs: Any, reward: Union[float, np.ndarray]) -> Dict[str, Any]:
        reward_obs = np.asarray(reward, dtype=np.float32).reshape(-1)
        if isinstance(obs, dict):
            obs["reward"] = reward_obs
        else:
            obs = {"obs": obs, "reward": reward_obs}
        return obs

    def step(self, action):
        obs, reward, done, truncated, infos = self.env.step(action)
        return self._convert_obs(obs, copy.deepcopy(reward)), reward, done, truncated, infos

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, infos = self.env.reset(seed=seed, options=options)
        return self._convert_obs(obs, 0), infos


class GrayscaleRenderWrapper(gym.Wrapper):
    """Renders grayscale frames as 3-channel so video encoders accept them
    (reference wrappers.py:243-255)."""

    def render(self):
        frame = super().render()
        if isinstance(frame, np.ndarray):
            if frame.ndim == 2:
                frame = frame[..., np.newaxis]
            if frame.ndim == 3 and frame.shape[-1] == 1:
                frame = frame.repeat(3, axis=-1)
        return frame


class ActionsAsObservationWrapper(gym.Wrapper):
    """Stack the last actions (one-hot for discrete spaces) into an
    ``action_stack`` observation key (reference wrappers.py:258-342)."""

    def __init__(self, env: gym.Env, num_stack: int, noop: float | int | List[int], dilation: int = 1):
        super().__init__(env)
        if num_stack < 1:
            raise ValueError(f"The number of stacked actions must be greater or equal than 1, got: {num_stack}")
        if dilation < 1:
            raise ValueError(f"The actions stack dilation argument must be greater than zero, got: {dilation}")
        if not isinstance(noop, (int, float, list)):
            raise ValueError(f"The noop action must be an integer or float or list, got: {noop} ({type(noop)})")
        self._num_stack = num_stack
        self._dilation = dilation
        self._actions = deque(maxlen=num_stack * dilation)
        self._is_continuous = isinstance(self.env.action_space, gym.spaces.Box)
        self._is_multidiscrete = isinstance(self.env.action_space, gym.spaces.MultiDiscrete)
        self.observation_space = copy.deepcopy(self.env.observation_space)
        if self._is_continuous:
            self._action_shape = self.env.action_space.shape[0]
            low = np.resize(self.env.action_space.low, self._action_shape * num_stack)
            high = np.resize(self.env.action_space.high, self._action_shape * num_stack)
        elif self._is_multidiscrete:
            low, high = 0, 1
            self._action_shape = int(sum(self.env.action_space.nvec))
        else:
            low, high = 0, 1
            self._action_shape = int(self.env.action_space.n)
        self.observation_space["action_stack"] = gym.spaces.Box(
            low=low, high=high, shape=(self._action_shape * num_stack,), dtype=np.float32
        )
        if self._is_continuous:
            if isinstance(noop, list):
                raise ValueError(f"The noop actions must be a float for continuous action spaces, got: {noop}")
            self.noop = np.full((self._action_shape,), noop, dtype=np.float32)
        elif self._is_multidiscrete:
            if not isinstance(noop, list):
                raise ValueError(f"The noop actions must be a list for multi-discrete action spaces, got: {noop}")
            if len(self.env.action_space.nvec) != len(noop):
                raise RuntimeError(
                    "The number of noop actions must equal the number of actions of the environment. "
                    f"Got {self.env.action_space.nvec} and noop={noop}"
                )
            noops = []
            for noop_i, n in zip(noop, self.env.action_space.nvec):
                oh = np.zeros((int(n),), dtype=np.float32)
                oh[noop_i] = 1.0
                noops.append(oh)
            self.noop = np.concatenate(noops, axis=-1)
        else:
            if isinstance(noop, (list, float)):
                raise ValueError(f"The noop actions must be an integer for discrete action spaces, got: {noop}")
            self.noop = np.zeros((self._action_shape,), dtype=np.float32)
            self.noop[noop] = 1.0

    def _one_hot(self, action) -> np.ndarray:
        if self._is_continuous:
            return np.asarray(action, dtype=np.float32).reshape(-1)
        if self._is_multidiscrete:
            parts = []
            for act, n in zip(action, self.env.action_space.nvec):
                oh = np.zeros((int(n),), dtype=np.float32)
                oh[int(act)] = 1.0
                parts.append(oh)
            return np.concatenate(parts, axis=-1)
        oh = np.zeros((self._action_shape,), dtype=np.float32)
        oh[int(np.asarray(action).item())] = 1.0
        return oh

    def _get_actions_stack(self) -> np.ndarray:
        actions_stack = list(self._actions)[self._dilation - 1 :: self._dilation]
        return np.concatenate(actions_stack, axis=-1).astype(np.float32)

    def step(self, action):
        self._actions.append(self._one_hot(action))
        obs, reward, done, truncated, info = self.env.step(action)
        obs["action_stack"] = self._get_actions_stack()
        return obs, reward, done, truncated, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, info = self.env.reset(seed=seed, options=options)
        self._actions.clear()
        for _ in range(self._num_stack * self._dilation):
            self._actions.append(self.noop)
        obs["action_stack"] = self._get_actions_stack()
        return obs, info
