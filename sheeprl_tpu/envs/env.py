"""Environment factory: dict-obs normalization + wrapper-stack assembly.

Behavioral equivalent of /root/reference/sheeprl/utils/env.py:26-249, written
against gymnasium >= 1.0.  Every env is normalized to a ``gym.spaces.Dict``
observation space; pixel keys go through the cv2 pipeline (resize, optional
grayscale, CHW uint8) so buffers store the same layout the reference does.
Vectorization is gymnasium Sync/AsyncVectorEnv picked by ``cfg.env.sync_env``
— on a TPU-VM the async workers are the host-CPU actor parallelism.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, Optional

import cv2
import gymnasium as gym
import numpy as np

from sheeprl_tpu.config import instantiate
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FrameStack,
    GrayscaleRenderWrapper,
    MaskVelocityWrapper,
    RewardAsObservationWrapper,
)


class _DictObs(gym.ObservationWrapper):
    """Wrap a single Box observation under a named key."""

    def __init__(self, env: gym.Env, key: str):
        super().__init__(env)
        self._key = key
        self.observation_space = gym.spaces.Dict({key: env.observation_space})

    def observation(self, observation):
        return {self._key: observation}


class _RenderPixels(gym.Wrapper):
    """Add a pixel key from env.render() for vector-obs envs when the config
    asks for a cnn encoder (replaces gym 0.29 PixelObservationWrapper)."""

    def __init__(self, env: gym.Env, pixel_key: str, state_key: Optional[str] = None):
        super().__init__(env)
        self._pixel_key = pixel_key
        self._state_key = state_key
        env.reset()  # gymnasium's OrderEnforcer forbids render() before the first reset
        frame = env.render()
        if frame is None:
            raise RuntimeError(
                f"Cannot build pixel observations for '{env}' because render() returned None; "
                "construct the env with render_mode='rgb_array'"
            )
        frame = np.asarray(frame)
        spaces = {pixel_key: gym.spaces.Box(0, 255, frame.shape, np.uint8)}
        if state_key is not None:
            spaces[state_key] = env.observation_space
        self.observation_space = gym.spaces.Dict(spaces)

    def _obs(self, observation):
        out = {self._pixel_key: np.asarray(self.env.render(), dtype=np.uint8)}
        if self._state_key is not None:
            out[self._state_key] = observation
        return out

    def step(self, action):
        obs, reward, done, truncated, info = self.env.step(action)
        return self._obs(obs), reward, done, truncated, info

    def reset(self, *, seed=None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        return self._obs(obs), info


class _PixelPipeline(gym.ObservationWrapper):
    """cv2 resize + optional grayscale + CHW uint8 for each cnn key
    (reference utils/env.py:161-203)."""

    def __init__(self, env: gym.Env, cnn_keys, screen_size: int, grayscale: bool):
        super().__init__(env)
        self._cnn_keys = cnn_keys
        self._screen_size = screen_size
        self._grayscale = grayscale
        self.observation_space = gym.spaces.Dict(dict(env.observation_space.spaces))
        for k in cnn_keys:
            self.observation_space[k] = gym.spaces.Box(
                0, 255, (1 if grayscale else 3, screen_size, screen_size), np.uint8
            )

    def observation(self, obs):
        for k in self._cnn_keys:
            current = np.asarray(obs[k])
            shape = current.shape
            is_3d = len(shape) == 3
            is_grayscale = not is_3d or shape[0] == 1 or shape[-1] == 1
            channel_first = not is_3d or shape[0] in (1, 3)
            if not is_3d:
                current = np.expand_dims(current, axis=0)
            if channel_first:
                current = np.transpose(current, (1, 2, 0))
            if current.shape[:-1] != (self._screen_size, self._screen_size):
                current = cv2.resize(
                    current, (self._screen_size, self._screen_size), interpolation=cv2.INTER_AREA
                )
            if self._grayscale and not is_grayscale:
                current = cv2.cvtColor(current, cv2.COLOR_RGB2GRAY)
            if current.ndim == 2:
                current = np.expand_dims(current, axis=-1)
                if not self._grayscale:
                    current = np.repeat(current, 3, axis=-1)
            obs[k] = np.ascontiguousarray(current.transpose(2, 0, 1), dtype=np.uint8)
        return obs


def make_env(
    cfg: Dict[str, Any],
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
    vector_env_idx: int = 0,
) -> Callable[[], gym.Env]:
    """Build a thunk creating one fully-wrapped env (reference utils/env.py:26-237)."""

    def thunk() -> gym.Env:
        wrapper_cfg = dict(cfg.env.wrapper)
        instantiate_kwargs = {}
        if "seed" in wrapper_cfg:
            instantiate_kwargs["seed"] = seed
        if "rank" in wrapper_cfg:
            instantiate_kwargs["rank"] = rank + vector_env_idx
        env = instantiate(wrapper_cfg, **instantiate_kwargs)

        if cfg.env.action_repeat > 1:
            env = ActionRepeat(env, cfg.env.action_repeat)
        if cfg.env.get("mask_velocities", False):
            env = MaskVelocityWrapper(env)

        cnn_encoder_keys = cfg.algo.cnn_keys.encoder
        mlp_encoder_keys = cfg.algo.mlp_keys.encoder
        if not (
            isinstance(mlp_encoder_keys, list)
            and isinstance(cnn_encoder_keys, list)
            and len(cnn_encoder_keys + mlp_encoder_keys) > 0
        ):
            raise ValueError(
                "`algo.cnn_keys.encoder` and `algo.mlp_keys.encoder` must be lists of strings with at "
                f"least one total key, got: cnn={cnn_encoder_keys} mlp={mlp_encoder_keys}"
            )

        # Normalize the observation space to a Dict
        if isinstance(env.observation_space, gym.spaces.Box) and len(env.observation_space.shape) < 2:
            if len(cnn_encoder_keys) > 0:
                if len(cnn_encoder_keys) > 1:
                    warnings.warn(f"Only the first cnn key is kept for {cfg.env.id}: {cnn_encoder_keys[0]}")
                state_key = mlp_encoder_keys[0] if len(mlp_encoder_keys) > 0 else None
                env = _RenderPixels(env, pixel_key=cnn_encoder_keys[0], state_key=state_key)
            else:
                if len(mlp_encoder_keys) > 1:
                    warnings.warn(f"Only the first mlp key is kept for {cfg.env.id}: {mlp_encoder_keys[0]}")
                env = _DictObs(env, mlp_encoder_keys[0])
        elif isinstance(env.observation_space, gym.spaces.Box) and 2 <= len(env.observation_space.shape) <= 3:
            if len(cnn_encoder_keys) == 0:
                raise ValueError(
                    "You have selected a pixel observation but no cnn key has been specified. "
                    "Set `algo.cnn_keys.encoder=[your_cnn_key]`"
                )
            if len(cnn_encoder_keys) > 1:
                warnings.warn(f"Only the first cnn key is kept for {cfg.env.id}: {cnn_encoder_keys[0]}")
            env = _DictObs(env, cnn_encoder_keys[0])

        requested = set(mlp_encoder_keys + cnn_encoder_keys)
        if len(requested.intersection(env.observation_space.keys())) == 0:
            raise ValueError(
                f"The user-specified keys {sorted(requested)} are not a subset of the environment "
                f"observation keys {sorted(env.observation_space.keys())}. Check your config."
            )

        env_cnn_keys = set(
            k for k in env.observation_space.spaces.keys() if len(env.observation_space[k].shape) in (2, 3)
        )
        cnn_keys = sorted(env_cnn_keys.intersection(cnn_encoder_keys))
        if cnn_keys:
            env = _PixelPipeline(env, cnn_keys, cfg.env.screen_size, cfg.env.grayscale)
            if cfg.env.frame_stack > 1:
                if cfg.env.frame_stack_dilation <= 0:
                    raise ValueError(
                        f"The frame stack dilation argument must be greater than zero, "
                        f"got: {cfg.env.frame_stack_dilation}"
                    )
                env = FrameStack(env, cfg.env.frame_stack, cnn_keys, cfg.env.frame_stack_dilation)

        if cfg.env.actions_as_observation.num_stack > 0:
            env = ActionsAsObservationWrapper(env, **cfg.env.actions_as_observation)
        if cfg.env.reward_as_observation:
            env = RewardAsObservationWrapper(env)

        env.action_space.seed(seed)
        env.observation_space.seed(seed)
        if cfg.env.max_episode_steps and cfg.env.max_episode_steps > 0:
            env = gym.wrappers.TimeLimit(env, max_episode_steps=cfg.env.max_episode_steps)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if cfg.env.capture_video and rank == 0 and vector_env_idx == 0 and run_name is not None:
            if cfg.env.grayscale:
                env = GrayscaleRenderWrapper(env)
            try:
                env = gym.wrappers.RecordVideo(
                    env,
                    os.path.join(run_name, prefix + "_videos" if prefix else "videos"),
                    disable_logger=True,
                )
            except Exception as err:  # moviepy may be missing in minimal images
                warnings.warn(f"Video capture disabled: {err}")
        return env

    return thunk


def make_env_fns(cfg, log_dir: Optional[str] = None, prefix: str = "train", restartable: bool = True):
    """Every training loop's env thunks, built in one place.

    Each thunk is wrapped in :class:`~sheeprl_tpu.envs.wrappers.RestartOnException`
    (the Dreamer loops always did this; the on-policy loops used to pass bare
    ``make_env`` fns, so one transient env crash killed the whole run).  A
    restarted env surfaces ``info["restart_on_exception"]`` — loops that track
    episode continuity (Dreamer) patch their buffers from it, everyone else
    just keeps training through the discontinuity.  Construction-time errors
    (bad config, missing sim) still raise immediately.
    """
    from functools import partial

    from sheeprl_tpu.envs.wrappers import RestartOnException

    fns = []
    for i in range(cfg.env.num_envs):
        thunk = make_env(cfg, cfg.seed + i, 0, log_dir, prefix, vector_env_idx=i)
        fns.append(partial(RestartOnException, thunk) if restartable else thunk)
    return fns


def resolve_executor(cfg) -> str:
    """Map ``cfg.env.executor`` (new knob) + ``cfg.env.sync_env`` (legacy) to
    an executor name: ``sync`` | ``async`` | ``shared_memory``.  Unset/``auto``
    honors ``sync_env`` verbatim, so existing configs behave identically."""
    executor = cfg.env.get("executor", None)
    if executor in (None, "", "auto"):
        return "sync" if cfg.env.sync_env else "async"
    executor = str(executor)
    from sheeprl_tpu.envs.pipeline import EXECUTORS

    if executor not in EXECUTORS:
        raise ValueError(f"env.executor must be one of {EXECUTORS} (or null/auto), got: {executor}")
    return executor


def pipelined_vector_env(cfg, env_fns):
    """Build the configured executor and wrap it in
    :class:`~sheeprl_tpu.envs.pipeline.PipelinedVectorEnv` so the hot loops
    can ``step_async``/``step_wait``.  ``step()`` still works for loops that
    have not been rewired."""
    if ((cfg.get("algo") or {}).get("offline") or {}).get("enabled"):
        # the enforced invariant behind the offline-mode acceptance drill:
        # env-free training must never spawn env workers — an online loop
        # reached by mistake fails loudly here instead of silently
        # collecting fresh experience (howto/offline_rl.md)
        raise RuntimeError(
            "algo.offline.enabled=true is an env-free training mode: environments must not "
            "be constructed (the offline entrypoint drives the train step from the dataset "
            "loader; see sheeprl_tpu/offline/train.py)"
        )
    from sheeprl_tpu.envs.pipeline import PipelinedVectorEnv

    executor = resolve_executor(cfg)
    if executor == "shared_memory":
        from sheeprl_tpu.envs.executor import SharedMemoryVectorEnv

        envs = SharedMemoryVectorEnv(
            env_fns,
            context="spawn",
            envs_per_worker=cfg.env.get("envs_per_worker", None),
        )
    else:
        envs = vectorized_env(env_fns, sync=executor == "sync")
    return PipelinedVectorEnv(envs)


def vectorized_env(env_fns, sync: bool = True) -> gym.vector.VectorEnv:
    """SyncVectorEnv or AsyncVectorEnv (one OS subprocess per env — the
    reference's actor parallelism, utils/env.py + e.g. algos/ppo/ppo.py:137).

    ``SAME_STEP`` autoreset reproduces the gym-0.29 semantics the reference
    was written against: on done the returned obs is the new episode's reset
    obs and the terminal obs rides in ``infos["final_obs"]`` (needed for
    truncation bootstrapping, reference algos/ppo/ppo.py:287-306).
    """
    mode = gym.vector.AutoresetMode.SAME_STEP
    if sync:
        return gym.vector.SyncVectorEnv(env_fns, autoreset_mode=mode)
    # spawn (not fork), even for a single env: env workers get a pristine
    # runtime, which GL renderers require — creating a dm_control EGL
    # context inside the jax/XLA host process segfaults (mesa EGL is not
    # compatible with the loaded runtime state), and forking a threaded jax
    # process is equally unsafe.  A lone async env is the supported way to
    # run pixel DMC/mario alongside the device runtime.  Honoring sync_env
    # verbatim (no single-env fast path) also matches the reference
    # (sheeprl/algos/ppo/ppo.py:137 picks the class purely on cfg.env.sync_env);
    # gymnasium's shared-memory obs transport keeps the per-step IPC cost
    # far below a policy step.
    return gym.vector.AsyncVectorEnv(env_fns, autoreset_mode=mode, context="spawn")


def get_dummy_env(id: str, sleep_ms: float = 0.0) -> gym.Env:
    """Dummy env selector (reference utils/env.py:240-249).  ``sleep_ms``
    (settable as ``env.wrapper.sleep_ms``) gives each step a deterministic
    wall-clock latency so pipelining overlap is testable without a real
    slow simulator."""
    if "continuous" in id:
        from sheeprl_tpu.envs.dummy import ContinuousDummyEnv

        return ContinuousDummyEnv(sleep_ms=sleep_ms)
    elif "multidiscrete" in id:
        from sheeprl_tpu.envs.dummy import MultiDiscreteDummyEnv

        return MultiDiscreteDummyEnv(sleep_ms=sleep_ms)
    elif "discrete" in id:
        from sheeprl_tpu.envs.dummy import DiscreteDummyEnv

        return DiscreteDummyEnv(sleep_ms=sleep_ms)
    raise ValueError(f"Unrecognized dummy environment: {id}")
