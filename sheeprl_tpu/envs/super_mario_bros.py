"""Super Mario Bros adapter (behavioral equivalent of
`/root/reference/sheeprl/envs/super_mario_bros.py:26-70`).

gym-super-mario-bros is an old-gym NES emulator env; this adapter binds one of
the three canonical joypad action sets and exposes gymnasium semantics with
the frame under Dict key ``rgb``.  The NES `info["time"]` clock distinguishes
running-out-of-time (truncation) from death/flag (termination).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_SUPER_MARIO_AVAILABLE

if not _IS_SUPER_MARIO_AVAILABLE:
    raise ModuleNotFoundError("No module named 'gym_super_mario_bros'")

import gym_super_mario_bros  # noqa: E402
from gym_super_mario_bros import actions as smb_actions  # noqa: E402
from nes_py.wrappers import JoypadSpace  # noqa: E402

ACTION_SETS = {
    "right_only": smb_actions.RIGHT_ONLY,
    "simple": smb_actions.SIMPLE_MOVEMENT,
    "complex": smb_actions.COMPLEX_MOVEMENT,
}


class SuperMarioBrosWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(self, id: str, action_space: str = "simple", render_mode: str = "rgb_array"):
        if action_space not in ACTION_SETS:
            raise ValueError(f"Unknown action set {action_space!r}; expected one of {sorted(ACTION_SETS)}")
        inner = gym_super_mario_bros.make(id)
        self._env = JoypadSpace(inner, ACTION_SETS[action_space])
        self.render_mode = render_mode

        frame_space = inner.observation_space
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(frame_space.low, frame_space.high, frame_space.shape, frame_space.dtype)}
        )
        self.action_space = spaces.Discrete(self._env.action_space.n)

    def step(self, action: Any) -> Tuple[Dict[str, np.ndarray], float, bool, bool, Dict[str, Any]]:
        if isinstance(action, np.ndarray):
            action = int(action.squeeze())
        obs, reward, done, info = self._env.step(action)
        # time==0 means the NES clock expired: a truncation, not a real
        # terminal state.  (The reference wrapper tests `bool(info["time"])`,
        # super_mario_bros.py:58, which inverts this — deliberate fix.)
        out_of_time = info.get("time", 1) == 0
        return {"rgb": obs.copy()}, float(reward), done and not out_of_time, done and out_of_time, info

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        # JoypadSpace predates the seeded reset signature; call the wrapped env
        obs = self._env.env.reset(seed=seed, options=options)
        return {"rgb": np.asarray(obs).copy()}, {}

    def render(self) -> Optional[np.ndarray]:
        frame = self._env.render(mode=self.render_mode)
        if self.render_mode == "rgb_array" and frame is not None:
            return np.asarray(frame).copy()
        return None

    def close(self) -> None:
        self._env.close()
