"""Shared host-side logic for the Minecraft-family adapters (MineRL, MineDojo).

The reference duplicates sticky-action bookkeeping and pitch clamping in both
`/root/reference/sheeprl/envs/minerl.py:238-252,293-306` and
`/root/reference/sheeprl/envs/minedojo.py:184-224,243-248`.  Here that state
machine lives once, as a pure dataclass with no simulator dependency, so it is
unit-testable in this image (neither `minerl` nor `minedojo` is installed) and
both adapters stay thin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple

import numpy as np

__all__ = ["StickyActions", "MineDojoSticky", "PitchTracker", "count_items"]


@dataclass
class StickyActions:
    """MineRL-style sticky attack/jump: repeat for a configurable number of
    steps after last selected, unconditionally (Hafner's Minecraft trick;
    reference minerl.py:238-252).  A sticky attack suppresses jumping; the
    MineRL adapter additionally presses `forward` while a jump is sticky.

    `attack_for`/`jump_for` of 0 disables the respective stickiness.  The
    caller asks `update(attack=..., jump=...)` each step with the *selected*
    flags and receives the *effective* flags.
    """

    attack_for: int = 30
    jump_for: int = 10
    _attack_left: int = field(default=0, init=False)
    _jump_left: int = field(default=0, init=False)

    def update(self, attack: bool, jump: bool) -> Tuple[bool, bool]:
        if self.attack_for:
            if attack:
                self._attack_left = self.attack_for
            if self._attack_left > 0:
                attack = True
                jump = False
                self._attack_left -= 1
        if self.jump_for:
            if jump:
                self._jump_left = self.jump_for
            if self._jump_left > 0:
                jump = True
                self._jump_left -= 1
        return attack, jump

    def reset(self) -> None:
        self._attack_left = 0
        self._jump_left = 0


@dataclass
class MineDojoSticky:
    """MineDojo-style *cancelable* sticky attack/jump, operating on the
    converted 8-slot MineDojo action vector (reference minedojo.py:184-215).

    Differences from the MineRL machine, preserved exactly:
    - selecting attack arms ``attack_for - 1`` *extra* repeats (the selection
      step itself is not counted down);
    - a pending sticky attack only fires on functional no-ops and is canceled
      by any other functional action; it does NOT suppress jumping;
    - a pending sticky jump only fires when no forward/backward was selected
      (pressing forward too when the agent is otherwise still) and is canceled
      when the agent picks sneak/sprint instead of jump.

    Vector slots: 0 forward/backward, 1 left/right, 2 jump/sneak/sprint
    (1 = jump), 5 functional (3 = attack).
    """

    attack_for: int = 30
    jump_for: int = 10
    _attack_left: int = field(default=0, init=False)
    _jump_left: int = field(default=0, init=False)

    def apply(self, vec: np.ndarray) -> np.ndarray:
        if self.attack_for:
            if vec[5] == 3:
                self._attack_left = self.attack_for - 1
            if self._attack_left > 0 and vec[5] == 0:
                vec[5] = 3
                self._attack_left -= 1
            elif vec[5] != 3:
                self._attack_left = 0
        if self.jump_for:
            if vec[2] == 1:
                self._jump_left = self.jump_for - 1
            if self._jump_left > 0 and vec[0] == 0:
                vec[2] = 1
                if vec[0] == 0 and vec[1] == 0:
                    vec[0] = 1
                self._jump_left -= 1
            elif vec[2] != 1:
                self._jump_left = 0
        return vec

    def reset(self) -> None:
        self._attack_left = 0
        self._jump_left = 0


@dataclass
class PitchTracker:
    """Track camera pitch/yaw and veto camera commands that would push the
    pitch outside `limits` (reference minerl.py:293-299, minedojo.py:243-248).
    """

    limits: Tuple[float, float] = (-60.0, 60.0)
    pitch: float = field(default=0.0, init=False)
    yaw: float = field(default=0.0, init=False)

    def apply(self, d_pitch: float, d_yaw: float) -> Tuple[float, float]:
        """Returns the (possibly vetoed) camera delta actually allowed."""
        new_pitch = self.pitch + d_pitch
        if not (self.limits[0] <= new_pitch <= self.limits[1]):
            d_pitch = 0.0
            new_pitch = self.pitch
        self.pitch = new_pitch
        self.yaw = ((self.yaw + d_yaw) + 180.0) % 360.0 - 180.0
        return d_pitch, d_yaw

    def reset(self, pitch: float = 0.0, yaw: float = 0.0) -> None:
        self.pitch = pitch
        self.yaw = yaw


def count_items(
    names, quantities, name_to_id: Dict[str, int], size: int, air_counts_once: bool = True
) -> np.ndarray:
    """Turn an (item name, quantity) listing into a dense per-item count vector
    (the multihot inventory of reference minerl.py:262-273 / minedojo.py:124-144).

    Minecraft reports every empty slot as one `air` item; with
    `air_counts_once` each air slot contributes 1 (matching the reference).
    """
    counts = np.zeros(size, dtype=np.float32)
    for name, qty in zip(names, quantities):
        name = "_".join(str(name).split(" "))
        if name not in name_to_id:
            continue
        counts[name_to_id[name]] += 1.0 if (name == "air" and air_counts_once) else float(qty)
    return counts
