"""Device-resident batched inference helpers for the player hot loops.

At 64-512 concurrent envs the obs→action path must not grow with
``num_envs`` on the host side (PERF.md §2/§11).  Two invariants enforce
that, shared by every rewired loop:

* **one h2d per vector step** — the batched obs slab is staged in a single
  :func:`jax.device_put` call against a sharding object built ONCE per run
  (:func:`obs_sharding`): reusing the sharding lets jax cache the transfer
  plan instead of re-deriving placement per key per step;
* **one blocking d2h per vector step** — every policy output the host needs
  (actions, logprobs, values, ...) is fetched in a single
  :func:`fetch_values` call, so the device-link round trip (~95 ms through a
  remote tunnel, PERF.md §2) is paid once per *vector* step regardless of
  ``num_envs`` — the fetch amortization ``Telemetry/fetch_amortization``
  tracks live.

The policy forward itself stays behind ``diag.instrument(kind="rollout")``,
which is also what counts the fetches for the amortization gauge.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


def obs_sharding(mesh: Optional[Any] = None):
    """The reusable sharding the player stages its obs slab with: fully
    replicated over ``mesh`` when one is given (multi-device rollouts), else
    committed to the default device.  Build it once per run and pass it to
    every per-step ``jax.device_put``/``prepare_obs`` call."""
    import jax

    if mesh is not None and getattr(mesh, "devices", None) is not None and mesh.devices.size > 1:
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(mesh, PartitionSpec())
    return jax.sharding.SingleDeviceSharding(jax.devices()[0])


def fetch_values(*arrays: Any) -> Tuple[Any, ...]:
    """ONE blocking device→host fetch for every policy output the host loop
    needs — ``np.asarray`` per output would pay the link round trip per
    array.  Returns numpy arrays in argument order."""
    import jax

    return tuple(jax.device_get(arrays))
