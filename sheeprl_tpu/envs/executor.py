"""EnvPool-style persistent shared-memory vector-env executor.

Gymnasium's ``AsyncVectorEnv`` round-trips every observation through a pickled
pipe message (or, with ``shared_memory=True``, still pays a per-step pickle of
the step results).  This executor keeps persistent worker processes (spawned
once, reused for the whole run — the EnvPool model, Weng et al. 2022) and
moves the per-step payload entirely through pre-allocated shared buffers:

* actions are written in place by the parent, read in place by workers;
* observations (and the terminal observation on autoreset boundaries) are
  written in place by workers into per-key shared buffers and copied out
  **once**, batched, in :meth:`step_wait`;
* rewards / terminated / truncated live in shared scalar buffers (rewards as
  float32 end-to-end — the training loops cast to float32 anyway, so a
  float64 slab would only buy a bigger buffer and one extra downcast copy);
* the per-step pipe traffic is a single command byte down and a single ack
  byte back **per worker** — the only pickling left happens on the rare steps
  whose ``info`` dict is non-empty (episode ends, env restarts).

Worker sharding (``envs_per_worker``): each worker owns a contiguous slab of
envs and steps it sequentially, writing results straight into its slice of
the shared buffers.  The host's per-step Python work is therefore
O(num_workers) — one command write and one ack drain per worker — plus one
vectorized copy per observation key, instead of the one-process-per-env
model's O(num_envs) pipe round-trips and per-env read loop.  That is what
keeps 64-512 concurrent envs throughput-bound instead of Python-bound
(PERF.md §11); ``envs_per_worker=1`` recovers the one-env-per-process layout
for expensive simulators that need a whole core each.

Autoreset follows ``gym.vector.AutoresetMode.SAME_STEP`` bit-for-bit with
``SyncVectorEnv``: on done the returned obs is the new episode's reset obs,
the terminal obs rides in ``infos["final_obs"]`` and the final step's info in
``infos["final_info"]`` (aggregated through the inherited ``_add_info``, so
the ``_key`` mask layout is byte-identical to gymnasium's own vector envs).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import gymnasium as gym
import numpy as np
from gymnasium.vector.utils import CloudpickleWrapper, batch_space

_CMD_STEP = b"S"  # step every env of the worker's slab
_CMD_CLOSE = b"C"
_CMD_RESET = b"R"  # followed by pickled (per-slab seed list, options)
_ACK_EMPTY = b"n"  # slab stepped: every info was {} and no autoreset happened


def _obs_layout(space: gym.Space) -> List[Tuple[Optional[str], tuple, np.dtype]]:
    """Flatten a Dict-of-Box (or plain Box) observation space into
    ``(key, shape, dtype)`` buffer specs; ``key is None`` for a bare Box."""
    if isinstance(space, gym.spaces.Dict):
        return [(k, tuple(s.shape), np.dtype(s.dtype)) for k, s in space.spaces.items()]
    if isinstance(space, gym.spaces.Box):
        return [(None, tuple(space.shape), np.dtype(space.dtype))]
    raise TypeError(
        f"SharedMemoryVectorEnv supports Box or Dict[str, Box] observation spaces, got: {space}"
    )


def _alloc(ctx, num_envs: int, layout) -> Dict[Optional[str], Any]:
    """One shared byte buffer per obs key, sized ``[num_envs, *shape]``."""
    return {
        key: ctx.RawArray("b", int(num_envs * np.prod(shape, dtype=np.int64) * dtype.itemsize) or 1)
        for key, shape, dtype in layout
    }


def _views(bufs, num_envs: int, layout) -> Dict[Optional[str], np.ndarray]:
    return {
        key: np.frombuffer(bufs[key], dtype=dtype).reshape(num_envs, *shape)
        for key, shape, dtype in layout
    }


def _write_obs(views: Dict[Optional[str], np.ndarray], index: int, obs: Any) -> None:
    for key, view in views.items():
        view[index] = obs if key is None else np.asarray(obs[key])


def _read_obs(views: Dict[Optional[str], np.ndarray], index: int) -> Any:
    if list(views.keys()) == [None]:
        return np.array(views[None][index], copy=True)
    return {k: np.array(v[index], copy=True) for k, v in views.items()}


def auto_envs_per_worker(num_envs: int) -> int:
    """Default slab size: enough workers to use every host core (one env per
    worker up to ``cpu_count`` workers), then grow the slabs instead of the
    process count — 256 envs on a 64-core TPU-VM host become 64 workers of 4
    envs, not 256 processes fighting the scheduler."""
    workers = max(1, min(int(num_envs), os.cpu_count() or 1))
    return -(-int(num_envs) // workers)  # ceil division


def _worker(
    start: int,
    env_fns_wrapper: CloudpickleWrapper,
    pipe,
    obs_bufs,
    final_bufs,
    act_buf,
    rew_buf,
    term_buf,
    trunc_buf,
    obs_specs,
    act_shape,
    act_dtype,
    num_envs: int,
) -> None:
    """Persistent slab worker: owns envs ``[start, start + len(fns))`` and
    steps/resets them in place over the shared buffers, one command/ack round
    trip per *vector* step.

    Env-level fault tolerance stays INSIDE the worker — wrap the env fns in
    ``RestartOnException`` before building the executor and a transient env
    crash is absorbed here (the restart info flag still reaches the parent),
    instead of killing the worker process and its whole slab.
    """
    envs = [fn() for fn in env_fns_wrapper.fn]
    obs_views = _views(obs_bufs, num_envs, obs_specs)
    final_views = _views(final_bufs, num_envs, obs_specs)
    act_view = np.frombuffer(act_buf, dtype=act_dtype).reshape(num_envs, *act_shape[1:])
    rew_view = np.frombuffer(rew_buf, dtype=np.float32)
    term_view = np.frombuffer(term_buf, dtype=np.uint8)
    trunc_view = np.frombuffer(trunc_buf, dtype=np.uint8)
    try:
        while True:
            cmd = pipe.recv_bytes()
            try:
                if cmd == _CMD_STEP:
                    # (env index, info, has_final, final_info) for the rare
                    # envs with something to pickle; an all-quiet slab acks
                    # with one byte
                    payloads: List[Tuple[int, dict, bool, Optional[dict]]] = []
                    for offset, env in enumerate(envs):
                        index = start + offset
                        action = act_view[index]
                        if action.ndim > 0:
                            action = np.array(action, copy=True)  # detach from the shared page
                        obs, reward, terminated, truncated, info = env.step(action)
                        has_final = False
                        final_info: Optional[dict] = None
                        if terminated or truncated:  # SAME_STEP autoreset
                            _write_obs(final_views, index, obs)
                            final_info = info
                            has_final = True
                            obs, info = env.reset()
                        _write_obs(obs_views, index, obs)
                        rew_view[index] = np.float32(reward)
                        term_view[index] = np.uint8(terminated)
                        trunc_view[index] = np.uint8(truncated)
                        if info or has_final:
                            payloads.append((index, info, has_final, final_info))
                    if payloads:
                        pipe.send_bytes(pickle.dumps(("ok", payloads)))
                    else:
                        pipe.send_bytes(_ACK_EMPTY)
                elif cmd == _CMD_CLOSE:
                    break
                else:  # reset: _CMD_RESET + pickled (slab seed list, options)
                    seeds, options = pickle.loads(cmd[1:])
                    infos: List[dict] = []
                    for offset, env in enumerate(envs):
                        obs, info = env.reset(seed=seeds[offset], options=options)
                        _write_obs(obs_views, start + offset, obs)
                        infos.append(info)
                    pipe.send_bytes(pickle.dumps(("ok", infos)))
            except Exception as err:  # noqa: BLE001 — surfaced in the parent
                import traceback

                pipe.send_bytes(pickle.dumps(("error", f"{err!r}\n{traceback.format_exc()}")))
    finally:
        for env in envs:
            try:
                env.close()
            except Exception:  # pragma: no cover - best-effort teardown
                pass
        pipe.close()


class SharedMemoryVectorEnv(gym.vector.VectorEnv):
    """Persistent slab-worker vector env with in-place shared-memory transport.

    Drop-in for ``Sync``/``AsyncVectorEnv`` under SAME_STEP autoreset, with
    native ``step_async``/``step_wait`` so the training loops can overlap env
    stepping with device dispatch.  Selected via ``cfg.env.executor=shared_memory``;
    ``cfg.env.envs_per_worker`` sets the slab size (null = auto heuristic).
    """

    def __init__(
        self,
        env_fns: Sequence[Callable[[], gym.Env]],
        context: str = "spawn",
        step_timeout: Optional[float] = None,
        envs_per_worker: Optional[int] = None,
    ):
        self.env_fns = list(env_fns)
        self.num_envs = len(self.env_fns)
        if self.num_envs == 0:
            raise ValueError("SharedMemoryVectorEnv needs at least one env fn")
        self._step_timeout = step_timeout
        if envs_per_worker in (None, "auto"):
            envs_per_worker = auto_envs_per_worker(self.num_envs)
        self.envs_per_worker = int(envs_per_worker)
        if self.envs_per_worker < 1:
            raise ValueError(f"envs_per_worker must be >= 1, got: {envs_per_worker}")
        # contiguous slabs: worker w owns envs [w*epw, min((w+1)*epw, N))
        self._slabs: List[Tuple[int, int]] = [
            (lo, min(lo + self.envs_per_worker, self.num_envs))
            for lo in range(0, self.num_envs, self.envs_per_worker)
        ]
        self.num_workers = len(self._slabs)

        # probe spaces/metadata exactly like gymnasium's AsyncVectorEnv does
        probe = self.env_fns[0]()
        try:
            self.metadata = dict(getattr(probe, "metadata", {}) or {})
            self.single_observation_space = probe.observation_space
            self.single_action_space = probe.action_space
            self.render_mode = getattr(probe, "render_mode", None)
        finally:
            probe.close()
        self.metadata["autoreset_mode"] = gym.vector.AutoresetMode.SAME_STEP
        self.observation_space = batch_space(self.single_observation_space, self.num_envs)
        # fail at construction like the obs path does — an unsupported action
        # space would otherwise surface as a confusing dtype/reshape error on
        # the first step (batch_space(Dict/Tuple).dtype is None)
        if not isinstance(
            self.single_action_space, (gym.spaces.Box, gym.spaces.Discrete, gym.spaces.MultiDiscrete)
        ):
            raise TypeError(
                "SharedMemoryVectorEnv supports Box, Discrete or MultiDiscrete action "
                f"spaces, got: {self.single_action_space}"
            )
        self.action_space = batch_space(self.single_action_space, self.num_envs)

        ctx = mp.get_context(context)
        self._obs_specs = _obs_layout(self.single_observation_space)
        self._obs_bufs = _alloc(ctx, self.num_envs, self._obs_specs)
        self._final_bufs = _alloc(ctx, self.num_envs, self._obs_specs)
        act_dtype = np.dtype(self.action_space.dtype)
        act_shape = tuple(self.action_space.shape)
        self._act_buf = ctx.RawArray("b", int(np.prod(act_shape, dtype=np.int64) * act_dtype.itemsize) or 1)
        self._rew_buf = ctx.RawArray("b", self.num_envs * 4)  # float32 end-to-end
        self._term_buf = ctx.RawArray("b", self.num_envs)
        self._trunc_buf = ctx.RawArray("b", self.num_envs)

        self._obs_views = _views(self._obs_bufs, self.num_envs, self._obs_specs)
        self._final_views = _views(self._final_bufs, self.num_envs, self._obs_specs)
        self._act_view = np.frombuffer(self._act_buf, dtype=act_dtype).reshape(act_shape)
        self._rew_view = np.frombuffer(self._rew_buf, dtype=np.float32)
        self._term_view = np.frombuffer(self._term_buf, dtype=np.uint8)
        self._trunc_view = np.frombuffer(self._trunc_buf, dtype=np.uint8)

        self._pipes = []
        self._processes = []
        self._pending = False
        self._closed = False
        for w, (lo, hi) in enumerate(self._slabs):
            parent_pipe, child_pipe = ctx.Pipe()
            proc = ctx.Process(
                target=_worker,
                name=f"shm-env-{lo}-{hi - 1}",
                args=(
                    lo,
                    CloudpickleWrapper(tuple(self.env_fns[lo:hi])),
                    child_pipe,
                    self._obs_bufs,
                    self._final_bufs,
                    self._act_buf,
                    self._rew_buf,
                    self._term_buf,
                    self._trunc_buf,
                    self._obs_specs,
                    act_shape,
                    act_dtype,
                    self.num_envs,
                ),
                daemon=True,
            )
            proc.start()
            child_pipe.close()
            self._pipes.append(parent_pipe)
            self._processes.append(proc)

    # -- helpers -----------------------------------------------------------
    def _recv(self, worker: int):
        """One ack from one worker: ``("ok", payload)`` or a raised worker
        error.  ``payload`` is the step payload list or the reset info list."""
        pipe = self._pipes[worker]
        lo, hi = self._slabs[worker]
        if self._step_timeout is not None and not pipe.poll(self._step_timeout):
            raise TimeoutError(
                f"env worker {worker} (envs {lo}..{hi - 1}) did not answer within {self._step_timeout}s"
            )
        try:
            msg = pipe.recv_bytes()
        except (EOFError, ConnectionResetError) as err:
            raise RuntimeError(
                f"env worker {worker} (envs {lo}..{hi - 1}) died (crashed outside RestartOnException?)"
            ) from err
        if msg == _ACK_EMPTY:
            return []
        payload = pickle.loads(msg)
        if payload[0] == "error":
            raise RuntimeError(f"env worker {worker} (envs {lo}..{hi - 1}) raised:\n{payload[1]}")
        return payload[1]

    def _batched_obs(self):
        # ONE vectorized memcpy per key out of the shared slabs.  The copy —
        # not a zero-copy view — is deliberate: the training loops retain the
        # returned obs across the next step_async window, during which the
        # workers are already overwriting the shared pages in place.
        if list(self._obs_views.keys()) == [None]:
            return np.array(self._obs_views[None], copy=True)
        return {k: np.array(v, copy=True) for k, v in self._obs_views.items()}

    # -- gym.vector API ----------------------------------------------------
    def reset(self, *, seed=None, options=None):
        if self._pending:
            raise RuntimeError("reset() called while a step_async is in flight")
        if seed is None:
            seeds: List[Optional[int]] = [None] * self.num_envs
        elif isinstance(seed, int):
            seeds = [seed + i for i in range(self.num_envs)]
        else:
            seeds = list(seed)
            if len(seeds) != self.num_envs:
                raise ValueError(f"expected {self.num_envs} seeds, got {len(seeds)}")
        for pipe, (lo, hi) in zip(self._pipes, self._slabs):
            pipe.send_bytes(_CMD_RESET + pickle.dumps((seeds[lo:hi], options)))
        infos: Dict[str, Any] = {}
        for w, (lo, _) in enumerate(self._slabs):
            for offset, info in enumerate(self._recv(w)):
                infos = self._add_info(infos, info, lo + offset)
        return self._batched_obs(), infos

    def step_async(self, actions) -> None:
        if self._pending:
            raise RuntimeError("step_async() called while a previous step is still in flight")
        np.copyto(self._act_view, np.asarray(actions, dtype=self._act_view.dtype).reshape(self._act_view.shape))
        for pipe in self._pipes:
            pipe.send_bytes(_CMD_STEP)
        self._pending = True

    def step_wait(self):
        if not self._pending:
            raise RuntimeError("step_wait() called with no step_async in flight")
        self._pending = False
        # one ack drain per WORKER; per-env Python happens only for the rare
        # envs that shipped a payload (episode end, restart, non-empty info)
        infos: Dict[str, Any] = {}
        for w in range(self.num_workers):
            for index, info, has_final, final_info in self._recv(w):
                if has_final:
                    infos = self._add_info(
                        infos,
                        {"final_obs": _read_obs(self._final_views, index), "final_info": final_info or {}},
                        index,
                    )
                infos = self._add_info(infos, info, index)
        return (
            self._batched_obs(),
            self._rew_view.copy(),
            self._term_view.astype(np.bool_),
            self._trunc_view.astype(np.bool_),
            infos,
        )

    def step(self, actions):
        self.step_async(actions)
        return self.step_wait()

    def close(self, **kwargs) -> None:
        if self._closed:
            return
        self._closed = True
        if self._pending:  # drain so workers are at the top of their loop
            try:
                self.step_wait()
            except Exception:  # pragma: no cover - already tearing down
                pass
        for pipe in self._pipes:
            try:
                pipe.send_bytes(_CMD_CLOSE)
            except (BrokenPipeError, OSError):
                pass
        deadline = time.monotonic() + 5.0
        for proc in self._processes:
            proc.join(max(0.1, deadline - time.monotonic()))
            if proc.is_alive():  # pragma: no cover - stuck worker
                proc.terminate()
        for pipe in self._pipes:
            pipe.close()

    def __del__(self):  # pragma: no cover - GC teardown
        try:
            self.close()
        except Exception:
            pass
