"""Deterministic synthetic environments used as the test backbone
(reference /root/reference/sheeprl/envs/dummy.py).  They produce a dict
observation space with a ``rgb`` pixel key (CHW uint8) and a ``state`` vector
key, across the three action-space families.

``sleep_ms`` gives ``step`` a deterministic wall-clock latency (a plain
``time.sleep``, so it overlaps host work from a worker thread/process exactly
like a real simulator would) — the async env-pipeline tests use it to assert
wall-clock overlap without depending on a real slow environment."""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

import gymnasium as gym
import numpy as np


class _DummyEnv(gym.Env):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        dict_obs_space: bool = True,
        sleep_ms: float = 0.0,
    ):
        self._dict_obs_space = dict_obs_space
        self._sleep_s = max(0.0, float(sleep_ms)) / 1000.0
        if dict_obs_space:
            self.observation_space = gym.spaces.Dict(
                {
                    "rgb": gym.spaces.Box(0, 255, shape=image_size, dtype=np.uint8),
                    "state": gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32),
                }
            )
        else:
            self.observation_space = gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32)
        self.reward_range = (-np.inf, np.inf)
        self._current_step = 0
        self._n_steps = n_steps

    def get_obs(self):
        if self._dict_obs_space:
            return {
                "rgb": np.full(self.observation_space["rgb"].shape, self._current_step % 256, dtype=np.uint8),
                "state": np.full(self.observation_space["state"].shape, self._current_step % 20, dtype=np.float32),
            }
        return np.full(self.observation_space.shape, self._current_step % 20, dtype=np.float32)

    def step(self, action):
        if self._sleep_s > 0.0:
            time.sleep(self._sleep_s)
        done = self._current_step == self._n_steps
        self._current_step += 1
        return self.get_obs(), 0.0, done, False, {}

    def reset(self, seed=None, options=None):
        self._current_step = 0
        return self.get_obs(), {}

    def render(self):
        return np.zeros((64, 64, 3), dtype=np.uint8)

    def close(self):
        pass


class ContinuousDummyEnv(_DummyEnv):
    def __init__(self, action_dim: int = 2, **kwargs):
        self.action_space = gym.spaces.Box(-np.inf, np.inf, shape=(action_dim,))
        super().__init__(**kwargs)


class DiscreteDummyEnv(_DummyEnv):
    def __init__(self, action_dim: int = 2, n_steps: int = 4, **kwargs):
        self.action_space = gym.spaces.Discrete(action_dim)
        super().__init__(n_steps=n_steps, **kwargs)


class MultiDiscreteDummyEnv(_DummyEnv):
    def __init__(self, action_dims: List[int] = [2, 2], **kwargs):
        self.action_space = gym.spaces.MultiDiscrete(action_dims)
        super().__init__(**kwargs)
