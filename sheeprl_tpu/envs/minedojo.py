"""MineDojo adapter (behavioral equivalent of
`/root/reference/sheeprl/envs/minedojo.py:56-307`).

Exposes a MultiDiscrete([n_action_types, n_craft_items, n_all_items]) action
space over MineDojo's 8-slot ARNN action encoding, and a Dict observation with
dense per-item inventory/equipment vectors plus the action masks the
hierarchical `MinedojoActor` consumes (see
sheeprl_tpu/algos/dreamer_v3/agent.py MinedojoActor).

Sticky attack/jump and pitch clamping are delegated to the shared
`sheeprl_tpu.envs._minecraft` state machines.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.envs._minecraft import MineDojoSticky, PitchTracker, count_items
from sheeprl_tpu.utils.imports import _IS_MINEDOJO_AVAILABLE

if not _IS_MINEDOJO_AVAILABLE:
    raise ModuleNotFoundError("No module named 'minedojo'")

import minedojo  # noqa: E402
import minedojo.tasks  # noqa: E402
from minedojo.sim import ALL_CRAFT_SMELT_ITEMS, ALL_ITEMS  # noqa: E402

N_ALL_ITEMS = len(ALL_ITEMS)
ITEM_NAME_TO_ID = {name: i for i, name in enumerate(ALL_ITEMS)}
ITEM_ID_TO_NAME = dict(enumerate(ALL_ITEMS))

# The 19 composite action types, each encoded as an 8-slot ARNN action:
# [move, strafe, jump/sneak/sprint, pitch(0..24, 12=noop), yaw(0..24, 12=noop),
#  functional(0=noop 1=use 2=drop 3=attack 4=craft 5=equip 6=place 7=destroy),
#  craft arg, inventory arg]
_NOOP = (0, 0, 0, 12, 12, 0, 0, 0)


def _arnn(move=0, strafe=0, body=0, pitch=12, yaw=12, fn=0) -> np.ndarray:
    return np.array([move, strafe, body, pitch, yaw, fn, 0, 0])


ACTION_MAP: Dict[int, np.ndarray] = {
    0: _arnn(),  # no-op
    1: _arnn(move=1),  # forward
    2: _arnn(move=2),  # back
    3: _arnn(strafe=1),  # left
    4: _arnn(strafe=2),  # right
    5: _arnn(move=1, body=1),  # jump + forward
    6: _arnn(move=1, body=2),  # sneak + forward
    7: _arnn(move=1, body=3),  # sprint + forward
    8: _arnn(pitch=11),  # pitch down −15°
    9: _arnn(pitch=13),  # pitch up +15°
    10: _arnn(yaw=11),  # yaw −15°
    11: _arnn(yaw=13),  # yaw +15°
    12: _arnn(fn=1),  # use
    13: _arnn(fn=2),  # drop
    14: _arnn(fn=3),  # attack
    15: _arnn(fn=4),  # craft
    16: _arnn(fn=5),  # equip
    17: _arnn(fn=6),  # place
    18: _arnn(fn=7),  # destroy
}
_FN_ATTACK, _FN_CRAFT = 3, 4
_FN_WITH_ITEM_ARG = (5, 6, 7)  # equip / place / destroy


class MineDojoWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array", "human"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: int = 30,
        sticky_jump: int = 10,
        **kwargs: Any,
    ):
        self._start_position = kwargs.get("start_position", None)
        break_speed = kwargs.pop("break_speed_multiplier", 100)
        if self._start_position is not None and not (
            pitch_limits[0] <= self._start_position["pitch"] <= pitch_limits[1]
        ):
            raise ValueError(
                f"Initial pitch {self._start_position['pitch']} outside the limits {pitch_limits}"
            )
        # a >1 break-speed multiplier already shortens digging; stickiness on
        # top of it would overshoot (reference minedojo.py:74)
        self._sticky = MineDojoSticky(
            attack_for=0 if break_speed > 1 else sticky_attack, jump_for=sticky_jump
        )
        self._pitch = PitchTracker(limits=(float(pitch_limits[0]), float(pitch_limits[1])))

        # minedojo.make mutates the global task-spec table; restore it after
        task_specs_backup = copy.deepcopy(minedojo.tasks.ALL_TASKS_SPECS)
        self._env = minedojo.make(
            task_id=id,
            image_size=(height, width),
            world_seed=seed,
            fast_reset=True,
            break_speed_multiplier=break_speed,
            **kwargs,
        )
        minedojo.tasks.ALL_TASKS_SPECS = task_specs_backup

        self._slot_of_item: Dict[str, int] = {}  # item name -> first inventory slot
        self._slot_names: np.ndarray = np.array([])
        self._inventory_max = np.zeros(N_ALL_ITEMS, np.float32)
        self.action_space = spaces.MultiDiscrete(
            np.array([len(ACTION_MAP), len(ALL_CRAFT_SMELT_ITEMS), N_ALL_ITEMS])
        )
        self.observation_space = spaces.Dict(
            {
                "rgb": spaces.Box(0, 255, self._env.observation_space["rgb"].shape, np.uint8),
                "inventory": spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_max": spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_delta": spaces.Box(-np.inf, np.inf, (N_ALL_ITEMS,), np.float32),
                "equipment": spaces.Box(0.0, 1.0, (N_ALL_ITEMS,), np.int32),
                "life_stats": spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
                "mask_action_type": spaces.Box(0, 1, (len(ACTION_MAP),), bool),
                "mask_equip_place": spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_destroy": spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_craft_smelt": spaces.Box(0, 1, (len(ALL_CRAFT_SMELT_ITEMS),), bool),
            }
        )
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    # ---- observation conversion -------------------------------------------------

    def _scan_inventory(self, inventory: Dict[str, Any]) -> np.ndarray:
        names = ["_".join(str(n).split(" ")) for n in inventory["name"].tolist()]
        self._slot_names = np.array(names)
        self._slot_of_item = {}
        for slot, name in enumerate(names):
            self._slot_of_item.setdefault(name, slot)
        counts = count_items(names, inventory["quantity"], ITEM_NAME_TO_ID, N_ALL_ITEMS)
        self._inventory_max = np.maximum(counts, self._inventory_max)
        return counts

    @staticmethod
    def _inventory_delta(delta: Dict[str, Any]) -> np.ndarray:
        out = np.zeros(N_ALL_ITEMS, np.float32)
        for names_key, qty_key, sign in (
            ("inc_name_by_craft", "inc_quantity_by_craft", 1.0),
            ("dec_name_by_craft", "dec_quantity_by_craft", -1.0),
            ("inc_name_by_other", "inc_quantity_by_other", 1.0),
            ("dec_name_by_other", "dec_quantity_by_other", -1.0),
        ):
            for name, qty in zip(delta[names_key], delta[qty_key]):
                out[ITEM_NAME_TO_ID["_".join(str(name).split(" "))]] += sign * float(qty)
        return out

    @staticmethod
    def _equipment_onehot(equipment: Dict[str, Any]) -> np.ndarray:
        onehot = np.zeros(N_ALL_ITEMS, np.int32)
        onehot[ITEM_NAME_TO_ID["_".join(str(equipment["name"][0]).split(" "))]] = 1
        return onehot

    def _masks(self, masks: Dict[str, Any]) -> Dict[str, np.ndarray]:
        # per-slot equip/destroy masks -> per-item-id masks
        equip_mask = np.zeros(N_ALL_ITEMS, bool)
        destroy_mask = np.zeros(N_ALL_ITEMS, bool)
        for name, can_equip, can_destroy in zip(self._slot_names, masks["equip"], masks["destroy"]):
            item_id = ITEM_NAME_TO_ID[name]
            equip_mask[item_id] |= bool(can_equip)
            destroy_mask[item_id] |= bool(can_destroy)
        action_type = masks["action_type"].copy()
        action_type[5:7] &= bool(equip_mask.any())  # equip/place need an equippable item
        action_type[7] &= bool(destroy_mask.any())
        return {
            # the 12 movement/camera action types are always legal
            "mask_action_type": np.concatenate((np.ones(12, bool), action_type[1:])),
            "mask_equip_place": equip_mask,
            "mask_destroy": destroy_mask,
            "mask_craft_smelt": np.asarray(masks["craft_smelt"], bool),
        }

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            "rgb": obs["rgb"].copy(),
            "inventory": self._scan_inventory(obs["inventory"]),
            "inventory_max": self._inventory_max,
            "inventory_delta": self._inventory_delta(obs["delta_inv"]),
            "equipment": self._equipment_onehot(obs["equipment"]),
            "life_stats": np.concatenate(
                (obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["oxygen"])
            ),
            **self._masks(obs["masks"]),
        }

    # ---- action conversion ------------------------------------------------------

    def _convert_action(self, action: np.ndarray) -> np.ndarray:
        arnn = ACTION_MAP[int(action[0])].copy()
        arnn = self._sticky.apply(arnn)
        arnn[6] = int(action[1]) if arnn[5] == _FN_CRAFT else 0
        # equip/place/destroy take the *slot* of the chosen item id
        if arnn[5] in _FN_WITH_ITEM_ARG:
            arnn[7] = self._slot_of_item[ITEM_ID_TO_NAME[int(action[2])]]
        else:
            arnn[7] = 0
        return arnn

    # ---- gym API ----------------------------------------------------------------

    @staticmethod
    def _location(obs: Dict[str, Any]) -> Dict[str, float]:
        pos = obs["location_stats"]["pos"]
        return {
            "x": float(pos[0]),
            "y": float(pos[1]),
            "z": float(pos[2]),
            "pitch": float(obs["location_stats"]["pitch"].item()),
            "yaw": float(obs["location_stats"]["yaw"].item()),
        }

    @staticmethod
    def _life(obs: Dict[str, Any]) -> Dict[str, float]:
        return {
            "life": float(obs["life_stats"]["life"].item()),
            "oxygen": float(obs["life_stats"]["oxygen"].item()),
            "food": float(obs["life_stats"]["food"].item()),
        }

    def step(self, action: np.ndarray) -> Tuple[Any, float, bool, bool, Dict[str, Any]]:
        arnn = self._convert_action(np.asarray(action))
        d_pitch, _ = self._pitch.apply((arnn[3] - 12) * 15.0, (arnn[4] - 12) * 15.0)
        if d_pitch == 0.0 and arnn[3] != 12:
            arnn[3] = 12  # camera veto: pitch would leave the limits

        obs, reward, done, info = self._env.step(arnn)
        out_of_time = bool(info.get("TimeLimit.truncated", False))
        loc = self._location(obs)
        self._pitch.pitch, self._pitch.yaw = loc["pitch"], loc["yaw"]
        info.update(
            {
                "life_stats": self._life(obs),
                "location_stats": loc,
                "action": np.asarray(action).tolist(),
                "biomeid": float(obs["location_stats"]["biome_id"].item()),
            }
        )
        return self._convert_obs(obs), float(reward), done and not out_of_time, done and out_of_time, info

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Any, Dict[str, Any]]:
        obs = self._env.reset()
        loc = self._location(obs)
        self._sticky.reset()
        self._pitch.reset(pitch=loc["pitch"], yaw=loc["yaw"])
        self._inventory_max = np.zeros(N_ALL_ITEMS, np.float32)
        return self._convert_obs(obs), {
            "life_stats": self._life(obs),
            "location_stats": loc,
            "biomeid": float(obs["location_stats"]["biome_id"].item()),
        }

    def render(self) -> Optional[np.ndarray]:
        prev = self._env.unwrapped._prev_obs
        return None if prev is None else prev["rgb"]

    def close(self) -> None:
        self._env.close()
