"""Custom MineRL task specs (reference: /root/reference/sheeprl/envs/minerl_envs/)."""
