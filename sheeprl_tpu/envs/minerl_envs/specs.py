"""Custom MineRL (herobraine) task specs: Navigate and Obtain variants.

Behavioral equivalent of `/root/reference/sheeprl/envs/minerl_envs/
{backend,navigate,obtain}.py` (~530 LoC, themselves derived from
minerllabs/minerl and danijar/diamond_env), reorganised as one data-driven
module: the per-task differences (observables, actionables, reward schedule,
quit conditions, world generation) are declarative class attributes on a
single spec base instead of three parallel subclass files.

Key shared behaviors:
  * a `BreakSpeedMultiplier` agent-start handler (faster digging, Hafner's
    diamond_env trick);
  * time limits are handled OUTSIDE the simulator (max_episode_steps=None)
    because MineRL cannot distinguish terminated from truncated — the
    gymnasium TimeLimit wrapper in make_env does it instead;
  * the simple-embodiment keyboard action set + camera.
"""

from __future__ import annotations

from abc import ABC
from typing import Any, Dict, List, Optional, Sequence

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("No module named 'minerl'")

from minerl.herobraine.env_spec import EnvSpec  # noqa: E402
from minerl.herobraine.hero import handler, handlers  # noqa: E402
from minerl.herobraine.hero.mc import INVERSE_KEYMAP  # noqa: E402

KEYBOARD_ACTIONS = ("forward", "back", "left", "right", "jump", "sneak", "sprint", "attack")
NONE = "none"

# The item hierarchy toward a diamond, with the standard MineRL milestone
# rewards.  ObtainIronPickaxe uses the same ladder truncated before diamond.
DIAMOND_REWARD_LADDER: List[Dict[str, Any]] = [
    {"type": "log", "amount": 1, "reward": 1},
    {"type": "planks", "amount": 1, "reward": 2},
    {"type": "stick", "amount": 1, "reward": 4},
    {"type": "crafting_table", "amount": 1, "reward": 4},
    {"type": "wooden_pickaxe", "amount": 1, "reward": 8},
    {"type": "cobblestone", "amount": 1, "reward": 16},
    {"type": "furnace", "amount": 1, "reward": 32},
    {"type": "stone_pickaxe", "amount": 1, "reward": 32},
    {"type": "iron_ore", "amount": 1, "reward": 64},
    {"type": "iron_ingot", "amount": 1, "reward": 128},
    {"type": "iron_pickaxe", "amount": 1, "reward": 256},
    {"type": "diamond", "amount": 1, "reward": 1024},
]

OBTAIN_INVENTORY_ITEMS = (
    "dirt", "coal", "torch", "log", "planks", "stick", "crafting_table",
    "wooden_axe", "wooden_pickaxe", "stone", "cobblestone", "furnace",
    "stone_axe", "stone_pickaxe", "iron_ore", "iron_ingot", "iron_axe", "iron_pickaxe",
)  # fmt: skip
TOOL_ITEMS = (
    "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe", "iron_axe", "iron_pickaxe",
)  # fmt: skip


class BreakSpeedMultiplier(handler.Handler):
    """Malmo agent-start flag that scales block-breaking speed."""

    def __init__(self, multiplier: float = 1.0):
        self.multiplier = multiplier

    def to_string(self) -> str:
        return f"break_speed({self.multiplier})"

    def xml_template(self) -> str:
        return "<BreakSpeedMultiplier>{{multiplier}}</BreakSpeedMultiplier>"


class _SimpleEmbodimentSpec(EnvSpec, ABC):
    """Shared base: POV + location + life-stats observables, keyboard+camera
    actions, break-speed agent start."""

    def __init__(self, name: str, *args, resolution=(64, 64), break_speed: float = 100, **kwargs):
        self.resolution = resolution
        self.break_speed = break_speed
        super().__init__(name, *args, **kwargs)

    def create_agent_start(self) -> List[handler.Handler]:
        return [BreakSpeedMultiplier(self.break_speed)]

    def create_observables(self) -> List[handler.Handler]:
        return [
            handlers.POVObservation(self.resolution),
            handlers.ObservationFromCurrentLocation(),
            handlers.ObservationFromLifeStats(),
        ]

    def create_actionables(self) -> List[handler.Handler]:
        keyboard = [
            handlers.KeybasedCommandAction(key, value)
            for key, value in INVERSE_KEYMAP.items()
            if key in KEYBOARD_ACTIONS
        ]
        return keyboard + [handlers.CameraAction()]

    def create_monitors(self) -> List[handler.Handler]:
        return []

    def create_server_quit_producers(self) -> List[handler.Handler]:
        return [handlers.ServerQuitWhenAnyAgentFinishes()]

    def get_docstring(self) -> str:
        return self.__class__.__doc__ or ""


class CustomNavigate(_SimpleEmbodimentSpec):
    """Reach a diamond block ~64 m away guided by a compass observation.

    `dense` adds per-block progress reward; `extreme` spawns in extreme-hills
    terrain.  +100 on touching the goal block, which also ends the episode.
    """

    def __init__(self, dense: bool, extreme: bool, *args, **kwargs):
        self.dense, self.extreme = dense, extreme
        name = "CustomMineRLNavigate{}{}-v0".format("Extreme" if extreme else "", "Dense" if dense else "")
        kwargs.pop("max_episode_steps", None)  # TimeLimit lives outside the sim
        super().__init__(name, *args, max_episode_steps=None, **kwargs)

    def is_from_folder(self, folder: str) -> bool:
        return folder == ("navigateextreme" if self.extreme else "navigate")

    def create_observables(self) -> List[handler.Handler]:
        return super().create_observables() + [
            handlers.CompassObservation(angle=True, distance=False),
            handlers.FlatInventoryObservation(["dirt"]),
        ]

    def create_actionables(self) -> List[handler.Handler]:
        place_dirt = handlers.PlaceBlock([NONE, "dirt"], _other=NONE, _default=NONE)
        return super().create_actionables() + [place_dirt]

    def create_rewardables(self) -> List[handler.Handler]:
        goal = handlers.RewardForTouchingBlockType(
            [{"type": "diamond_block", "behaviour": "onceOnly", "reward": 100.0}]
        )
        if self.dense:
            return [goal, handlers.RewardForDistanceTraveledToCompassTarget(reward_per_block=1.0)]
        return [goal]

    def create_agent_start(self) -> List[handler.Handler]:
        compass = handlers.SimpleInventoryAgentStart([{"type": "compass", "quantity": "1"}])
        return super().create_agent_start() + [compass]

    def create_agent_handlers(self) -> List[handler.Handler]:
        return [handlers.AgentQuitFromTouchingBlockType(["diamond_block"])]

    def create_server_world_generators(self) -> List[handler.Handler]:
        if self.extreme:
            return [handlers.BiomeGenerator(biome=3, force_reset=True)]
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_decorators(self) -> List[handler.Handler]:
        return [
            handlers.NavigationDecorator(
                max_randomized_radius=64,
                min_randomized_radius=64,
                block="diamond_block",
                placement="surface",
                max_radius=8,
                min_radius=0,
                max_randomized_distance=8,
                min_randomized_distance=0,
                randomize_compass_location=True,
            )
        ]

    def create_server_initial_conditions(self) -> List[handler.Handler]:
        return [
            handlers.TimeInitialCondition(allow_passage_of_time=False, start_time=6000),
            handlers.WeatherInitialCondition("clear"),
            handlers.SpawningInitialCondition("false"),
        ]

    def determine_success_from_rewards(self, rewards: Sequence[float]) -> bool:
        return sum(rewards) >= (160.0 if self.dense else 100.0)


class _CustomObtain(_SimpleEmbodimentSpec):
    """Shared machinery for the Obtain* tasks: crafting/smelting/placing
    action handlers, the obtain inventory view, and a milestone reward ladder
    (each rung rewarded once, or on every collection when `dense`)."""

    target_item: str = ""
    quit_handler_factory = staticmethod(
        lambda: [handlers.AgentQuitFromPossessingItem([{"type": "diamond", "amount": 1}])]
    )

    def __init__(self, dense: bool, *args, reward_schedule: Optional[List[Dict[str, Any]]] = None, **kwargs):
        self.dense = dense
        self.reward_schedule = reward_schedule or [{"type": self.target_item, "amount": 1, "reward": 1}]
        camel = "".join(part.capitalize() for part in self.target_item.split("_"))
        name = "CustomMineRLObtain{}{}-v0".format(camel, "Dense" if dense else "")
        kwargs.pop("max_episode_steps", None)
        super().__init__(name, *args, max_episode_steps=None, **kwargs)

    def is_from_folder(self, folder: str) -> bool:
        return folder == f"o_{self.target_item}"

    def create_observables(self) -> List[handler.Handler]:
        return super().create_observables() + [
            handlers.FlatInventoryObservation(list(OBTAIN_INVENTORY_ITEMS)),
            handlers.EquippedItemObservation(
                items=["air", *TOOL_ITEMS, "other"], _default="air", _other="other"
            ),
        ]

    def create_actionables(self) -> List[handler.Handler]:
        return super().create_actionables() + [
            handlers.PlaceBlock(
                [NONE, "dirt", "stone", "cobblestone", "crafting_table", "furnace", "torch"],
                _other=NONE,
                _default=NONE,
            ),
            handlers.EquipAction([NONE, "air", *TOOL_ITEMS], _other=NONE, _default=NONE),
            handlers.CraftAction([NONE, "torch", "stick", "planks", "crafting_table"], _other=NONE, _default=NONE),
            handlers.CraftNearbyAction([NONE, *TOOL_ITEMS, "furnace"], _other=NONE, _default=NONE),
            handlers.SmeltItemNearby([NONE, "iron_ingot", "coal"], _other=NONE, _default=NONE),
        ]

    def create_rewardables(self) -> List[handler.Handler]:
        reward_cls = handlers.RewardForCollectingItems if self.dense else handlers.RewardForCollectingItemsOnce
        return [reward_cls(self.reward_schedule)]

    def create_agent_handlers(self) -> List[handler.Handler]:
        return self.quit_handler_factory()

    def create_server_world_generators(self) -> List[handler.Handler]:
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_decorators(self) -> List[handler.Handler]:
        return []

    def create_server_initial_conditions(self) -> List[handler.Handler]:
        return [
            handlers.TimeInitialCondition(start_time=6000, allow_passage_of_time=True),
            handlers.SpawningInitialCondition(allow_spawning=True),
        ]

    def determine_success_from_rewards(self, rewards: Sequence[float]) -> bool:
        # success = hit (almost) every rung of the ladder; 10% slack
        ladder = {rung["reward"] for rung in self.reward_schedule}
        misses_allowed = round(len(self.reward_schedule) * 0.1)
        return len(ladder.intersection(set(rewards))) >= len(ladder) - misses_allowed


class CustomObtainDiamond(_CustomObtain):
    """Obtain a diamond from scratch; episode ends on success."""

    target_item = "diamond"

    def __init__(self, dense: bool, *args, **kwargs):
        super().__init__(dense, *args, reward_schedule=list(DIAMOND_REWARD_LADDER), **kwargs)


class CustomObtainIronPickaxe(_CustomObtain):
    """Obtain (craft) an iron pickaxe; episode ends on crafting it."""

    target_item = "iron_pickaxe"
    quit_handler_factory = staticmethod(
        lambda: [handlers.AgentQuitFromCraftingItem([{"type": "iron_pickaxe", "amount": 1}])]
    )

    def __init__(self, dense: bool, *args, **kwargs):
        super().__init__(dense, *args, reward_schedule=DIAMOND_REWARD_LADDER[:-1], **kwargs)
