"""DeepMind Control Suite adapter.

Behavioral equivalent of `/root/reference/sheeprl/envs/dmc.py:49-244` (itself
descended from dmc2gym): a `gymnasium.Env` over `dm_control.suite` tasks with
a normalized [-1, 1] action space, pixel and/or vector observations under a
Dict space, and dm_env discount semantics mapped onto gymnasium's
terminated/truncated split.

The spec/observation conversions are pure module functions so they are
unit-testable without dm_control installed (see tests/test_envs/test_dmc.py).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_DMC_AVAILABLE

if not _IS_DMC_AVAILABLE:
    raise ModuleNotFoundError("No module named 'dm_control'")

from dm_control import suite  # noqa: E402
from dm_env import specs  # noqa: E402


def specs_to_box(spec_list: Iterable[Any], dtype=np.float32) -> spaces.Box:
    """Concatenate a sequence of dm_env array specs into one flat Box.

    Unbounded `Array` specs become (-inf, inf); `BoundedArray` keeps its
    bounds, broadcast to the flattened length.
    """
    lows, highs = [], []
    for s in spec_list:
        n = int(np.prod(s.shape)) if s.shape else 1
        if isinstance(s, specs.BoundedArray):
            lows.append(np.broadcast_to(np.asarray(s.minimum, np.float32), (n,)).ravel())
            highs.append(np.broadcast_to(np.asarray(s.maximum, np.float32), (n,)).ravel())
        elif isinstance(s, specs.Array):
            lows.append(np.full((n,), -np.inf, np.float32))
            highs.append(np.full((n,), np.inf, np.float32))
        else:
            raise ValueError(f"Unsupported dm_env spec: {type(s)}")
    low = np.concatenate(lows).astype(dtype)
    high = np.concatenate(highs).astype(dtype)
    return spaces.Box(low, high, dtype=dtype)


def flatten_dmc_obs(obs: Dict[str, Any]) -> np.ndarray:
    """Flatten a dm_env observation OrderedDict into one 1-D float vector."""
    parts = [np.atleast_1d(np.asarray(v)).ravel() for v in obs.values()]
    return np.concatenate(parts, axis=0)


def rescale_action(action: np.ndarray, low: np.ndarray, high: np.ndarray) -> np.ndarray:
    """Map an action in [-1, 1] onto the task's true bounds [low, high]."""
    action = np.asarray(action, np.float64)
    return (low + (action + 1.0) * 0.5 * (high - low)).astype(np.float32)


class DMCWrapper(gym.Env):
    """Gymnasium front-end over one dm_control suite task.

    Observation space (always a Dict):
      * ``rgb``   — camera render, uint8, CHW if `channels_first` — present
        when `from_pixels`;
      * ``state`` — flattened task observation vector — present when
        `from_vectors`.

    dm_env episode semantics: an episode that ends with discount 0 is a true
    termination; ending with discount 1 is a time-limit truncation
    (reference dmc.py:228-229).
    """

    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        domain_name: str,
        task_name: str,
        from_pixels: bool = False,
        from_vectors: bool = True,
        height: int = 84,
        width: int = 84,
        camera_id: int = 0,
        task_kwargs: Optional[Dict[str, Any]] = None,
        environment_kwargs: Optional[Dict[str, Any]] = None,
        channels_first: bool = True,
        visualize_reward: bool = False,
        seed: Optional[int] = None,
    ):
        if not (from_pixels or from_vectors):
            raise ValueError("At least one of 'from_pixels'/'from_vectors' must be True")
        task_kwargs = dict(task_kwargs or {})
        task_kwargs.pop("random", None)  # seeding goes through reset()

        self._env = suite.load(
            domain_name=domain_name,
            task_name=task_name,
            task_kwargs=task_kwargs,
            environment_kwargs=environment_kwargs,
            visualize_reward=visualize_reward,
        )
        self._from_pixels = from_pixels
        self._from_vectors = from_vectors
        self._height, self._width = height, width
        self._camera_id = camera_id
        self._channels_first = channels_first

        self._true_action_space = specs_to_box([self._env.action_spec()])
        self.action_space = spaces.Box(-1.0, 1.0, self._true_action_space.shape, np.float32)

        obs_spaces: Dict[str, spaces.Space] = {}
        if from_pixels:
            img_shape = (3, height, width) if channels_first else (height, width, 3)
            obs_spaces["rgb"] = spaces.Box(0, 255, img_shape, np.uint8)
        if from_vectors:
            obs_spaces["state"] = specs_to_box(self._env.observation_spec().values(), np.float64)
        self.observation_space = spaces.Dict(obs_spaces)
        self.state_space = specs_to_box(self._env.observation_spec().values(), np.float64)

        reward_box = specs_to_box([self._env.reward_spec()])
        self.reward_range = (float(reward_box.low[0]), float(reward_box.high[0]))
        self.render_mode = "rgb_array"
        self.current_state: Optional[np.ndarray] = None
        self._seed_spaces(seed)

    def _seed_spaces(self, seed: Optional[int]) -> None:
        self.action_space.seed(seed)
        self.observation_space.seed(seed)

    def _observe(self, time_step) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        if self._from_pixels:
            frame = self.render()
            if self._channels_first:
                frame = np.transpose(frame, (2, 0, 1)).copy()
            out["rgb"] = frame
        if self._from_vectors:
            out["state"] = flatten_dmc_obs(time_step.observation)
        return out

    def step(self, action) -> Tuple[Dict[str, np.ndarray], float, bool, bool, Dict[str, Any]]:
        scaled = rescale_action(action, self._true_action_space.low, self._true_action_space.high)
        ts = self._env.step(scaled)
        self.current_state = flatten_dmc_obs(ts.observation)
        terminated = bool(ts.last() and ts.discount == 0) and not ts.first()
        truncated = bool(ts.last() and ts.discount == 1)
        info = {"discount": ts.discount, "internal_state": self._env.physics.get_state().copy()}
        return self._observe(ts), float(ts.reward or 0.0), terminated, truncated, info

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        # dm_control tasks hold their RNG on the task object
        self._env.task._random = np.random.RandomState(seed)
        ts = self._env.reset()
        self.current_state = flatten_dmc_obs(ts.observation)
        return self._observe(ts), {}

    def render(self) -> np.ndarray:
        return self._env.physics.render(height=self._height, width=self._width, camera_id=self._camera_id)

    def close(self) -> None:
        self._env.close()
