"""Crafter adapter (behavioral equivalent of
`/root/reference/sheeprl/envs/crafter.py:17-66`).

Crafter is an old-gym env (4-tuple step, `info["discount"]`); this wraps it
into gymnasium semantics with the pixel observation under a Dict key ``rgb``.
`id` selects the reward variant: ``crafter_reward`` or ``crafter_nonreward``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.utils.imports import _IS_CRAFTER_AVAILABLE

if not _IS_CRAFTER_AVAILABLE:
    raise ModuleNotFoundError("No module named 'crafter'")

import crafter  # noqa: E402

_VALID_IDS = ("crafter_reward", "crafter_nonreward")


class CrafterWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}

    def __init__(self, id: str, screen_size: Union[int, Tuple[int, int]] = 64, seed: Optional[int] = None):
        if id not in _VALID_IDS:
            raise ValueError(f"Unknown crafter id {id!r}; expected one of {_VALID_IDS}")
        size = (screen_size, screen_size) if isinstance(screen_size, int) else tuple(screen_size)
        self._env = crafter.Env(size=size, seed=seed, reward=(id == "crafter_reward"))

        inner = self._env.observation_space
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(inner.low, inner.high, inner.shape, inner.dtype)}
        )
        self.action_space = spaces.Discrete(self._env.action_space.n)
        self.reward_range = self._env.reward_range or (-np.inf, np.inf)
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    def step(self, action: Any) -> Tuple[Dict[str, np.ndarray], float, bool, bool, Dict[str, Any]]:
        obs, reward, done, info = self._env.step(action)
        # crafter signals a true death with discount 0; discount 1 at done is
        # the 10k-step time limit (reference crafter.py:51-53)
        terminated = done and info["discount"] == 0
        truncated = done and info["discount"] != 0
        return {"rgb": obs}, float(reward), terminated, truncated, info

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        self._env._seed = seed
        return {"rgb": self._env.reset()}, {}

    def render(self) -> Optional[np.ndarray]:
        return self._env.render()

    def close(self) -> None:
        pass
