"""Split-phase (``step_async`` / ``step_wait``) facade over any vector env.

The training loops' critical path used to be ``fetch actions -> envs.step ->
train dispatch`` — a fully serialized sum (PERF.md §2).  This wrapper gives
every executor one uniform async surface so the hot loops can issue the env
step the moment the action values land, keep dispatching device work (train
step, replay writes) while the env workers are stepping, and only block in
``step_wait`` right before the observations are needed — making the
per-iteration critical path ``max(host dispatch + fetch, env_step)``.

Executors (``cfg.env.executor``):

* ``sync`` — gymnasium ``SyncVectorEnv``; ``step_async`` runs the serial step
  on a dedicated background thread.  Real simulators release the GIL in their
  native step (and plain sleeps do too), so the overlap is real; for pure
  in-process Python toy envs it degrades gracefully to the serialized cost.
* ``async`` — gymnasium ``AsyncVectorEnv`` (one spawned OS process per env);
  its native ``step_async``/``step_wait`` is used directly.
* ``shared_memory`` — :class:`~sheeprl_tpu.envs.executor.SharedMemoryVectorEnv`,
  persistent slab workers with in-place shared obs/action buffers
  (EnvPool-style: no per-step pickling, one batched copy out, one
  command/ack per worker — ``env.envs_per_worker`` sets the slab size).

All three keep ``SAME_STEP`` autoreset semantics bit-for-bit (golden-tested
in ``tests/test_envs/test_async_pipeline.py``), and ``step()`` still works
(``step_async`` + ``step_wait``) so non-pipelined call sites are unaffected.
"""

from __future__ import annotations

from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Optional

import gymnasium as gym

EXECUTORS = ("sync", "async", "shared_memory")


class PipelinedVectorEnv:
    """Uniform ``step_async``/``step_wait`` over Sync/Async/shared-memory
    vector envs; everything else (spaces, ``reset``, ``step``, ``num_envs``)
    delegates to the wrapped env."""

    def __init__(self, envs: gym.vector.VectorEnv):
        self.envs = envs
        self._native = callable(getattr(envs, "step_async", None)) and callable(
            getattr(envs, "step_wait", None)
        )
        self._pool: Optional[ThreadPoolExecutor] = (
            None if self._native else ThreadPoolExecutor(1, thread_name_prefix="env-step")
        )
        self._future: Optional[Future] = None
        self._pending = False

    # -- split-phase stepping ---------------------------------------------
    def step_async(self, actions: Any) -> None:
        """Start stepping the envs; returns immediately."""
        if self._pending:
            raise RuntimeError("step_async() called while a previous step is still in flight")
        if self._native:
            self.envs.step_async(actions)
        else:
            self._future = self._pool.submit(self.envs.step, actions)
        # only after a successful dispatch: a raising dispatch (bad actions
        # shape etc.) must leave the wrapper usable, not wedged in-flight
        self._pending = True

    def step_wait(self):
        """Block until the in-flight step finishes; returns the usual
        ``(obs, rewards, terminated, truncated, infos)`` 5-tuple."""
        if not self._pending:
            raise RuntimeError("step_wait() called with no step_async in flight")
        self._pending = False
        if self._native:
            return self.envs.step_wait()
        future, self._future = self._future, None
        return future.result()

    def step(self, actions: Any):
        """Serialized convenience path (identical results to async+wait)."""
        self.step_async(actions)
        return self.step_wait()

    # -- passthrough -------------------------------------------------------
    def reset(self, *, seed=None, options=None):
        if self._pending:
            raise RuntimeError("reset() called while a step_async is in flight")
        return self.envs.reset(seed=seed, options=options)

    def close(self, **kwargs) -> None:
        if self._pending:  # drain so the executor shuts down at a step boundary
            try:
                self.step_wait()
            except Exception:  # pragma: no cover - already tearing down
                pass
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        self.envs.close(**kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_") or name == "envs":  # avoid recursion pre-__init__
            raise AttributeError(name)
        return getattr(self.envs, name)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"PipelinedVectorEnv({self.envs!r}, native={self._native})"
