"""A2C losses (reference /root/reference/sheeprl/algos/a2c/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    reduction = reduction.lower()
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction == "none":
        return x
    raise ValueError(f"Unrecognized reduction: {reduction}")


def policy_loss(logprobs: jax.Array, advantages: jax.Array, reduction: str = "mean") -> jax.Array:
    """Vanilla policy-gradient loss (reference loss.py:5-32)."""
    return _reduce(-(logprobs * advantages), reduction)


def value_loss(values: jax.Array, returns: jax.Array, reduction: str = "mean") -> jax.Array:
    """MSE critic loss (reference loss.py:35-40)."""
    return _reduce((values - returns) ** 2, reduction)
