"""A2C agent (reference /root/reference/sheeprl/algos/a2c/agent.py).

The reference A2C agent is the PPO architecture restricted to vector
observations (MLP encoder only); the flax module is shared with PPO — the
restriction is enforced in ``build_agent``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import gymnasium

from sheeprl_tpu.algos.ppo.agent import PPOAgent as A2CAgent  # noqa: F401
from sheeprl_tpu.algos.ppo.agent import build_agent as _build_ppo_agent


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Dict[str, Any]] = None,
):
    if cfg.algo.cnn_keys.encoder:
        raise ValueError("A2C only supports vector observations (algo.cnn_keys.encoder must be [])")
    return _build_ppo_agent(runtime, actions_dim, is_continuous, cfg, obs_space, agent_state)
